"""Durable file-backed log transport: segment files + commit journal.

The single-node durability substrate standing in for the reference's Kafka broker
(SURVEY.md §2.9 item 3): same observable contract as :class:`InMemoryLog` — atomic
multi-topic transactions, epoch fencing, read_committed views — plus crash recovery.

Layout under the root directory::

    topics.json           topic specs (rewritten + fsynced on create)
    epochs.json           producer epochs (rewritten + fsynced on producer open)
    commits.log           the COMMIT JOURNAL: one JSON line per transaction listing
                          [topic, partition, base_offset, count, seg_end_pos] per
                          touched partition, fsynced after the data blocks
    compaction.json       the COMPACTION RECOVERY MANIFEST: per compacted
                          partition, the current segment file (generational
                          name), its post-swap frontier (end_offset/end_pos),
                          and the clean state (clean_end/clean_count) feeding
                          the dirty-ratio scheduler (surge_tpu.log.compactor)
    data/{topic}-{p}.seg  one segment file per topic-partition: a sequence of
                          compressed blocks (surge_tpu.log.segment), one per
                          transaction per partition. After a compaction the
                          current file is data/{topic}-{p}.g{N}.seg — blocks
                          are latest-record-per-key with sparse offsets, and
                          the manifest names which generation is live

**Compaction crash-safety.** ``compact_partition`` writes the rewritten segment to a
``.tmp`` beside the new generational name, fsyncs it, renames it into place (an atomic
publish of a complete file), and only then rewrites the manifest — the real commit
point, since recovery resolves each partition's file through the manifest. A crash at
any earlier step leaves the manifest pointing at the intact old segment and at most an
orphaned file that recovery sweeps. Journal lines written after the swap carry
positions in the new file (appends continue at its end), so recovery uses the journal
frontier when it is ahead of the manifest's and the manifest frontier otherwise.

**Crash atomicity.** A transaction is durable iff its journal line is. Small data
blocks (up to ``_EMBED_MAX_BYTES`` compressed) are EMBEDDED in their journal line
(base64), so the journal is a self-contained WAL for the command path: the segment
write stays in the page cache (no per-file fsync) and recovery backfills any
missing or garbled segment tail from the journaled payloads. Oversized blocks
(bulk loads) keep the old discipline — data fsynced *before* the journal line.
Segment bytes beyond the last journaled end position (a torn write from a crashed
commit) are truncated away; a journaled position whose segment bytes are absent or
corrupt is re-materialized from the embedded payload, and only clamped away when
no payload exists (a pre-WAL journal, or an oversized block lost under
``fsync="none"``). This mirrors the role Kafka's transaction markers play for
read_committed consumers (SurgeStateStoreConsumer.scala:38) with a single-node
journal instead of a two-phase broker protocol.

**Group commit.** Under ``fsync="commit"`` the journal fsync — the only fsync on
the small-transaction path — is a shared round: concurrent committers (the
per-partition publisher lanes, or a broker's handler threads) elect a leader that
fsyncs once for every journal line written so far; the rest wait for the round
covering their line. One ~ms fsync therefore acknowledges a whole group of
transactions (the Aurora-style WAL group commit the command path's latency
budget rests on), instead of each transaction paying fsyncs for every touched
segment file plus the journal while holding the log lock.

Producers reuse :class:`InMemoryTxnProducer` — the transactional/fencing protocol is
identical; only ``_append`` differs (journaled disk commit vs list append).
"""

from __future__ import annotations

import asyncio
import base64
import json
import os
import threading
import time
import zlib
from collections import OrderedDict
from concurrent.futures import Future as ConcurrentFuture
from typing import Dict, List, Optional, Sequence, Tuple

from surge_tpu.common import logger
from surge_tpu.log import native_gate
from surge_tpu.log import segment as seg
from surge_tpu.log.memory import InMemoryTxnProducer, LogBase
from surge_tpu.log.transport import LogRecord, TopicSpec


#: compressed blocks at most this large ride inside their journal line (the
#: WAL fast path: no per-segment-file fsync). Bigger blocks (bulk loads) fsync
#: their segment file before the journal line, exactly as before.
_EMBED_MAX_BYTES = 256 << 10

#: lazy-materialization bound: a partition's pending (journal-covered but
#: unwritten) segment tail flushes inline once it exceeds this — the
#: background flusher is non-blocking and may lose the log-lock race under
#: sustained load
_PENDING_FLUSH_BYTES = 8 << 20


def _fsync_dir(path: str) -> None:
    """Durably record directory entries (new/renamed files) — without this a crash
    can lose a whole file whose contents were fsynced."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:  # pragma: no cover — platform without directory fds
        return
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


class _Partition:
    """In-memory index of one partition's segment file."""

    def __init__(self, path: str) -> None:
        self.path = path
        self.blocks: List[Tuple[int, int, int]] = []  # (base_offset, file_pos, count)
        self.end_offset = 0
        self.end_pos = 0  # applied end of the segment file
        #: offsets < this survived a journal fsync round — the read_committed
        #: frontier readers see (applied-but-unsynced records stay invisible,
        #: like records of an open Kafka transaction); == end_offset under
        #: fsync="none" and immediately after recovery
        self.durable_offset = 0
        self.gen = 0  # compaction generation (bumped on every segment swap)
        self.file = None  # append handle, opened lazily
        # decoded-block LRU keyed by file_pos: a tailing indexer re-reads the last
        # block every poll and a rebuild walks blocks in order; both hit the cache
        # instead of re-decompressing (VERDICT r2 weak #6). Bounded by BYTES,
        # not block count — one commit's block holds that commit's whole batch,
        # so a count limit would let 8 bulk-load blocks pin hundreds of MB of
        # decoded records during a paged restore scan (VERDICT r4 missing #4).
        self._cache: "OrderedDict[int, List[LogRecord]]" = OrderedDict()
        self._cache_sizes: Dict[int, int] = {}
        self._cache_bytes = 0
        self._cache_limit_bytes = 32 << 20
        # lazy segment materialization (native hot path): embedded blocks are
        # staged here — a contiguous tail keyed by file position — instead of
        # being written on the commit path; the group-sync worker writes them
        # in the background and reads serve straight from this map. Durability
        # is untouched: the journal line embeds the same bytes, and recovery
        # re-materializes a lost tail from it (the WAL contract).
        self.pending: "OrderedDict[int, object]" = OrderedDict()
        self.pending_bytes = 0


class FileLog(LogBase):
    """Durable :class:`surge_tpu.log.transport.LogTransport` implementation.

    ``fsync`` policy: ``"commit"`` (default — fsync data + journal + directory
    entries on every commit; crash-durable) or ``"none"`` (OS buffering only; fast,
    for tests/benches).
    """

    #: rotate the commit journal once this many bytes are durably covered
    #: (surge.log.journal-rotate-bytes overrides via the ``journal_rotate_bytes``
    #: parameter); 0 disables rotation
    DEFAULT_JOURNAL_ROTATE_BYTES = 64 << 20

    def __init__(self, root: str, fsync: str = "commit",
                 auto_create_partitions: int = 1,
                 journal_rotate_bytes: Optional[int] = None,
                 faults=None, config=None) -> None:
        from surge_tpu.config import default_config

        cfg = config if config is not None else default_config()
        self.root = root
        self._fsync = fsync == "commit"
        self._auto_create_partitions = auto_create_partitions
        if journal_rotate_bytes is None:
            journal_rotate_bytes = cfg.get_int(
                "surge.log.journal-rotate-bytes",
                self.DEFAULT_JOURNAL_ROTATE_BYTES)
        self._rotate_bytes = journal_rotate_bytes
        #: the native append path (csrc/txn.cc via log/native_gate): one C++
        #: call formats each transaction's blocks + journal line off the GIL,
        #: journal lines are staged for ONE write+fsync per group-sync round,
        #: and embedded segment blocks materialize lazily in the background.
        #: None (pure-Python path, bit-identical bytes) when the library is
        #: unbuilt or surge.log.native.enabled=false.
        self._native = native_gate if native_gate.enabled(cfg) else None
        # debug escape hatches for bisecting the native mechanisms in
        # isolation (used by the perf diagnosis in BENCH_NOTES round 8);
        # production keeps both on
        self._native_lazy = os.environ.get("SURGE_NATIVE_LAZY", "1") == "1"
        self._native_staged = os.environ.get(
            "SURGE_NATIVE_STAGED", "1") == "1"
        #: armed fault plane (surge_tpu.log.transport.FaultInjector) or None;
        #: sites: journal.write (torn), fsync.journal / fsync.segment,
        #: crash.journal.post-write
        self.faults = faults
        #: broker observability hooks, wired by a hosting LogServer (both
        #: None-guarded on the hot path): ``broker_metrics`` is a
        #: surge_tpu.metrics.broker.BrokerMetrics quiver fed by the group-sync
        #: worker (fsync round duration/occupancy, WAL bytes, rotations);
        #: ``flight`` a surge_tpu.observability.FlightRecorder that gets the
        #: journal-rotation events
        self.broker_metrics = None
        self.flight = None
        self._lock = threading.RLock()
        self._topics: Dict[str, TopicSpec] = {}
        self._epochs: Dict[str, int] = {}
        self._parts: Dict[Tuple[str, int], _Partition] = {}
        self._clean: Dict[Tuple[str, int], Tuple[int, int]] = {}
        self._manifest: Dict[str, Dict[str, dict]] = {}  # topic -> str(p) -> entry
        self._append_events: Dict[Tuple[str, int], asyncio.Event] = {}
        os.makedirs(os.path.join(root, "data"), exist_ok=True)
        self._journal_path = os.path.join(root, "commits.log")
        # group-commit round state (one shared journal-fsync worker):
        # _gc_written = journal bytes successfully written+flushed (candidates
        # for the next round), _gc_durable = bytes covered by a completed
        # fsync, _gc_waiters = (target, concurrent.Future) pairs resolved as
        # rounds complete. ONE worker thread fsyncs for everyone — blocking
        # committers wait their future, pipelined committers await it — so a
        # whole wave of transactions across every partition lane costs one
        # fsync and one thread handoff. Lock order: the main log lock may
        # acquire _gc_cv's lock, never the reverse.
        self._gc_cv = threading.Condition()
        self._gc_written = 0
        self._gc_durable = 0
        self._gc_waiters: List[Tuple[int, "ConcurrentFuture"]] = []
        self._gc_thread: Optional[threading.Thread] = None
        self._gc_stop = False
        # staged WAL lines (native hot path, fsync="commit" only): committers
        # stage formatted lines; the group-sync worker hands the round's
        # concatenation to ONE native write+fsync. The buffer AND all journal
        # FILE writes are guarded by their own _wal_lock (lock order: log
        # lock -> _wal_lock, never the reverse) so the worker's per-round
        # drain never contends with appliers holding the log lock — on a
        # fast-fsync filesystem rounds spin quickly enough that a worker
        # queuing on the log lock convoys the whole command path.
        # _journal_end = logical journal end (staged bytes included) — the
        # physical file ends _wal_staged_bytes earlier until the next round.
        self._wal_lock = threading.Lock()
        self._wal_buf: List[bytes] = []
        self._wal_staged_bytes = 0
        self._recover()
        self._journal = open(self._journal_path, "ab")
        self._gc_written = self._gc_durable = self._journal.tell()
        self._journal_end = self._journal.tell()

    # -- recovery -------------------------------------------------------------------------

    def _recover(self) -> None:
        topics_path = os.path.join(self.root, "topics.json")
        if os.path.exists(topics_path):
            with open(topics_path) as f:
                for name, meta in json.load(f).items():
                    self._topics[name] = TopicSpec(name, meta["partitions"],
                                                   meta["compacted"])
                    for p in range(meta["partitions"]):
                        self._parts[(name, p)] = _Partition(self._seg_path(name, p))
        epochs_path = os.path.join(self.root, "epochs.json")
        if os.path.exists(epochs_path):
            with open(epochs_path) as f:
                self._epochs = {k: int(v) for k, v in json.load(f).items()}
        # compaction manifest: names each compacted partition's CURRENT segment
        # file (generational) and the frontier at swap time. Loaded before the
        # journal scan so frontier resolution and block rebuild run against the
        # live file, and orphans of interrupted swaps can be swept.
        manifest_path = os.path.join(self.root, "compaction.json")
        if os.path.exists(manifest_path):
            with open(manifest_path) as f:
                self._manifest = json.load(f)
        for topic, parts in self._manifest.items():
            for p_str, entry in parts.items():
                key = (topic, int(p_str))
                part = self._parts.get(key)
                if part is None:
                    continue  # manifest for a topic dropped from topics.json
                part.path = os.path.join(self.root, entry["file"])
                part.gen = int(entry.get("gen", 0))
                self._clean[key] = (int(entry.get("clean_end", 0)),
                                    int(entry.get("clean_count", 0)))
        self._sweep_orphans()

        # journal scan: the durable frontier of every partition. A torn tail line
        # (crash mid-journal-write) is truncated away so the reopened append handle
        # never concatenates the next entry onto garbage. WAL-mode lines carry
        # their data blocks inline ("blk", base64 per touched partition) — those
        # payloads are collected by segment-file start position so the
        # per-partition pass below can re-materialize segment bytes the page
        # cache lost (the data files are no longer fsynced per commit).
        durable: Dict[Tuple[str, int], Tuple[int, int]] = {}  # -> (end_offset, end_pos)
        payloads: Dict[Tuple[str, int], Dict[int, str]] = {}  # -> {start_pos: b64}
        if os.path.exists(self._journal_path):
            good_end = 0
            with open(self._journal_path, "rb") as f:
                for line in f:
                    try:
                        entry = json.loads(line)
                    except ValueError:
                        break  # torn tail
                    if not line.endswith(b"\n"):
                        break  # complete JSON but no newline: still a torn write
                    good_end += len(line)
                    blks = entry.get("blk") or [None] * len(entry["parts"])
                    for (topic, p, base, count, end_pos), b64 in zip(
                            entry["parts"], blks):
                        durable[(topic, p)] = (base + count, end_pos)
                        if b64:
                            declen = (len(b64) * 3) // 4 - (
                                2 if b64.endswith("==") else
                                1 if b64.endswith("=") else 0)
                            payloads.setdefault((topic, p), {})[
                                end_pos - declen] = b64
            if os.path.getsize(self._journal_path) > good_end:
                with open(self._journal_path, "r+b") as f:
                    f.truncate(good_end)
        # truncate torn data tails; rebuild block indexes up to the durable frontier.
        # With fsync="none" a crash can also leave the journal AHEAD of the data file
        # (journal line flushed, data blocks lost in the page cache) — treat any
        # missing/corrupt tail as torn and clamp the frontier to the last intact
        # block instead of failing the open. Later appends journal the clamped
        # positions and recovery takes each partition's LAST journal line, so the
        # stale higher frontier is superseded.
        for key, part in self._parts.items():
            end_offset, end_pos = durable.get(key, (0, 0))
            entry = self._manifest.get(key[0], {}).get(str(key[1]))
            min_backfill_pos = 0
            if entry is not None:
                if int(entry["end_offset"]) >= end_offset:
                    # no post-swap appends journaled: the journal's positions
                    # refer to the pre-compaction file — the manifest frontier
                    # (recorded at swap time against the live generational
                    # file) supersedes
                    end_offset = int(entry["end_offset"])
                    end_pos = int(entry["end_pos"])
                # journaled payloads BELOW the swap frontier describe the
                # pre-compaction file; splicing them into the generational
                # file would corrupt it
                min_backfill_pos = int(entry["end_pos"])
            size = os.path.getsize(part.path) if os.path.exists(part.path) else 0
            if size > end_pos:  # torn tail from a crashed commit
                with open(part.path, "r+b") as f:
                    f.truncate(end_pos)
                size = end_pos
            data = b""
            if size:
                with open(part.path, "rb") as f:
                    data = f.read(min(end_pos, size))
            pos = 0
            good_offset = 0
            repaired = False
            part.blocks = []
            embedded = payloads.get(key, {})
            backfilled: set = set()  # positions already spliced (loop guard)
            while pos < end_pos:
                try:
                    codec, base, count, unlen, plen, crc, start = seg.read_block_header(
                        data, pos)
                    # unordered writeback can persist a block's header page but
                    # garble its payload — verify the CRC now so the clamp/
                    # backfill catches it here rather than a reader crashing
                    if zlib.crc32(data[start:start + plen]) & 0xFFFFFFFF != crc:
                        raise seg.BlockCorruptError("payload crc mismatch")
                except (seg.BlockCorruptError, IndexError):
                    # absent or garbled segment bytes at a journaled position:
                    # re-materialize the block from its journal payload (the
                    # WAL commit mode embeds it); the splice preserves every
                    # later block's position because the payload's length IS
                    # the block's on-disk length
                    b64 = (embedded.get(pos)
                           if pos >= min_backfill_pos and pos not in backfilled
                           else None)
                    if b64 is None:
                        break  # pre-WAL journal or oversized block: clamp
                    backfilled.add(pos)
                    block = base64.b64decode(b64)
                    data = data[:pos] + block + data[pos + len(block):]
                    repaired = True
                    continue
                part.blocks.append((base, pos, count))
                good_offset = base + count
                pos = start + plen
            if repaired:
                with open(part.path, "wb") as f:
                    f.write(data[:pos])
                    f.flush()
                    if self._fsync:
                        os.fsync(f.fileno())
                logger.info("backfilled %s[%d] to pos %d from journal payloads",
                            key[0], key[1], pos)
                size = pos
            if pos < end_pos:  # journal ran ahead of the data: clamp to intact prefix
                part.end_offset, part.end_pos = good_offset, pos
                if size > pos:
                    with open(part.path, "r+b") as f:
                        f.truncate(pos)
            else:
                part.end_offset, part.end_pos = end_offset, end_pos
            # everything recovered came from a durable journal: the
            # read_committed frontier restarts at the applied end
            part.durable_offset = part.end_offset

    def _seg_path(self, topic: str, partition: int) -> str:
        return os.path.join(self.root, "data", f"{topic}-{partition}.seg")

    def _gen_path(self, topic: str, partition: int, gen: int) -> str:
        return os.path.join(self.root, "data", f"{topic}-{partition}.g{gen}.seg")

    def _sweep_orphans(self) -> None:
        """Delete stale segment generations and interrupted-swap leftovers: any
        ``{topic}-{p}[.gN].seg[.tmp]`` that is not some partition's current
        file. A crash between the tmp-write/rename and the manifest update
        leaves exactly these; the manifest still names the intact old file."""
        live = {os.path.basename(p.path) for p in self._parts.values()}
        stems = set()  # every name a known partition could own, any generation
        for topic, p in self._parts:
            stems.add((f"{topic}-{p}.seg", ""))
            stems.add((f"{topic}-{p}.g", ".seg"))
        data_dir = os.path.join(self.root, "data")
        try:
            names = os.listdir(data_dir)
        except OSError:
            return
        for name in names:
            if name in live:
                continue
            stem = name[:-4] if name.endswith(".tmp") else name
            owned = any(
                stem == prefix if not suffix else (
                    stem.startswith(prefix) and stem.endswith(suffix)
                    and stem[len(prefix):-len(suffix)].isdigit())
                for prefix, suffix in stems)
            if not owned:
                continue
            try:
                os.unlink(os.path.join(data_dir, name))
                logger.info("swept orphan segment %s", name)
            except OSError:
                pass

    def _persist_json(self, name: str, obj) -> None:
        path = os.path.join(self.root, name)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(obj, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        _fsync_dir(self.root)

    # -- topics ---------------------------------------------------------------------------

    def create_topic(self, spec: TopicSpec) -> None:
        with self._lock:
            if spec.name in self._topics:
                return
            self._topics[spec.name] = spec
            for p in range(spec.partitions):
                self._parts.setdefault((spec.name, p),
                                       _Partition(self._seg_path(spec.name, p)))
            self._persist_json("topics.json", {
                t.name: {"partitions": t.partitions, "compacted": t.compacted}
                for t in self._topics.values()})

    # -- producers (protocol shared with the in-memory log) -------------------------------

    def transactional_producer(self, transactional_id: str) -> "FileTxnProducer":
        with self._lock:
            epoch = self._next_epoch(transactional_id)
            self._persist_json("epochs.json", self._epochs)
            return FileTxnProducer(self, transactional_id, epoch)

    def _append(self, records: Sequence[LogRecord]) -> List[LogRecord]:
        """One transaction: per-partition blocks + one journal line. Atomic under
        the commit journal (see module docstring)."""
        with self._lock:
            out, my_target, touched, marks = self._append_locked(records)
        return self._append_finish(out, my_target, touched, marks)

    def _append_fenced(self, transactional_id: str, epoch: int,
                       records: Sequence[LogRecord]) -> List[LogRecord]:
        # epoch check + append atomic under the lock; the group-commit fsync
        # round runs OUTSIDE it (LogBase._append_fenced docstring) so readers
        # and other committers never queue behind the disk
        with self._lock:
            self._check_epoch(transactional_id, epoch)
            out, my_target, touched, marks = self._append_locked(records)
        return self._append_finish(out, my_target, touched, marks)

    def append_verbatim(self, records: Sequence[LogRecord],
                        allow_gaps: bool = False) -> List[LogRecord]:
        """Append leader-assigned records AS-IS — offsets and timestamps
        preserved so a replica's segment files converge byte-identically with
        its leader's (the follower half of ship-on-commit replication;
        ``allow_gaps`` for catch_up over a compacted leader partition)."""
        with self._lock:
            out, my_target, touched, marks = self._append_locked(
                records, verbatim=True, allow_gaps=allow_gaps)
        return self._append_finish(out, my_target, touched, marks)

    def applied_end_offset(self, topic: str, partition: int) -> int:
        """The applied frontier (ahead of the durable ``end_offset`` while a
        group-sync round is open) — replica gap checks measure against this."""
        with self._lock:
            self.topic(topic)
            return self._parts[(topic, partition)].end_offset

    def _append_finish(self, out: List[LogRecord], my_target: int,
                       touched, marks) -> List[LogRecord]:
        if touched:
            # durability outside the log lock: join the group-commit round
            # covering this transaction's journal line (one shared fsync acks
            # the whole group) while other committers write theirs
            if self._fsync:
                self._commit_sync(my_target)
            elif self._rotate_bytes and my_target > self._rotate_bytes:
                # no group-sync worker runs under fsync="none", so rotation
                # must trigger from the append path or commits.log (which
                # embeds WAL payloads) grows without bound
                try:
                    self._maybe_rotate_journal()
                except Exception:  # noqa: BLE001 — rotation is opportunistic
                    logger.exception("journal rotation failed; will retry")
            self._mark_durable(marks)
            self._notify_append(touched)
            # eager digest maintenance (outside the log lock; the broker's
            # native Transact path never reaches here — its records are
            # chained lazily by partition_digest's catch-up read)
            self._digest_observe(out)
        return out

    def _mark_durable(self, marks) -> None:
        """Advance the read_committed frontier of every partition a (now
        durable) transaction touched — readers see the records only from
        here on, so a crash that loses an unsynced journal line can never
        un-happen something a consumer already observed."""
        with self._lock:
            for part, end in marks:
                if end > part.durable_offset:
                    part.durable_offset = end

    def _append_locked(self, records: Sequence[LogRecord],
                       verbatim: bool = False, allow_gaps: bool = False):
        """Phase 1 of one transaction (caller holds the log lock) — routes
        to the native batch path when built+enabled, on the assign path AND
        the verbatim replica-ingest path (the follower applies shipped
        batches off the GIL; PR-10 headroom note closed)."""
        if records and self._native is not None:
            if not verbatim:
                return self._append_locked_native(records)
            return self._append_locked_verbatim_native(records, allow_gaps)
        return self._append_locked_py(records, verbatim, allow_gaps)

    def _append_locked_native(self, records: Sequence[LogRecord]):
        """Native phase 1: ONE C++ call (csrc/txn.cc) frames every record,
        compresses+CRCs the per-partition blocks and formats the journal
        line; Python assigns bases and stages bookkeeping. Byte-identical to
        :meth:`_append_locked_py` (property-tested)."""
        batch = self._native.pack_records(records)
        if batch is None:  # pragma: no cover — library unloadable mid-run
            return self._append_locked_py(records, False, False)
        try:
            my_target, touched, marks, offsets, now = \
                self._append_batch_locked(batch)
        finally:
            batch.close()
        out = [LogRecord(topic=r.topic, key=r.key, value=r.value,
                         partition=r.partition, headers=dict(r.headers),
                         offset=off, timestamp=now)
               for r, off in zip(records, offsets)]
        return out, my_target, touched, marks

    def _append_locked_verbatim_native(self, records: Sequence[LogRecord],
                                       allow_gaps: bool):
        """Native verbatim phase 1 (replica ingest): ONE C++ call re-groups
        the leader-assigned records into contiguous-offset runs, frames each
        run's block with its ORIGINAL timestamps and formats the journal
        line — replica segment/journal bytes converge byte-identically with
        a leader that wrote the same records (property-tested). Gap checks
        mirror :meth:`_append_locked_py`'s verbatim semantics exactly."""
        batch = self._native.pack_verbatim(records)
        if batch is None:  # pragma: no cover — library unloadable mid-run
            return self._append_locked_py(records, True, allow_gaps)
        try:
            expected: dict = {}
            bases = batch.group_bases()
            for g, (topic, p, count) in enumerate(batch.groups):
                self.topic(topic)
                key = (topic, p)
                part = self._parts.get(key)
                if part is None:
                    raise KeyError(f"{topic}[{p}] does not exist")
                exp = expected.get(key)
                if exp is None:
                    exp = part.end_offset
                base = bases[g]
                if base < exp or (base > exp and not allow_gaps):
                    raise ValueError(
                        f"verbatim append at {topic}[{p}]@{base} but "
                        f"applied end is {exp}")
                expected[key] = base + count
            my_target, touched, marks, _offsets, _now = \
                self._append_batch_locked(batch, verbatim=True,
                                          verbatim_bases=bases)
        finally:
            batch.close()
        return list(records), my_target, touched, marks

    def _append_batch_locked(self, batch, verbatim: bool = False,
                             verbatim_bases=None):
        """Apply one pre-decoded :class:`~surge_tpu.log.native_gate.
        NativeBatch` (caller holds the log lock): format via the native call,
        stage embedded blocks in the lazy pending tail (the group-sync worker
        materializes segment files off the commit path), stage the journal
        line for the round's single native write+fsync. Returns
        ``(journal_target, touched, marks, offsets, timestamp)`` — no
        LogRecord materialization, for callers (the broker's native Transact
        path) that build replies from their own message objects."""
        groups = batch.groups
        now = time.time()
        if not groups:
            # empty transaction: the Python twin writes NOTHING (early
            # return) — staging a '{"parts": [], "blk": []}' line would
            # break bit-identity and leave _gc_written ahead of durable
            # with no waiter to drive a round
            return 0, set(), [], [], now
        parts_objs: List[_Partition] = []
        bases: List[int] = []
        pos0: List[int] = []
        for topic, p, _count in groups:
            self.topic(topic)
            part = self._parts.get((topic, p))
            if part is None:
                raise KeyError(f"{topic}[{p}] does not exist")
            parts_objs.append(part)
            if not verbatim:
                bases.append(part.end_offset)
            pos0.append(part.end_pos)
        if verbatim:
            # leader-assigned bases (the caller's gap check already pulled
            # them — one ctypes call per group, not two) and per-record
            # timestamps; same-partition runs chain their file positions
            # natively (the Python path's `pos = new_pos` walk)
            bases = (verbatim_bases if verbatim_bases is not None
                     else batch.group_bases())
            line, blocks, gouts, offsets = batch.format_verbatim(
                pos0, _EMBED_MAX_BYTES)
        else:
            line, blocks, gouts, offsets = batch.format(bases, pos0, now,
                                                        _EMBED_MAX_BYTES)
        # lazy segment materialization needs the group-sync worker (it only
        # runs under fsync="commit") to drain the pending tails
        lazy = self._fsync and self._native_lazy
        staged_ok = self._native_staged
        mv = memoryview(blocks)
        journal_pos = None
        staged_line = None  # set once the WAL line is staged (rollback key)
        staged: List[Tuple[_Partition, int, int, int, int]] = []
        try:
            for g, part in enumerate(parts_objs):
                boff, blen, embedded, new_pos = gouts[g]
                # the block's file position: chained for same-partition
                # verbatim runs (assign-path groups are unique per
                # partition, where this equals pos0[g])
                block_pos = new_pos - blen
                block_mv = mv[boff:boff + blen]
                if embedded and lazy:
                    if part.pending_bytes > _PENDING_FLUSH_BYTES:
                        # safety valve: the worker's non-blocking flush has
                        # been losing the lock race — bound the tail inline
                        self._flush_pending_locked(part)
                    # a bytes COPY of just this block: a memoryview slice
                    # would pin the whole batch's blocks buffer (incl. any
                    # multi-MB oversized group) while pending_bytes accounts
                    # only the slice — the flush valve would undercount
                    part.pending[block_pos] = bytes(block_mv)
                    part.pending_bytes += blen
                else:
                    self._flush_pending_locked(part)
                    if part.file is None:
                        existed = os.path.exists(part.path)
                        part.file = open(part.path, "ab")
                        if self._fsync and not existed:
                            _fsync_dir(os.path.dirname(part.path))
                    part.file.write(block_mv)
                    part.file.flush()
                    if not embedded and self._fsync:
                        # oversized block: its payload does NOT ride the
                        # journal line, so the segment bytes must be durable
                        # before the commit point — exactly the Python path
                        if self.faults is not None:
                            self.faults.on_fsync("segment")
                        os.fsync(part.file.fileno())
                staged.append((part, bases[g], block_pos, new_pos,
                               groups[g][2]))
            if self._fsync and self.faults is None and staged_ok:
                # stage the commit point: the group-sync worker writes every
                # staged line with ONE native append per fsync round
                with self._wal_lock:
                    self._wal_buf.append(line)
                    staged_line = line
                    self._wal_staged_bytes += len(line)
                    self._journal_end += len(line)
                    my_target = self._journal_end
            else:
                # direct write (fsync="none", or a fault plane armed on the
                # journal sites): identical semantics to the Python path
                with self._wal_lock:
                    self._journal_drain_locked()
                    journal_pos = self._journal.tell()
                    if self.faults is not None:
                        torn = self.faults.torn("journal.write", line)
                        if torn is not None:
                            self._journal.write(torn)
                            self._journal.flush()
                            from surge_tpu.testing.faults import \
                                SimulatedCrash

                            raise SimulatedCrash("journal.write torn")
                    self._journal.write(line)
                    self._journal.flush()
                    if self.faults is not None:
                        self.faults.crash_point("journal.post-write")
                    my_target = self._journal.tell()
                    self._journal_end = my_target
            with self._gc_cv:
                if my_target > self._gc_written:
                    self._gc_written = my_target
        except BaseException as _append_exc:
            if type(_append_exc).__name__ == "SimulatedCrash":
                raise  # leave the torn bytes for recovery (see Python path)
            if staged_line is not None:
                # an async exception (KeyboardInterrupt/MemoryError) landed
                # AFTER the line was staged: unstage it, or the worker would
                # write+fsync a WAL entry for a rolled-back transaction whose
                # bases the NEXT transaction reuses (phantom records after a
                # restart). We hold the log lock, so no later line can have
                # stacked on top AND rotation (which needs the log lock)
                # cannot have swapped the journal; but the group-sync
                # worker's drain (wal lock only) may already have WRITTEN
                # the line — then it is truncated back off the file.
                with self._wal_lock:
                    if self._wal_buf and self._wal_buf[-1] is staged_line:
                        self._wal_buf.pop()
                        self._wal_staged_bytes -= len(staged_line)
                        self._journal_end -= len(staged_line)
                    else:
                        try:
                            end = self._journal_end - len(staged_line)
                            self._journal.flush()
                            os.ftruncate(self._journal.fileno(), end)
                            self._journal.seek(0, os.SEEK_END)
                            self._journal_end = end
                        except OSError:
                            logger.exception(
                                "rolled-back txn's drained WAL line could "
                                "not be truncated; recovery may resurrect "
                                "it (phantom records)")
                    with self._gc_cv:
                        if self._gc_written > self._journal_end:
                            self._gc_written = self._journal_end
                        if self._gc_durable > self._journal_end:
                            self._gc_durable = self._journal_end
            for part in parts_objs:
                # drop this transaction's pending entries (at/past the
                # un-advanced end_pos) and truncate any torn direct write —
                # the physical file ends pending_bytes before end_pos
                for pos in [p_ for p_ in part.pending if p_ >= part.end_pos]:
                    part.pending_bytes -= len(part.pending.pop(pos))
                if part.file is not None:
                    part.file.truncate(part.end_pos - part.pending_bytes)
                    part.file.seek(0, os.SEEK_END)
            if journal_pos is not None:
                try:
                    with self._wal_lock:
                        self._journal.truncate(journal_pos)
                        self._journal.seek(0, os.SEEK_END)
                        self._journal_end = journal_pos
                except OSError:
                    logger.exception(
                        "journal rollback failed; commits.log may hold a "
                        "torn line until restart")
            raise
        touched = {(t, p) for t, p, _c in groups}
        for part, base, old_pos, new_pos, count in staged:
            part.blocks.append((base, old_pos, count))
            part.end_pos = new_pos
            part.end_offset = base + count
        marks = [(part, base + count)
                 for part, base, _op, _np, count in staged]
        return my_target, touched, marks, offsets, now

    def _flush_pending_locked(self, part: "_Partition") -> None:
        """Write a partition's lazy pending tail to its segment file (caller
        holds the log lock). Every path that touches the file directly —
        oversized blocks, verbatim appends, compaction snapshots, truncation,
        rotation, close — flushes first, so the physical file is always a
        prefix of the logical one."""
        if not part.pending:
            return
        if part.file is None:
            existed = os.path.exists(part.path)
            part.file = open(part.path, "ab")
            if self._fsync and not existed:
                _fsync_dir(os.path.dirname(part.path))
        # the physical file ends exactly where the pending tail begins (the
        # lazy-materialization invariant); a PARTIAL flush must roll back to
        # it, or the retry would append already-written block bytes a second
        # time and shift every later position — live-log corruption with no
        # crash involved
        start = next(iter(part.pending))
        try:
            for block in part.pending.values():
                part.file.write(block)
            part.file.flush()
        except BaseException:
            try:
                part.file.truncate(start)
                part.file.seek(0, os.SEEK_END)
            except OSError:
                logger.exception(
                    "pending-flush rollback failed for %s; reads may fail "
                    "until restart (journal backfill repairs the file)",
                    part.path)
            raise
        part.pending.clear()
        part.pending_bytes = 0

    def _flush_all_pending(self) -> None:
        """Background half of lazy materialization: the group-sync worker
        calls this once per fsync round to move every pending tail to disk
        OFF the commit path. NON-BLOCKING on the log lock — when committers
        are busy the flush just waits for a later round (or the inline
        safety valve in _append_batch_locked); a worker queuing on the hot
        log lock would convoy the very commit path this exists to unblock."""
        if not self._lock.acquire(blocking=False):
            return
        try:
            for part in self._parts.values():
                if part.pending:
                    try:
                        self._flush_pending_locked(part)
                    except OSError:
                        logger.exception("pending segment flush failed; "
                                         "will retry next round")
        finally:
            self._lock.release()

    def _journal_drain_locked(self) -> None:
        """Write any staged journal lines through the buffered handle (caller
        holds ``_wal_lock``) — the ordering barrier every direct journal
        writer (legacy append, truncation frontier lines, rotation) takes
        before bypassing the staging buffer. The buffer clears only AFTER a
        successful write (mirroring _journal_round_drain): clearing first
        would let a failed write silently lose committed lines that the next
        fsync round then acknowledges as durable."""
        if not self._wal_buf:
            return
        buf = b"".join(self._wal_buf)
        start = self._journal_end - self._wal_staged_bytes
        try:
            self._journal.write(buf)
            self._journal.flush()
        except BaseException:
            try:  # remove any partial bytes; the staged lines stay queued
                self._journal.truncate(start)
                self._journal.seek(0, os.SEEK_END)
            except OSError:
                logger.exception("journal partial-write rollback failed")
            raise
        self._wal_buf.clear()
        self._wal_staged_bytes = 0

    def _journal_round_drain(self) -> None:
        """The group-sync worker's write half: hand the round's staged lines
        to ONE native append (no fsync — that is the round's next step). Only
        ``_wal_lock`` is taken — never the log lock, which committers hold
        for whole appends (a worker queuing there convoys the command path).
        On a write failure the lines stay staged for the next round and any
        partial bytes are truncated away, so the journal never holds a torn
        line followed by good ones."""
        with self._wal_lock:
            if not self._wal_buf:
                return
            buf = b"".join(self._wal_buf)
            start = self._journal_end - self._wal_staged_bytes
            self._journal.flush()  # empty in staged mode; ordering safety
            try:
                if self._native is not None:
                    self._native.wal_append(self._journal.fileno(), buf,
                                            False)
                else:  # pragma: no cover — staging implies native, belt+braces
                    os.write(self._journal.fileno(), buf)
            except BaseException:
                try:
                    os.ftruncate(self._journal.fileno(), start)
                except OSError:
                    logger.exception("journal partial-write rollback failed")
                raise
            self._wal_buf.clear()
            self._wal_staged_bytes = 0

    def _append_locked_py(self, records: Sequence[LogRecord],
                          verbatim: bool = False,
                          allow_gaps: bool = False):
        """Pure-Python phase 1 (the pre-native path, byte-identical output):
        assign offsets, write blocks + the journal line (page cache), stage
        indexes. Returns (records_with_offsets, journal_target,
        touched_partitions, marks).

        ``verbatim`` (replica ingest) keeps the caller's offsets AND
        timestamps — a replica converges byte-identically with its leader —
        splitting each partition's records into contiguous-offset runs (one
        block per run; a block's decode assigns ``base+i``, so it must never
        span an offset hole)."""
        if not records:
            return [], 0, set(), []
        with self._wal_lock:  # journal-line order vs the staged WAL
            self._journal_drain_locked()
        out: List[LogRecord] = []
        now = time.time()
        grouped: Dict[Tuple[str, int], List[LogRecord]] = {}
        for r in records:
            self.topic(r.topic)
            key = (r.topic, r.partition)
            if key not in self._parts:
                raise KeyError(f"{r.topic}[{r.partition}] does not exist")
            if verbatim:
                prev = grouped.get(key)
                expect = (prev[-1].offset + 1 if prev
                          else self._parts[key].end_offset)
                if r.offset < expect or (r.offset > expect and not allow_gaps):
                    raise ValueError(
                        f"verbatim append at {r.topic}[{r.partition}]@"
                        f"{r.offset} but applied end is {expect}")
                assigned = r
            else:
                assigned = LogRecord(
                    topic=r.topic, key=r.key, value=r.value,
                    partition=r.partition, headers=dict(r.headers),
                    offset=self._parts[key].end_offset
                    + len(grouped.get(key, [])),
                    timestamp=now)
            grouped.setdefault(key, []).append(assigned)
            out.append(assigned)

        entry_parts = []
        entry_blocks = []  # base64 payloads (None for oversized blocks)
        # (partition, base_offset, old_pos, new_pos, count)
        staged: List[Tuple[_Partition, int, int, int, int]] = []
        journal_pos = self._journal.tell()
        try:
            for (topic, p), recs in grouped.items():
                part = self._parts[(topic, p)]
                self._flush_pending_locked(part)  # direct writes need the
                # physical file caught up with the lazy tail
                # contiguous-offset runs (one block each); the assign path is
                # always a single run
                runs: List[List[LogRecord]] = [[recs[0]]]
                for r in recs[1:]:
                    if r.offset == runs[-1][-1].offset + 1:
                        runs[-1].append(r)
                    else:
                        runs.append([r])
                if part.file is None:
                    existed = os.path.exists(part.path)
                    part.file = open(part.path, "ab")
                    if self._fsync and not existed:
                        _fsync_dir(os.path.dirname(part.path))
                pos = part.end_pos
                for run in runs:
                    base = run[0].offset
                    block = seg.encode_block(run, base)
                    part.file.write(block)
                    part.file.flush()
                    if len(block) <= _EMBED_MAX_BYTES:
                        # WAL fast path: the journal line carries the block,
                        # so the segment write may stay in the page cache —
                        # recovery re-materializes it from the payload
                        entry_blocks.append(
                            base64.b64encode(block).decode("ascii"))
                    else:
                        entry_blocks.append(None)
                        if self._fsync:
                            if self.faults is not None:
                                self.faults.on_fsync("segment")
                            os.fsync(part.file.fileno())
                    new_pos = pos + len(block)
                    entry_parts.append([topic, p, base, len(run), new_pos])
                    staged.append((part, base, pos, new_pos, len(run)))
                    pos = new_pos

            # the commit point: journal line durable => transaction durable
            line = (json.dumps(
                {"parts": entry_parts, "blk": entry_blocks}) + "\n").encode()
            if self.faults is not None:
                torn = self.faults.torn("journal.write", line)
                if torn is not None:
                    # crash mid-journal-write: the torn prefix reaches the OS
                    # (as a real power cut would leave it) and the process
                    # "dies" here — recovery must discard the torn tail
                    self._journal.write(torn)
                    self._journal.flush()
                    from surge_tpu.testing.faults import SimulatedCrash

                    raise SimulatedCrash("journal.write torn")
            self._journal.write(line)
            self._journal.flush()
            if self.faults is not None:
                # crash AFTER the durable-intent write: recovery must KEEP it
                self.faults.crash_point("journal.post-write")
            my_target = self._journal.tell()
            self._journal_end = my_target
            with self._gc_cv:
                if my_target > self._gc_written:
                    self._gc_written = my_target
        except BaseException as _append_exc:
            if type(_append_exc).__name__ == "SimulatedCrash":
                # a simulated crash leaves the torn bytes in place — the
                # physical rollback below would undo the very state recovery
                # is being tested against
                raise
            # physical rollback: a failed commit must leave no orphan block below
            # a later transaction's journaled frontier (recovery would resurrect
            # it as committed data with overlapping offsets). Truncate every
            # partition the transaction touched — including the one whose own
            # write/flush raised, which was never staged but may hold torn bytes
            # past its durable end_pos. A partition the loop never reached may
            # still carry a lazy pending tail: its physical file ends
            # pending_bytes short of end_pos.
            for key in grouped:
                part = self._parts[key]
                if part.file is not None:
                    part.file.truncate(part.end_pos - part.pending_bytes)
                    part.file.seek(0, os.SEEK_END)
            # a journal flush that failed after a partial OS write leaves a torn
            # half-line that would make recovery discard every LATER committed
            # transaction — roll the journal back to its pre-transaction length
            try:
                self._journal.truncate(journal_pos)
                self._journal.seek(0, os.SEEK_END)
                self._journal_end = journal_pos
            except OSError:
                logger.exception("journal rollback failed; commits.log may hold "
                                 "a torn line until restart")
            raise

        touched = set(grouped)
        for part, base, old_pos, new_pos, count in staged:
            part.blocks.append((base, old_pos, count))
            part.end_pos = new_pos
            part.end_offset = base + count
        return (out, my_target, touched,
                [(part, base + count) for part, base, _op, _np, count
                 in staged])

    def _commit_sync(self, my_target: int) -> None:
        """Block until journal bytes ``< my_target`` are fsynced (one shared
        round per group of committers). A round's fsync failure raises into
        every commit it covered (the publisher retry ladder owns recovery)."""
        self._enqueue_sync(my_target).result()

    def _enqueue_sync(self, my_target: int) -> "ConcurrentFuture":
        """Register a durability waiter with the group-sync worker; the
        returned future resolves (None) once a completed fsync covers
        ``my_target``, or carries the round's exception."""
        fut: "ConcurrentFuture" = ConcurrentFuture()
        with self._gc_cv:
            if self._gc_durable >= my_target:
                fut.set_result(None)
                return fut
            if my_target > self._gc_written:
                # the counters are monotonic except for journal rotation's
                # reset — and rotation's quiesce bar (written == durable, no
                # waiters) proves every byte of the OLD journal, this target
                # included, was fsynced before the reset. A committer that
                # appended, released the log lock, and registered its waiter
                # only after a rotation squeezed in would otherwise wait on
                # a target the counters can never reach again.
                fut.set_result(None)
                return fut
            if self._gc_stop:
                fut.set_exception(RuntimeError("log closed"))
                return fut
            self._gc_waiters.append((my_target, fut))
            if self._gc_thread is None:
                self._gc_thread = threading.Thread(
                    target=self._gc_loop, name="surge-log-groupsync",
                    daemon=True)
                self._gc_thread.start()
            self._gc_cv.notify_all()
        return fut

    def _gc_loop(self) -> None:
        """The group-sync worker: one fsync per round covers every journal
        line written before it, resolving all covered waiters at once.

        Waiter futures are ALWAYS resolved OUTSIDE _gc_cv: a done-callback
        chained on one (the pipelined commit's visibility publish) takes the
        main log lock, and a committer holding the main lock registers
        waiters under _gc_cv — resolving under _gc_cv would invert the
        documented lock order and deadlock."""
        while True:
            with self._gc_cv:
                while not self._gc_waiters and not self._gc_stop:
                    self._gc_cv.wait(0.5)
                if self._gc_stop:
                    waiters, self._gc_waiters = self._gc_waiters, []
                else:
                    waiters = None
                    target = self._gc_written
            if waiters is not None:
                for _t, fut in waiters:
                    if not fut.done():
                        fut.set_exception(RuntimeError("log closed"))
                return
            err: Optional[BaseException] = None
            round_t0 = time.perf_counter()
            try:
                self._journal_round_drain()
                # lazy segment materialization's background half: the
                # round's pending block tails go down HERE, before the
                # fsync — one coherent I/O burst per round. Flushing after
                # the round instead queues the burst on the (shared) slow
                # filesystem channel right in front of the NEXT round's
                # fsync, inflating it — measured 3-10x round-time collapse
                # on this 9p host.
                self._flush_all_pending()
                if self.faults is not None:
                    self.faults.on_fsync("journal")
                if self._native is not None and self.faults is None:
                    # the native half of the round: one GIL-free fsync call
                    # (the round's staged lines went down in ONE write above)
                    self._native.wal_append(self._journal.fileno(), b"", True)
                else:
                    os.fsync(self._journal.fileno())
            except BaseException as exc:  # noqa: BLE001 — fail this round's waiters
                err = exc
            ready: List[Tuple[int, "ConcurrentFuture"]] = []
            with self._gc_cv:
                if err is None:
                    if target > self._gc_durable:
                        self._gc_durable = target
                    keep = []
                    for t, fut in self._gc_waiters:
                        (ready if t <= self._gc_durable else keep).append(
                            (t, fut))
                    self._gc_waiters = keep
                else:
                    # durability unknown: fail everyone queued — a blocking
                    # commit raises, a pipelined handle retries via
                    # retry_pipelined (re-joining a later round; the records
                    # are already placed, nothing re-appends)
                    ready, self._gc_waiters = self._gc_waiters, []
            bm = self.broker_metrics
            if bm is not None and err is None:
                bm.journal_fsync_round_timer.record_ms(
                    (time.perf_counter() - round_t0) * 1000.0)
                bm.journal_round_occupancy.record(len(ready))
                bm.journal_wal_bytes.record(target)
            for _t, fut in ready:
                if not fut.done():
                    if err is None:
                        fut.set_result(None)
                    else:
                        fut.set_exception(err)
            if err is None and self._rotate_bytes:
                try:
                    self._maybe_rotate_journal()
                    # a never-idle leader defeats the opportunistic path
                    # forever (new lines land between every round and its
                    # quiesce check) — past the hard ceiling, rotate by FORCE:
                    # take the log lock as a barrier and make the quiesced
                    # invariant true instead of waiting for it
                    with self._gc_cv:
                        durable = self._gc_durable
                    if durable >= 2 * self._rotate_bytes:
                        self._force_rotate_journal()
                except Exception:  # noqa: BLE001 — rotation is opportunistic
                    logger.exception("journal rotation failed; will retry "
                                     "after the next sync round")

    # -- journal rotation -----------------------------------------------------------------

    def _force_rotate_journal(self) -> None:
        """Size-forced rotation BARRIER (run by the group-sync worker once
        the durable journal passes twice the rotate threshold): under
        sustained load the opportunistic quiesce check never passes — some
        committer has always written a line since the last round — so the WAL
        would grow without bound. The force path inverts the discipline: take
        the MAIN log lock first (no appender can start a new journal line),
        fsync everything already written, resolve the covered waiters, and
        rotate while the quiesced invariant is held BY THE LOCK rather than
        by luck. Commit latency pays one rotation inline — bounded by segment
        fsyncs + one rename — which is the explicit trade against an
        unbounded commits.log."""
        with self._lock:
            with self._wal_lock:
                self._journal_drain_locked()
            with self._gc_cv:
                if self._gc_stop:
                    return
                target = self._gc_written
            if target > self._gc_durable:
                if self.faults is not None:
                    self.faults.on_fsync("journal")
                os.fsync(self._journal.fileno())
            ready: List[Tuple[int, "ConcurrentFuture"]] = []
            with self._gc_cv:
                if target > self._gc_durable:
                    self._gc_durable = target
                keep = []
                for t, fut in self._gc_waiters:
                    (ready if t <= self._gc_durable else keep).append((t, fut))
                self._gc_waiters = keep
            for _t, fut in ready:
                # resolving under the (reentrant) log lock is safe: the only
                # callback chained on these futures re-takes this same lock
                if not fut.done():
                    fut.set_result(None)
            self._maybe_rotate_journal(forced=True)

    def _maybe_rotate_journal(self, forced: bool = False) -> None:
        """Rotate ``commits.log`` once its durable bytes exceed the rotation
        threshold: the journal embeds WAL payloads, so unrotated it grows
        without bound (ROADMAP follow-up). A rotation generation is safe to
        retire only when every segment byte it backs is durable on its own —
        so the segments are fsynced FIRST, then a fresh journal whose first
        line records every partition's frontier atomically replaces the old
        one (write tmp → fsync → rename → dir fsync). A crash before the
        rename recovers from the old journal; after it, from the frontier
        line. ``os.replace`` IS the old generation's GC."""
        if self._fsync:
            with self._gc_cv:
                if self._gc_durable < self._rotate_bytes:
                    return
        with self._lock:
            if not self._fsync and self._journal.tell() < self._rotate_bytes:
                return  # raced another committer's rotation
            with self._gc_cv:
                # quiesced check under both locks: no committer can be writing
                # (they hold the log lock) and nothing written is unsynced
                # (the durable counter only advances in fsync mode)
                if self._gc_stop or self._gc_waiters or (
                        self._fsync
                        and self._gc_written != self._gc_durable):
                    return
            with self._wal_lock:
                self._journal_drain_locked()  # quiesce implies empty
            # segments first: after rotation the old journal's embedded
            # payloads are gone, so the segment files must stand alone
            for part in self._parts.values():
                self._flush_pending_locked(part)  # lazy tails must hit disk
                if part.end_pos <= 0 or not os.path.exists(part.path):
                    continue
                if self._fsync:
                    fd = os.open(part.path, os.O_RDONLY)
                    try:
                        os.fsync(fd)
                    finally:
                        os.close(fd)
            entry_parts = [[t, p, part.end_offset, 0, part.end_pos]
                           for (t, p), part in self._parts.items()
                           if part.end_offset or part.end_pos]
            line = (json.dumps({"parts": entry_parts,
                                "blk": [None] * len(entry_parts),
                                "rotated": True}) + "\n").encode()
            tmp = self._journal_path + ".tmp"
            with open(tmp, "wb") as f:
                f.write(line)
                f.flush()
                if self._fsync:
                    os.fsync(f.fileno())
            old_size = self._journal.tell()
            self._journal.close()
            os.replace(tmp, self._journal_path)
            if self._fsync:
                _fsync_dir(self.root)
            self._journal = open(self._journal_path, "ab")
            self._journal_end = self._journal.tell()
            with self._gc_cv:
                self._gc_written = self._gc_durable = self._journal.tell()
            if self.broker_metrics is not None:
                self.broker_metrics.journal_rotations.record()
                self.broker_metrics.journal_wal_bytes.record(
                    self._journal.tell())
            if self.flight is not None:
                self.flight.record("journal.rotate", old_bytes=old_size,
                                   new_bytes=self._journal.tell(),
                                   forced=forced)
            logger.info("rotated commit journal (%d -> %d bytes%s)",
                        old_size, self._journal.tell(),
                        ", forced" if forced else "")

    # -- reads ----------------------------------------------------------------------------

    def _decode_block_at(self, part: _Partition, topic: str, p: int,
                         file_pos: int, path: Optional[str] = None,
                         gen: Optional[int] = None) -> List[LogRecord]:
        """Decode one block. ``path``/``gen`` carry a reader's consistent
        snapshot: block positions are only meaningful against the segment file
        they were snapshotted with, and a concurrent compaction swaps the
        file — the gen guard keeps stale decodes out of the fresh cache."""
        pend = None
        with self._lock:  # cache read-modify-write must not race concurrent evictions
            fresh = gen is None or part.gen == gen
            if fresh:
                hit = part._cache.get(file_pos)
                if hit is not None:
                    part._cache.move_to_end(file_pos)
                    return hit
                # lazy materialization: a block the background writer has not
                # flushed yet is served straight from its pending bytes
                pend = part.pending.get(file_pos)
                if pend is not None:
                    pend = bytes(pend)
            if path is None:
                path = part.path
        if pend is not None:
            data = pend
        else:
            with open(path, "rb") as f:  # decode outside the lock (idempotent)
                f.seek(file_pos)
                header = f.read(seg.HEADER_SIZE)
                plen = seg.header_payload_len(header)
                data = header + f.read(plen)
        # this log's own native flag pins the decoder: an explicit
        # surge.log.native.enabled=false config must reach reads too, not
        # just the append path (the ambient default_config may differ)
        recs, _ = seg.decode_block(data, 0, topic, p,
                                   native=self._native is not None)
        # approximate decoded footprint: payload bytes + per-record overhead
        size = sum(len(r.value or b"") + len(r.key or "") + 64 for r in recs)
        with self._lock:
            if (gen is None or part.gen == gen) and file_pos not in part._cache:
                part._cache[file_pos] = recs
                part._cache_sizes[file_pos] = size
                part._cache_bytes += size
            # keep at least the newest block (the tailing indexer's hot one)
            while part._cache_bytes > part._cache_limit_bytes and len(part._cache) > 1:
                evicted, _ = part._cache.popitem(last=False)
                part._cache_bytes -= part._cache_sizes.pop(evicted)
        return recs

    def read(self, topic: str, partition: int, from_offset: int = 0,
             max_records: Optional[int] = None,
             isolation: str = "read_committed") -> Sequence[LogRecord]:
        del isolation  # reads serve the DURABLE frontier (read_committed):
        # an applied-but-unsynced group-commit transaction stays invisible —
        # like records of an open Kafka transaction — so a crash that loses
        # an unsynced journal line can never un-happen observed records
        while True:
            with self._lock:
                part = self._parts.get((topic, partition))
                if part is None:  # parity with InMemoryLog: reads never create topics
                    return []
                durable = part.durable_offset if self._fsync else part.end_offset
                blocks = list(part.blocks)
                path, gen = part.path, part.gen
            out: List[LogRecord] = []
            limit = max_records if max_records is not None else None
            try:
                for base, pos, count in blocks:
                    if base + count <= from_offset or base >= durable:
                        continue
                    recs = self._decode_block_at(part, topic, partition, pos,
                                                 path, gen)
                    for r in recs:
                        if r.offset < from_offset or r.offset >= durable:
                            continue
                        out.append(r)
                        if limit is not None and len(out) >= limit:
                            return out
                return out
            except (FileNotFoundError, seg.BlockCorruptError):
                with self._lock:
                    if part.gen == gen:
                        raise  # real corruption, not a concurrent compaction
                # the segment was swapped mid-read: retry on the new snapshot

    def end_offset(self, topic: str, partition: int,
                   isolation: str = "read_committed") -> int:
        del isolation  # durable frontier, matching read() (read_committed)
        with self._lock:
            self.topic(topic)
            part = self._parts[(topic, partition)]
            return part.durable_offset if self._fsync else part.end_offset

    # -- failover truncation --------------------------------------------------------------

    def truncate_partition(self, topic: str, partition: int,
                           to_offset: int) -> int:
        """Drop every record at offset >= ``to_offset`` — the KIP-101 role: a
        deposed leader truncates its divergent unreplicated tail to the new
        leader's epoch-start offset before rejoining as a follower.

        Crash-safe via the same generational-swap discipline as compaction:
        the surviving prefix is rewritten to the next generation file (tmp →
        fsync → rename), the manifest is updated, and a frontier journal line
        is appended + fsynced so recovery can never resurrect the truncated
        tail from embedded WAL payloads. Returns the records dropped."""
        with self._lock:
            self.topic(topic)
            key = (topic, partition)
            part = self._parts[key]
            if part.end_offset <= to_offset:
                return 0
            # the rewrite below reads the physical file and appends a direct
            # journal line: lazy tails and staged lines must land first
            self._flush_pending_locked(part)
            with self._wal_lock:
                self._journal_drain_locked()
            # blocks wholly below the cut survive VERBATIM (their file-prefix
            # bytes and positions are unchanged); only blocks at/past the cut
            # are decoded — the boundary block partially re-encoded, later
            # ones dropped — so truncation costs O(truncated tail), not
            # O(partition)
            split = len(part.blocks)
            for i, (base, pos, count) in enumerate(part.blocks):
                if base + count > to_offset:
                    split = i
                    break
            keep_blocks = list(part.blocks[:split])
            prefix_end = (part.blocks[split][1] if split < len(part.blocks)
                          else part.end_pos)
            boundary: List[LogRecord] = []
            dropped = 0
            for base, pos, count in part.blocks[split:]:
                for r in self._decode_block_at(part, topic, partition, pos,
                                               part.path, part.gen):
                    if r.offset < to_offset:
                        boundary.append(r)
                    else:
                        dropped += 1
            runs: List[List[LogRecord]] = []
            for r in boundary:
                if runs and r.offset == runs[-1][-1].offset + 1:
                    runs[-1].append(r)
                else:
                    runs.append([r])
            new_path = self._gen_path(topic, partition, part.gen + 1)
            tmp = new_path + ".tmp"
            new_blocks: List[Tuple[int, int, int]] = keep_blocks
            with open(tmp, "wb") as f:
                if prefix_end:
                    with open(part.path, "rb") as src:
                        while src.tell() < prefix_end:
                            chunk = src.read(min(1 << 20,
                                                 prefix_end - src.tell()))
                            if not chunk:
                                raise RuntimeError(
                                    f"{part.path} shorter than its indexed "
                                    f"prefix {prefix_end}")
                            f.write(chunk)
                pos = prefix_end
                for run in runs:
                    block = seg.encode_block(run, run[0].offset)
                    new_blocks.append((run[0].offset, pos, len(run)))
                    f.write(block)
                    pos += len(block)
                f.flush()
                if self._fsync:
                    os.fsync(f.fileno())
            old_path = part.path
            os.replace(tmp, new_path)
            if self._fsync:
                _fsync_dir(os.path.dirname(new_path))
            if part.file is not None:
                part.file.close()
                part.file = None
            part.path = new_path
            part.gen += 1
            part.blocks = new_blocks
            # the log now ENDS at to_offset: offsets in [last kept + 1,
            # to_offset) are compaction holes, not reclaimed numbers — the
            # next append (or replicated record) continues at to_offset,
            # matching the new leader's numbering
            part.end_offset = min(part.end_offset, to_offset)
            part.end_pos = pos
            part.durable_offset = min(part.durable_offset, part.end_offset)
            part._cache.clear()
            part._cache_sizes.clear()
            part._cache_bytes = 0
            survivors = sum(c for _b, _p, c in keep_blocks) + len(boundary)
            clean_end, clean_count = self._clean.get(key, (0, 0))
            self._clean[key] = (min(clean_end, part.end_offset),
                                min(clean_count, survivors))
            self._write_manifest_entry(topic, partition, part)
            # frontier journal line: recovery's last-line-wins frontier must
            # reflect the truncation even before the next append (and stale
            # embedded payloads must never re-materialize the dropped tail —
            # the manifest's end_pos gates backfill below it)
            self._journal.write((json.dumps(
                {"parts": [[topic, partition, part.end_offset, 0,
                            part.end_pos]], "blk": [None],
                 "trunc": True}) + "\n").encode())
            self._journal.flush()
            self._journal_end = self._journal.tell()
            if self._fsync:
                os.fsync(self._journal.fileno())
            with self._gc_cv:
                target = self._journal.tell()
                if target > self._gc_written:
                    self._gc_written = target
                if target > self._gc_durable:
                    self._gc_durable = target
            try:
                if old_path != new_path:
                    os.unlink(old_path)
            except OSError:
                pass
        if self._digests is not None:
            self._digests.on_truncate(topic, partition, to_offset)
        return dropped

    # -- compaction ---------------------------------------------------------------------

    def compact_partition(self, topic: str, partition: int, *,
                          tombstone_retention_s: float = 0.0,
                          now: Optional[float] = None,
                          upto_offset: Optional[int] = None):
        """Rewrite one partition's segment to latest-record-per-key with
        tombstone GC (policy: surge_tpu.log.compactor.select_retained),
        crash-safely: tmp write → fsync → rename to the next generational
        file → manifest update (the commit point, see module docstring).
        Offsets and ``end_offset`` are preserved; retained records regroup
        into one block per contiguous offset run. ``upto_offset`` bounds the
        pass to blocks wholly below it (the replication compaction barrier:
        leader and follower compact the identical prefix; later blocks move
        over verbatim like any post-snapshot tail)."""
        from surge_tpu.log.compactor import CompactionStats, select_retained

        t0 = time.perf_counter()
        with self._lock:
            self.topic(topic)
            part = self._parts[(topic, partition)]
            # snapshot + tail-copy below read the physical file by position:
            # the lazy pending tail must be on disk first
            self._flush_pending_locked(part)
            blocks = list(part.blocks)
            frontier_off, frontier_pos = part.end_offset, part.end_pos
            if upto_offset is not None and upto_offset < frontier_off:
                split = len(blocks)
                for i, (base, pos, count) in enumerate(blocks):
                    if base + count > upto_offset:
                        split = i
                        break
                blocks = blocks[:split]
                frontier_off = upto_offset
                frontier_pos = (part.blocks[split][1] if split < len(part.blocks)
                                else part.end_pos)
            old_path, gen = part.path, part.gen
        records: List[LogRecord] = []
        for base, pos, count in blocks:  # decode outside the lock (immutable)
            records.extend(self._decode_block_at(part, topic, partition, pos,
                                                 old_path, gen))
        retained, dropped_tombstones = select_retained(
            records, now=now if now is not None else time.time(),
            tombstone_retention_s=tombstone_retention_s)
        stats = lambda after_bytes, after_n, dur: CompactionStats(  # noqa: E731
            topic=topic, partition=partition,
            records_before=len(records), records_after=after_n,
            bytes_before=frontier_pos, bytes_after=after_bytes,
            tombstones_dropped=dropped_tombstones, duration_s=dur)
        if len(retained) == len(records):
            # nothing to drop: record the clean pass (dirty ratio resets)
            # without churning a new segment generation. Clean frontier is the
            # SNAPSHOT frontier — records appended since it were never
            # examined and must stay dirty for the next pass
            with self._lock:
                if part.gen == gen:  # lost race with another compactor: skip
                    self._clean[(topic, partition)] = (frontier_off,
                                                       len(retained))
                    self._write_manifest_entry(topic, partition, part)
            return stats(frontier_pos, len(retained),
                         time.perf_counter() - t0)

        # rewrite: contiguous offset runs become blocks (decode assigns
        # offsets base+i, so a block must never span a compaction hole)
        runs: List[List[LogRecord]] = []
        for r in retained:
            if runs and r.offset == runs[-1][-1].offset + 1:
                runs[-1].append(r)
            else:
                runs.append([r])
        new_path = self._gen_path(topic, partition, gen + 1)
        tmp = new_path + ".tmp"
        new_blocks: List[Tuple[int, int, int]] = []
        with open(tmp, "wb") as f:
            pos = 0
            for run in runs:
                block = seg.encode_block(run, run[0].offset)
                new_blocks.append((run[0].offset, pos, len(run)))
                f.write(block)
                pos += len(block)
            clean_size = pos
            f.flush()
            if self._fsync:
                os.fsync(f.fileno())
        try:
            with self._lock:
                if part.gen != gen:
                    raise RuntimeError(
                        f"{topic}[{partition}] compacted concurrently")
                # blocks committed after our snapshot move over verbatim: copy
                # the byte tail [frontier_pos, end_pos) and shift its positions
                self._flush_pending_locked(part)  # post-snapshot lazy appends
                tail_blocks = part.blocks[len(blocks):]
                if part.end_pos > frontier_pos:
                    with open(old_path, "rb") as src, open(tmp, "ab") as dst:
                        src.seek(frontier_pos)
                        dst.write(src.read(part.end_pos - frontier_pos))
                        dst.flush()
                        if self._fsync:
                            os.fsync(dst.fileno())
                os.replace(tmp, new_path)
                if self._fsync:
                    _fsync_dir(os.path.dirname(new_path))
                # manifest update — the commit point: recovery now resolves
                # this partition through the new generational file
                shift = clean_size - frontier_pos
                if part.file is not None:
                    part.file.close()
                    part.file = None
                part.path = new_path
                part.gen = gen + 1
                part.blocks = new_blocks + [(b, p + shift, c)
                                            for b, p, c in tail_blocks]
                part.end_pos += shift
                part._cache.clear()
                part._cache_sizes.clear()
                part._cache_bytes = 0
                self._clean[(topic, partition)] = (frontier_off, len(retained))
                self._write_manifest_entry(topic, partition, part)
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise
        try:  # stale readers holding the old snapshot retry on FileNotFoundError
            os.unlink(old_path)
        except OSError:
            pass
        if self._digests is not None:
            self._digests.on_compact(topic, partition, frontier_off)
        return stats(clean_size, len(retained), time.perf_counter() - t0)

    def _write_manifest_entry(self, topic: str, partition: int,
                              part: _Partition) -> None:
        clean_end, clean_count = self._clean[(topic, partition)]
        self._manifest.setdefault(topic, {})[str(partition)] = {
            "file": os.path.relpath(part.path, self.root),
            "gen": part.gen,
            "clean_end": clean_end, "clean_count": clean_count,
            "end_offset": part.end_offset, "end_pos": part.end_pos,
        }
        self._persist_json("compaction.json", self._manifest)

    def close(self) -> None:
        with self._gc_cv:
            self._gc_stop = True
            self._gc_cv.notify_all()
        gc_thread = self._gc_thread
        if gc_thread is not None:
            gc_thread.join(2.0)
            self._gc_thread = None
        with self._lock:
            # a clean close leaves complete files: staged journal lines and
            # lazy segment tails land before the handles go away
            try:
                with self._wal_lock:
                    self._journal_drain_locked()
                for part in self._parts.values():
                    self._flush_pending_locked(part)
            except OSError:
                logger.exception("flush on close failed; recovery will "
                                 "backfill from the journal")
            self._journal.close()
            for part in self._parts.values():
                if part.file is not None:
                    part.file.close()
                    part.file = None


class FilePipelinedCommit:
    """One pipelined FileLog transaction: already APPLIED to the log (offsets
    assigned) but NOT yet visible to readers — the read_committed frontier
    (and the append notify) advances only when a group-sync round makes its
    journal line durable, which also resolves the future. ``retry_pipelined``
    re-joins a later round — the records never re-append, so the publisher's
    verbatim retry contract holds for the in-process transport too."""

    __slots__ = ("future", "producer", "target", "records_out", "marks",
                 "touched")

    def __init__(self, producer: "FileTxnProducer", target: int,
                 records_out: List[LogRecord]) -> None:
        self.producer = producer
        self.target = target
        self.records_out = records_out
        self.marks = []
        self.touched = set()
        self.future: "ConcurrentFuture" = ConcurrentFuture()


class FileTxnProducer(InMemoryTxnProducer):
    """FileLog producer: the shared transactional/fencing protocol plus
    pipelined group commits — ``commit_pipelined`` applies the transaction
    synchronously (fast: no fsync under the log lock) and returns a handle
    whose future resolves when the shared journal-fsync round covers it, so
    a publisher lane overlaps durability waits across its in-flight window
    and every lane's round rides ONE fsync."""

    def commit_pipelined(self) -> FilePipelinedCommit:
        if self._buffer is None:
            raise TransactionStateError("no open transaction")
        records, self._buffer = self._buffer, None
        log: FileLog = self._log
        with log._lock:
            log._check_epoch(self.transactional_id, self.epoch)
            out, my_target, touched, marks = log._append_locked(records)
        handle = FilePipelinedCommit(self, my_target, list(out))
        handle.marks = marks
        handle.touched = touched
        if log._fsync and touched:
            # visibility (durable frontier + append notify) advances with the
            # round, in _chain_sync's resolution — readers must never observe
            # records a crash could still erase
            self._chain_sync(handle)
        else:
            if touched:
                log._mark_durable(marks)
                log._notify_append(touched)
            handle.future.set_result(handle.records_out)
        return handle

    def commit_packed(self, batch):
        """Pipelined commit of a pre-decoded :class:`~surge_tpu.log.
        native_gate.NativeBatch` — the broker's native Transact path. No
        LogRecord materialization: returns ``(handle, offsets, timestamp)``
        and the caller builds its reply from its own message objects plus the
        assigned offsets (arrival order). ``handle.records_out`` is None."""
        log: FileLog = self._log
        with log._lock:
            log._check_epoch(self.transactional_id, self.epoch)
            my_target, touched, marks, offsets, ts = \
                log._append_batch_locked(batch)
        handle = FilePipelinedCommit(self, my_target, None)
        handle.marks = marks
        handle.touched = touched
        if log._fsync and touched:
            self._chain_sync(handle)
        else:
            if touched:
                log._mark_durable(marks)
                log._notify_append(touched)
            handle.future.set_result(None)
        return handle, offsets, ts

    def retry_pipelined(self, handle: FilePipelinedCommit) -> FilePipelinedCommit:
        """Re-await durability for an already-applied transaction (a failed
        fsync round): join a fresh round, never re-append."""
        if not handle.future.done():
            raise TransactionStateError("pipelined commit still in flight")
        handle.future = ConcurrentFuture()
        self._chain_sync(handle)
        return handle

    def _chain_sync(self, handle: FilePipelinedCommit) -> None:
        log = self._log
        fut = handle.future

        def _resolve(sync_fut) -> None:
            exc = sync_fut.exception()
            if exc is not None:
                fut.set_exception(exc)
            else:
                # durable now: publish to readers, then resolve the committer
                log._mark_durable(handle.marks)
                log._notify_append(handle.touched)
                fut.set_result(handle.records_out)

        log._enqueue_sync(handle.target).add_done_callback(_resolve)
