"""Deterministic in-process log with full transactional semantics.

The EmbeddedKafka analog (SURVEY.md §4 test strategy): every broker behavior the engine
depends on — atomic multi-topic commits, epoch fencing, read_committed isolation,
compaction views, offset queries — reproduced in-process so engine/publisher/store tests
are hermetic and fast. Also the default transport for single-process engines.

Offsets are assigned at commit time under one lock, so a transaction's records across
topics become visible atomically and read_committed == read_uncommitted at all times
(open transactions buffer producer-side). This is a simplification of Kafka's
LSO/control-record machinery that preserves the observable contract the engine uses.
"""

from __future__ import annotations

import asyncio
import threading
import time
from bisect import bisect_left
from typing import Dict, List, Mapping, Optional, Sequence, Tuple

from surge_tpu.common import cancel_safe_wait_for
from surge_tpu.log.transport import (
    LogRecord,
    ProducerFencedError,
    TopicSpec,
    TransactionStateError,
)


class LogBase:
    """Transport-independent log behavior shared by the in-memory and file backends:
    topic auto-creation, epoch bookkeeping/fencing checks, compaction views built on
    ``read``, and the consumer wakeup primitive. Subclasses provide storage
    (``create_topic``/``read``/``end_offset``/``_append``) and populate ``_topics``,
    ``_epochs``, ``_lock``, ``_append_events``."""

    _topics: Dict[str, TopicSpec]
    _epochs: Dict[str, int]
    _auto_create_partitions: int
    #: lazily-created chained digest index (surge_tpu.log.digest) — None until
    #: the first partition_digest query, so the append path pays one attribute
    #: check when nobody audits
    _digests = None

    def topic(self, name: str) -> TopicSpec:
        with self._lock:
            if name not in self._topics:
                self.create_topic(TopicSpec(name, self._auto_create_partitions))
            return self._topics[name]

    def num_partitions(self, name: str) -> int:
        return self.topic(name).partitions

    def _next_epoch(self, transactional_id: str) -> int:
        with self._lock:
            epoch = self._epochs.get(transactional_id, 0) + 1
            self._epochs[transactional_id] = epoch
            return epoch

    def _check_epoch(self, transactional_id: str, epoch: int) -> None:
        with self._lock:
            if self._epochs.get(transactional_id) != epoch:
                raise ProducerFencedError(
                    f"producer {transactional_id!r} epoch {epoch} fenced by "
                    f"epoch {self._epochs.get(transactional_id)}")

    def _append_fenced(self, transactional_id: str, epoch: int,
                       records: Sequence[LogRecord]) -> List[LogRecord]:
        """Epoch-check + append as one atomic step (the fencing window a
        commit must close). Subclasses whose append has a slow durability
        phase (FileLog's group-commit fsync) override this to run that phase
        OUTSIDE the log lock — holding the lock across an fsync would
        serialize every reader behind the disk."""
        with self._lock:
            self._check_epoch(transactional_id, epoch)
            return self._append(records)

    def latest_by_key(self, topic: str, partition: int,
                      isolation: str = "read_committed") -> Mapping[str, LogRecord]:
        out: Dict[str, LogRecord] = {}
        for r in self.read(topic, partition, isolation=isolation):
            if r.key is None:
                continue
            if r.value is None:
                out.pop(r.key, None)  # tombstone
            else:
                out[r.key] = r
        return out

    def compaction_state(self, topic: str, partition: int) -> Dict[str, int]:
        """Clean frontier of the last compaction pass: ``clean_end`` (offsets
        below it were compacted) and ``clean_count`` (records retained by that
        pass). The dirty-ratio scheduler (surge_tpu.log.compactor) reads this;
        backends update it from ``compact_partition``."""
        clean = getattr(self, "_clean", {})
        end, count = clean.get((topic, partition), (0, 0))
        return {"clean_end": end, "clean_count": count}

    # -- chained digests (the consistency auditor's integrity sensor) -------------------

    def partition_digest(self, topic: str, partition: int,
                         upto: Optional[int] = None) -> dict:
        """Chained CRC digest over ``[clean-base, upto)`` of one partition
        (surge_tpu.log.digest module doc). ``upto`` defaults to — and is
        clamped at — the durable end offset, so leader and follower compare
        at the same offset below the high-watermark without shipping
        records. Creates the digest index on first use; thereafter the
        append paths maintain it eagerly and queries fold only the delta."""
        idx = self._digests
        if idx is None:
            from surge_tpu.log.digest import DigestIndex

            with self._lock:
                if self._digests is None:
                    self._digests = DigestIndex(self)
                idx = self._digests
        end = self.end_offset(topic, partition)
        upto = end if upto is None else min(int(upto), end)
        return idx.digest_at(topic, partition, upto)

    def _digest_observe(self, records) -> None:
        """Eager digest hook — call OUTSIDE the log lock (the digest index
        reads the log under its own lock for catch-up; the only permitted
        ordering is digest-lock → log-lock)."""
        idx = self._digests
        if idx is not None and records:
            idx.observe(records)

    def _notify_append(self, touched) -> None:
        for tp in touched:
            ev = self._append_events.get(tp)
            if ev is not None:
                ev.set()

    async def wait_for_append(self, topic: str, partition: int,
                              after_offset: int) -> None:
        tp = (topic, partition)
        while self.end_offset(topic, partition) <= after_offset:
            ev = self._append_events.get(tp)
            if ev is None or ev.is_set():
                ev = asyncio.Event()
                self._append_events[tp] = ev
            try:
                await cancel_safe_wait_for(ev.wait(), timeout=0.5)
            except asyncio.TimeoutError:
                pass  # re-check end_offset (guards against lost wakeups across loops)


class InMemoryLog(LogBase):
    """In-process :class:`surge_tpu.log.transport.LogTransport` implementation.

    Partition storage is a list of records **sorted by offset but possibly
    sparse**: compaction (``compact_partition``) drops superseded records while
    every survivor keeps its original offset and ``end_offset`` keeps counting —
    the same observable contract a compacted Kafka partition has. A per-key
    latest-record index is maintained incrementally on append, so
    ``latest_by_key`` (the state-topic restore view) is O(keys) instead of a
    full-partition re-scan per call.
    """

    def __init__(self, auto_create_partitions: int = 1) -> None:
        self._topics: Dict[str, TopicSpec] = {}
        self._partitions: Dict[Tuple[str, int], List[LogRecord]] = {}
        self._ends: Dict[Tuple[str, int], int] = {}  # next offset to assign
        # incrementally-maintained compaction view: key -> latest non-tombstone
        self._latest: Dict[Tuple[str, int], Dict[str, LogRecord]] = {}
        self._clean: Dict[Tuple[str, int], Tuple[int, int]] = {}
        self._epochs: Dict[str, int] = {}
        self._lock = threading.RLock()
        self._auto_create_partitions = auto_create_partitions
        # async wakeups for consumers; created lazily per (topic, partition)
        self._append_events: Dict[Tuple[str, int], asyncio.Event] = {}

    # -- topics -------------------------------------------------------------------------

    def create_topic(self, spec: TopicSpec) -> None:
        with self._lock:
            if spec.name in self._topics:
                return
            self._topics[spec.name] = spec
            for p in range(spec.partitions):
                self._partitions[(spec.name, p)] = []
                self._ends[(spec.name, p)] = 0
                self._latest[(spec.name, p)] = {}

    # -- producers ----------------------------------------------------------------------

    def transactional_producer(self, transactional_id: str) -> "InMemoryTxnProducer":
        return InMemoryTxnProducer(self, transactional_id,
                                   self._next_epoch(transactional_id))

    def _append(self, records: Sequence[LogRecord]) -> List[LogRecord]:
        """Atomically append records (possibly spanning topics/partitions)."""
        out: List[LogRecord] = []
        now = time.time()
        with self._lock:
            touched = set()
            for r in records:
                self.topic(r.topic)  # auto-create
                key = (r.topic, r.partition)
                part = self._partitions.get(key)
                if part is None:
                    raise KeyError(f"{r.topic}[{r.partition}] does not exist")
                assigned = LogRecord(
                    topic=r.topic, key=r.key, value=r.value, partition=r.partition,
                    headers=dict(r.headers), offset=self._ends[key], timestamp=now)
                part.append(assigned)
                self._ends[key] += 1
                if r.key is not None:
                    if r.value is None:
                        self._latest[key].pop(r.key, None)  # tombstone
                    else:
                        self._latest[key][r.key] = assigned
                out.append(assigned)
                touched.add(key)
        self._notify_append(touched)
        self._digest_observe(out)
        return out

    # -- reads --------------------------------------------------------------------------

    def read(self, topic: str, partition: int, from_offset: int = 0,
             max_records: Optional[int] = None,
             isolation: str = "read_committed") -> Sequence[LogRecord]:
        del isolation  # open transactions are producer-side buffers; log is all-stable
        with self._lock:
            part = self._partitions.get((topic, partition), [])
            # offsets are sorted but may be sparse after compaction: bisect to
            # the first record at/after from_offset instead of list-slicing
            start = bisect_left(part, from_offset, key=lambda r: r.offset)
            end = len(part) if max_records is None else min(len(part),
                                                            start + max_records)
            return list(part[start:end])

    def end_offset(self, topic: str, partition: int,
                   isolation: str = "read_committed") -> int:
        del isolation
        with self._lock:
            self.topic(topic)
            return self._ends[(topic, partition)]

    def applied_end_offset(self, topic: str, partition: int) -> int:
        """The applied frontier — identical to ``end_offset`` in memory (no
        durability lag); FileLog's differs while an fsync round is open."""
        return self.end_offset(topic, partition)

    # -- replica ingest -----------------------------------------------------------------

    def append_verbatim(self, records: Sequence[LogRecord],
                        allow_gaps: bool = False) -> List[LogRecord]:
        """Append leader-assigned records AS-IS — offsets AND timestamps
        preserved, so a replica converges byte-identically with its leader
        (the follower half of ship-on-commit replication and catch_up).
        Offsets must continue each partition's applied end; with
        ``allow_gaps`` (catch_up over a compacted leader partition) they may
        jump forward, never backward."""
        with self._lock:
            touched = set()
            for r in records:
                self.topic(r.topic)
                key = (r.topic, r.partition)
                part = self._partitions.get(key)
                if part is None:
                    raise KeyError(f"{r.topic}[{r.partition}] does not exist")
                end = self._ends[key]
                if r.offset < end or (r.offset > end and not allow_gaps):
                    raise ValueError(
                        f"verbatim append at {r.topic}[{r.partition}]@"
                        f"{r.offset} but applied end is {end}")
                part.append(r)
                self._ends[key] = r.offset + 1
                if r.key is not None:
                    if r.value is None:
                        self._latest[key].pop(r.key, None)  # tombstone
                    else:
                        self._latest[key][r.key] = r
                touched.add(key)
        self._notify_append(touched)
        self._digest_observe(records)
        return list(records)

    # -- failover truncation ------------------------------------------------------------

    def truncate_partition(self, topic: str, partition: int,
                           to_offset: int) -> int:
        """Drop every record at offset >= ``to_offset`` (the KIP-101 role: a
        deposed leader truncates its divergent unreplicated tail to the new
        leader's epoch-start offset). Returns how many records were dropped."""
        with self._lock:
            self.topic(topic)
            key = (topic, partition)
            part = self._partitions[key]
            cut = bisect_left(part, to_offset, key=lambda r: r.offset)
            dropped = part[cut:]
            if not dropped and self._ends[key] <= to_offset:
                return 0
            del part[cut:]
            self._ends[key] = min(self._ends[key], to_offset)
            # rebuild the per-key latest index for this partition: a dropped
            # record may have superseded (or tombstoned) a surviving one
            latest: Dict[str, LogRecord] = {}
            for r in part:
                if r.key is None:
                    continue
                if r.value is None:
                    latest.pop(r.key, None)
                else:
                    latest[r.key] = r
            self._latest[key] = latest
            clean_end, clean_count = self._clean.get(key, (0, 0))
            if clean_end > to_offset:
                self._clean[key] = (to_offset, min(clean_count, len(part)))
        if self._digests is not None:
            self._digests.on_truncate(topic, partition, to_offset)
        return len(dropped)

    def latest_by_key(self, topic: str, partition: int,
                      isolation: str = "read_committed") -> Mapping[str, LogRecord]:
        del isolation
        with self._lock:
            self.topic(topic)
            # records are immutable (frozen dataclass): sharing them is safe
            return dict(self._latest[(topic, partition)])

    # -- compaction ---------------------------------------------------------------------

    def compact_partition(self, topic: str, partition: int, *,
                          tombstone_retention_s: float = 0.0,
                          now: Optional[float] = None,
                          upto_offset: Optional[int] = None):
        """Rewrite one partition to latest-record-per-key with tombstone GC
        (surge_tpu.log.compactor picks the retained set). Offsets and
        ``end_offset`` are preserved; only superseded records disappear.
        ``upto_offset`` bounds the pass to records below it (the replication
        compaction barrier compacts the same prefix on leader and follower;
        the tail stays verbatim)."""
        from surge_tpu.log.compactor import CompactionStats, select_retained

        t0 = time.perf_counter()
        with self._lock:
            self.topic(topic)
            key = (topic, partition)
            part = self._partitions[key]
            before = len(part)
            bytes_before = sum(_record_bytes(r) for r in part)
            if upto_offset is None:
                head, tail = part, []
                frontier = self._ends[key]
            else:
                cut = bisect_left(part, upto_offset, key=lambda r: r.offset)
                head, tail = part[:cut], part[cut:]
                frontier = upto_offset
            retained, dropped_tombstones = select_retained(
                head, now=now if now is not None else time.time(),
                tombstone_retention_s=tombstone_retention_s)
            retained = retained + tail
            self._partitions[key] = retained
            self._clean[key] = (frontier, len(retained) - len(tail))
            bytes_after = sum(_record_bytes(r) for r in retained)
        if self._digests is not None and len(retained) != before:
            # only a pass that dropped records invalidates the chain; a clean
            # pass leaves the stored bytes (and the digest) untouched
            self._digests.on_compact(topic, partition, frontier)
        return CompactionStats(
            topic=topic, partition=partition,
            records_before=before, records_after=len(retained),
            bytes_before=bytes_before, bytes_after=bytes_after,
            tombstones_dropped=dropped_tombstones,
            duration_s=time.perf_counter() - t0)


def _record_bytes(r: LogRecord) -> int:
    """Approximate storage footprint of one record (stats/dirty-ratio input)."""
    return len(r.value or b"") + len(r.key or "") + 32

class InMemoryTxnProducer:
    """Transactional producer handle; one per transactional id, epoch-fenced."""

    def __init__(self, log: InMemoryLog, transactional_id: str, epoch: int) -> None:
        self._log = log
        self.transactional_id = transactional_id
        self.epoch = epoch
        self._buffer: Optional[List[LogRecord]] = None

    @property
    def fenced(self) -> bool:
        try:
            self._log._check_epoch(self.transactional_id, self.epoch)
            return False
        except ProducerFencedError:
            return True

    @property
    def in_transaction(self) -> bool:
        return self._buffer is not None

    def begin(self) -> None:
        self._log._check_epoch(self.transactional_id, self.epoch)
        if self._buffer is not None:
            raise TransactionStateError("transaction already open")
        self._buffer = []

    def send(self, record: LogRecord) -> None:
        self._log._check_epoch(self.transactional_id, self.epoch)
        if self._buffer is None:
            raise TransactionStateError("no open transaction")
        self._buffer.append(record)

    def commit(self) -> Sequence[LogRecord]:
        # fencing is re-checked inside the atomic append's lock window
        if self._buffer is None:
            raise TransactionStateError("no open transaction")
        records = self._buffer
        self._buffer = None
        return self._log._append_fenced(self.transactional_id, self.epoch,
                                        records)

    def abort(self) -> None:
        if self._buffer is None:
            raise TransactionStateError("no open transaction")
        self._buffer = None

    def send_immediate(self, record: LogRecord) -> LogRecord:
        if self._buffer is not None:
            raise TransactionStateError(
                "send_immediate inside an open transaction")
        return self._log._append_fenced(self.transactional_id, self.epoch,
                                        [record])[0]
