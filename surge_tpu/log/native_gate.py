"""Native broker hot path — ctypes binding for csrc/txn.cc (libsurge_txn).

One C++ call per Transact batch decodes the payload records, and one more
formats the whole WAL journal entry — segment blocks (SLZ + CRC), base64
embedding and the JSON journal line — off the GIL, replacing several
per-record Python passes (``msg_to_record``, ``segment.encode_records``,
``base64``/``json`` per commit). The in-order/dedup gate's scalar decision
kernel (:func:`decide`) lives in the same library; window/alias/pending
bookkeeping stays in Python, which owns that state — Python remains the
control plane, C++ the per-record data plane.

Fallback contract: every native entry point has a pure-Python twin in this
module (:func:`py_decide`, :func:`py_format_journal`) producing **bit-identical
decisions and journal bytes** — enforced by the randomized property test in
tests/test_native_gate.py. When the library is unbuilt (``csrc/build.sh``)
or ``surge.log.native.enabled=false``, callers take the Python twins; an
unbuilt checkout behaves byte-for-byte like the native one.
"""

from __future__ import annotations

import base64
import ctypes
import json
from array import array
from typing import List, Optional, Sequence, Tuple

__all__ = [
    "ACCEPT", "REPLAY", "MAYBE_REOPEN", "WAIT", "FINALIZING",
    "NativeBatch", "available", "batch_from_request", "decide", "enabled",
    "pack_records", "pack_verbatim", "py_decide", "py_format_journal",
    "reply_format", "reply_index", "wal_append",
]

# gate decisions (csrc/txn.cc surge_txn_decide — keep in lockstep)
ACCEPT = 0        #: apply now (seq == applied+1, or unsequenced)
REPLAY = 1        #: seq <= last acked: answer from the dedup window
MAYBE_REOPEN = 2  #: reopened producer's first seq at last+1: absorption candidate
WAIT = 3          #: predecessor not applied: hold at the in-order gate
FINALIZING = 4    #: applied but not acked: ack bookkeeping in flight

_C = ctypes
_i64p = _C.POINTER(_C.c_int64)
_i32p = _C.POINTER(_C.c_int32)
_u8p = _C.c_char_p
#: ABI contract with csrc/txn.cc (checked by tests/test_abi_drift.py)
TXN_SIGNATURES = {
    "surge_txn_parse_request": ((_u8p, _C.c_size_t), _C.c_void_p),
    "surge_txn_parse_packed": ((_i64p, _C.c_size_t, _u8p, _C.c_size_t,
                                _u8p, _i64p, _C.c_size_t), _C.c_void_p),
    "surge_txn_free": ((_C.c_void_p,), None),
    "surge_txn_nrecords": ((_C.c_void_p,), _C.c_int64),
    "surge_txn_seq": ((_C.c_void_p,), _C.c_uint64),
    "surge_txn_token": ((_C.c_void_p,), _C.c_uint64),
    "surge_txn_op": ((_C.c_void_p,), _C.c_int32),
    "surge_txn_ngroups": ((_C.c_void_p,), _C.c_int64),
    "surge_txn_group_meta": ((_C.c_void_p, _C.c_int64, _i64p, _i32p, _i64p),
                             _C.c_void_p),
    "surge_txn_rec_groups": ((_C.c_void_p, _C.POINTER(_C.c_size_t)), _i32p),
    "surge_txn_format": ((_C.c_void_p, _i64p, _i64p, _C.c_double,
                          _C.c_int64), _C.c_int32),
    "surge_txn_line": ((_C.c_void_p, _C.POINTER(_C.c_size_t)), _C.c_void_p),
    "surge_txn_blocks": ((_C.c_void_p, _C.POINTER(_C.c_size_t)), _C.c_void_p),
    "surge_txn_group_out": ((_C.c_void_p, _C.c_int64, _i64p, _i64p, _i32p,
                             _i64p), _C.c_int32),
    "surge_txn_offsets": ((_C.c_void_p, _C.POINTER(_C.c_size_t)), _i64p),
    "surge_txn_decide": ((_C.c_uint64, _C.c_uint64, _C.c_uint64, _C.c_int32),
                         _C.c_int32),
    "surge_wal_append": ((_C.c_int32, _u8p, _C.c_size_t, _C.c_int32),
                         _C.c_int64),
    "surge_seg_index": ((_u8p, _C.c_size_t, _C.c_int64, _i64p,
                         _C.POINTER(_C.c_double)), _C.c_int64),
    # verbatim replica ingest (leader-assigned offsets/timestamps preserved)
    "surge_txn_parse_packed_v": ((_i64p, _C.c_size_t, _u8p, _C.c_size_t,
                                  _u8p, _i64p, _C.c_size_t, _i64p,
                                  _C.POINTER(_C.c_double)), _C.c_void_p),
    "surge_txn_group_base": ((_C.c_void_p, _C.c_int64), _C.c_int64),
    "surge_txn_format_verbatim": ((_C.c_void_p, _i64p, _C.c_int64),
                                  _C.c_int32),
    # reply legs: packed record-view materializer + wire reply formatter
    "surge_reply_count": ((_u8p, _C.c_size_t, _C.c_int32), _C.c_int64),
    "surge_reply_index": ((_u8p, _C.c_size_t, _C.c_int32, _i64p,
                           _C.c_size_t, _C.POINTER(_C.c_double)), _C.c_int64),
    "surge_reply_format": ((_i64p, _C.c_size_t, _u8p, _C.c_size_t, _u8p,
                            _i64p, _C.c_size_t, _C.POINTER(_C.c_double),
                            _C.c_int32, _u8p, _C.c_size_t), _C.c_int64),
}

_lib = None


def _load():
    global _lib
    if _lib is None:
        # deferred: surge_tpu.store's package __init__ imports back into
        # surge_tpu.log at interpreter startup (checkpoint -> file)
        from surge_tpu.store.native import load_native_library

        _lib = load_native_library("libsurge_txn.so", TXN_SIGNATURES)
    return _lib


def available() -> bool:
    """Whether libsurge_txn.so is built and loadable."""
    return _load() is not None


def enabled(config) -> bool:
    """Native hot path usable under this config: library built AND
    ``surge.log.native.enabled`` (default true — the flag is the operator
    kill-switch; an unbuilt library degrades silently either way)."""
    return config.get_bool("surge.log.native.enabled", True) and available()


_decode_enabled: Optional[bool] = None
_decode_pinned = False  # True only for an EXPLICIT set_decode_enabled pin


def set_decode_enabled(value: Optional[bool]) -> None:
    """Force the read-path decode switch (bench arms / tests): True/False pin
    it (True still requires the library), None re-derives from the ambient
    config + availability on next use."""
    global _decode_enabled, _decode_pinned
    _decode_pinned = value is not None
    _decode_enabled = None if value is None else (bool(value) and available())


def decode_pinned() -> Optional[bool]:
    """The explicit test/bench pin, or None when unpinned — distinct from
    :func:`decode_enabled`'s ambient-derived cache, so per-instance configs
    (a transport's own kill-switch) are only overridden by a REAL pin."""
    return _decode_enabled if _decode_pinned else None


def decode_enabled() -> bool:
    """Whether the segment read path's native record-index decode is on —
    the same kill-switch as the append path, read from the ambient config
    (the decoder has no per-call config handle) and cached. Tests reset by
    assigning ``native_gate._decode_enabled = None`` (or False to force the
    Python walk)."""
    global _decode_enabled
    if _decode_enabled is None:
        try:
            from surge_tpu.config import default_config

            _decode_enabled = (default_config().get_bool(
                "surge.log.native.enabled", True) and available())
        except Exception:  # pragma: no cover — config import cycle guard
            _decode_enabled = available()
    return _decode_enabled


# -- gate decision kernel ---------------------------------------------------------------


def py_decide(seq: int, last_seq: int, applied_seq: int, fresh: bool) -> int:
    """Pure-Python twin of csrc/txn.cc:surge_txn_decide (the fallback gate).
    The property test proves every (seq, state) agrees with the native kernel."""
    if not seq:
        return ACCEPT
    if seq <= last_seq:
        return REPLAY
    if fresh and seq == last_seq + 1 and last_seq and seq > applied_seq:
        return MAYBE_REOPEN
    if seq > applied_seq + 1:
        return WAIT
    if seq <= applied_seq:
        return FINALIZING
    return ACCEPT


def decide(seq: int, last_seq: int, applied_seq: int, fresh: bool) -> int:
    """Gate decision via the native kernel when built, else the Python twin."""
    lib = _load()
    if lib is None:
        return py_decide(seq, last_seq, applied_seq, fresh)
    return lib.surge_txn_decide(seq, last_seq, applied_seq, 1 if fresh else 0)


# -- batch handle -----------------------------------------------------------------------


class NativeBatch:
    """One decoded Transact batch held in native memory. ``groups`` is the
    [(topic, partition, count)] list in first-occurrence order — the same
    grouping (and block order) the Python append path produces."""

    __slots__ = ("_lib", "_h", "groups", "nrecords")

    def __init__(self, lib, handle) -> None:
        self._lib = lib
        self._h = handle
        self.nrecords = int(lib.surge_txn_nrecords(handle))
        tl = _C.c_int64()
        part = _C.c_int32()
        count = _C.c_int64()
        groups: List[Tuple[str, int, int]] = []
        for g in range(int(lib.surge_txn_ngroups(handle))):
            ptr = lib.surge_txn_group_meta(handle, g, _C.byref(tl),
                                           _C.byref(part), _C.byref(count))
            groups.append((_C.string_at(ptr, tl.value).decode("utf-8"),
                           part.value, count.value))
        self.groups = groups

    def close(self) -> None:
        h, self._h = self._h, None
        if h:
            self._lib.surge_txn_free(h)

    def __del__(self) -> None:  # pragma: no cover — close() is the normal path
        try:
            self.close()
        except Exception:  # noqa: BLE001 — interpreter teardown
            pass

    def rec_groups(self) -> Sequence[int]:
        """Per-record group index, arrival order (for locator construction)."""
        n = _C.c_size_t()
        ptr = self._lib.surge_txn_rec_groups(self._h, _C.byref(n))
        return ptr[:n.value]

    def group_bases(self) -> List[int]:
        """Per-group base offset (verbatim batches: the leader-assigned run
        base captured at parse; -1 on assign-path batches)."""
        lib, h = self._lib, self._h
        return [int(lib.surge_txn_group_base(h, g))
                for g in range(len(self.groups))]

    def format_verbatim(self, positions: Sequence[int], embed_max: int):
        """Verbatim twin of :meth:`format` (replica ingest): block bases are
        the leader-assigned run bases, every record frames with its own
        timestamp — replica segment bytes converge with the leader's."""
        lib, h = self._lib, self._h
        n = len(self.groups)
        rc = lib.surge_txn_format_verbatim(
            h, (_C.c_int64 * n)(*positions), embed_max)
        if rc != 0:  # pragma: no cover — format cannot fail on a parsed batch
            raise RuntimeError(f"surge_txn_format_verbatim failed ({rc})")
        return self._format_outputs()

    def format(self, bases: Sequence[int], positions: Sequence[int],
               timestamp: float, embed_max: int):
        """One native call: frame + compress + CRC every group's block, build
        the journal line (embedded base64 payloads included). Returns
        ``(line, blocks, gouts, offsets)`` — ``gouts`` per group is
        ``(block_off, block_len, embedded, new_pos)``; ``offsets`` are the
        assigned record offsets in arrival order."""
        lib, h = self._lib, self._h
        n = len(self.groups)
        rc = lib.surge_txn_format(h, (_C.c_int64 * n)(*bases),
                                  (_C.c_int64 * n)(*positions),
                                  timestamp, embed_max)
        if rc != 0:  # pragma: no cover — format cannot fail on a parsed batch
            raise RuntimeError(f"surge_txn_format failed ({rc})")
        return self._format_outputs()

    def _format_outputs(self):
        lib, h = self._lib, self._h
        sz = _C.c_size_t()
        line = _C.string_at(lib.surge_txn_line(h, _C.byref(sz)), sz.value)
        blocks = _C.string_at(lib.surge_txn_blocks(h, _C.byref(sz)), sz.value)
        off = _C.c_int64()
        blen = _C.c_int64()
        emb = _C.c_int32()
        pos = _C.c_int64()
        gouts = []
        for g in range(len(self.groups)):
            lib.surge_txn_group_out(h, g, _C.byref(off), _C.byref(blen),
                                    _C.byref(emb), _C.byref(pos))
            gouts.append((off.value, blen.value, emb.value, pos.value))
        optr = lib.surge_txn_offsets(h, _C.byref(sz))
        return line, blocks, gouts, optr[:sz.value]


def batch_from_request(request) -> Optional[NativeBatch]:
    """Decode a pb TxnRequest's records in ONE native call from its serialized
    bytes — no per-record Python, no ``msg_to_record``. None when the library
    is unbuilt or the wire bytes don't parse (caller takes the Python path)."""
    lib = _load()
    if lib is None:
        return None
    data = request.SerializeToString()
    h = lib.surge_txn_parse_request(data, len(data))
    if not h:
        return None
    return NativeBatch(lib, h)


def pack_records(records) -> Optional[NativeBatch]:
    """Decode a LogRecord batch into a native handle: ONE Python pass packs
    the fields (the in-process commit path has no wire form to parse), the
    native side re-groups and owns the bytes. None when unbuilt."""
    lib = _load()
    if lib is None:
        return None
    meta = array("q")
    ext = meta.extend
    parts: List[bytes] = []
    append = parts.append
    topic_idx = {}
    topic_blob: List[bytes] = []
    topic_lens = array("q")
    for r in records:
        t = r.topic
        g = topic_idx.get(t)
        if g is None:
            g = topic_idx[t] = len(topic_idx)
            tb = t.encode("utf-8")
            topic_blob.append(tb)
            topic_lens.append(len(tb))
        key = r.key
        value = r.value
        flags = 0
        klen = 0
        vlen = 0
        if key is not None:
            kb = key.encode("utf-8")
            flags = 1
            klen = len(kb)
            append(kb)
        if value is None:
            flags |= 2
        else:
            vlen = len(value)
            append(value)
        headers = r.headers
        if headers:
            row = [g, r.partition, flags, klen, vlen, len(headers)]
            for hk, hv in headers.items():
                hkb = hk.encode("utf-8")
                hvb = hv.encode("utf-8")
                append(hkb)
                append(hvb)
                row.append(len(hkb))
                row.append(len(hvb))
            ext(row)
        else:
            ext((g, r.partition, flags, klen, vlen, 0))
    blob = b"".join(parts)
    meta_c = (_C.c_int64 * len(meta)).from_buffer(meta) if meta else None
    lens_c = ((_C.c_int64 * len(topic_lens)).from_buffer(topic_lens)
              if topic_lens else None)
    h = lib.surge_txn_parse_packed(meta_c, len(meta), blob, len(blob),
                                   b"".join(topic_blob), lens_c,
                                   len(topic_lens))
    if not h:
        return None
    return NativeBatch(lib, h)


def pack_verbatim(records) -> Optional[NativeBatch]:
    """Pack a VERBATIM record batch (replica ingest) into a native handle:
    same one-pass packing as :func:`pack_records` plus the leader-assigned
    offsets and timestamps; the native side splits contiguous-offset runs
    into groups (one segment block per run, never spanning an offset hole).
    None when unbuilt."""
    lib = _load()
    if lib is None:
        return None
    meta = array("q")
    ext = meta.extend
    offsets = array("q")
    ts = array("d")
    parts: List[bytes] = []
    append = parts.append
    topic_idx = {}
    topic_blob: List[bytes] = []
    topic_lens = array("q")
    for r in records:
        t = r.topic
        g = topic_idx.get(t)
        if g is None:
            g = topic_idx[t] = len(topic_idx)
            tb = t.encode("utf-8")
            topic_blob.append(tb)
            topic_lens.append(len(tb))
        key = r.key
        value = r.value
        flags = 0
        klen = 0
        vlen = 0
        if key is not None:
            kb = key.encode("utf-8")
            flags = 1
            klen = len(kb)
            append(kb)
        if value is None:
            flags |= 2
        else:
            vlen = len(value)
            append(value)
        headers = r.headers
        if headers:
            row = [g, r.partition, flags, klen, vlen, len(headers)]
            for hk, hv in headers.items():
                hkb = hk.encode("utf-8")
                hvb = hv.encode("utf-8")
                append(hkb)
                append(hvb)
                row.append(len(hkb))
                row.append(len(hvb))
            ext(row)
        else:
            ext((g, r.partition, flags, klen, vlen, 0))
        offsets.append(r.offset)
        ts.append(r.timestamp)
    blob = b"".join(parts)
    meta_c = (_C.c_int64 * len(meta)).from_buffer(meta) if meta else None
    lens_c = ((_C.c_int64 * len(topic_lens)).from_buffer(topic_lens)
              if topic_lens else None)
    offs_c = ((_C.c_int64 * len(offsets)).from_buffer(offsets)
              if offsets else None)
    ts_c = (_C.c_double * len(ts)).from_buffer(ts) if ts else None
    h = lib.surge_txn_parse_packed_v(meta_c, len(meta), blob, len(blob),
                                     b"".join(topic_blob), lens_c,
                                     len(topic_lens), offs_c, ts_c)
    if not h:
        return None
    return NativeBatch(lib, h)


#: RecordMsg index-row width emitted by surge_reply_index (see csrc/txn.cc)
REPLY_ROW_WIDTH = 12


def reply_index(data: bytes, field: int):
    """Index the repeated RecordMsg ``field`` of a serialized reply in ONE
    native call: returns ``(rows, ts)`` — ``REPLY_ROW_WIDTH`` int64s per
    record ([flags, topic_off, topic_len, key_off, key_len, val_off,
    val_len, partition, offset, hdr_cnt, msg_off, msg_len]) plus the
    timestamp array — or None (library unbuilt / malformed bytes: callers
    take the protobuf parse)."""
    lib = _load()
    if lib is None:
        return None
    count = lib.surge_reply_count(data, len(data), field)
    if count < 0:
        return None
    if count == 0:
        return [], []
    rows = (_C.c_int64 * (REPLY_ROW_WIDTH * count))()
    ts = (_C.c_double * count)()
    n = lib.surge_reply_index(data, len(data), field, rows, count, ts)
    if n != count:
        return None
    # bulk-slice to Python lists: per-element ctypes __getitem__ costs more
    # than the decode it replaces
    return rows[:], ts[:]


def reply_format(records, field: int) -> Optional[bytes]:
    """Serialize ``records`` as the repeated RecordMsg ``field`` of a reply
    message in ONE native call (proto3 field order, defaults skipped,
    headers in sorted key order — the canonical form py_reply_format twins).
    One Python pass packs the fields; no RecordMsg ever materializes. None
    when the library is unbuilt (callers build the protobuf reply)."""
    lib = _load()
    if lib is None:
        return None
    # NOTE: this packing loop is the third copy of pack_records' shape (with
    # pack_verbatim) — deliberately unrolled rather than shared, because the
    # per-record call is the hot path each variant exists to shrink. The
    # three stay in lockstep through the bit-identity property tests
    # (tests/test_native_gate.py, tests/test_reply_views.py); change one
    # only with its twins.
    meta = array("q")
    ext = meta.extend
    ts = array("d")
    parts: List[bytes] = []
    append = parts.append
    topic_idx = {}
    topic_blob: List[bytes] = []
    topic_lens = array("q")
    cap = 0
    for r in records:
        t = r.topic
        g = topic_idx.get(t)
        if g is None:
            g = topic_idx[t] = len(topic_idx)
            tb = t.encode("utf-8")
            topic_blob.append(tb)
            topic_lens.append(len(tb))
        key = r.key
        value = r.value
        flags = 0
        klen = 0
        vlen = 0
        if key is not None:
            kb = key.encode("utf-8")
            flags = 1
            klen = len(kb)
            append(kb)
        if value is None:
            flags |= 2
        else:
            vlen = len(value)
            append(value)
        headers = r.headers
        nbytes = klen + vlen + 64
        if headers:
            row = [g, r.partition, flags, klen, vlen, len(headers),
                   r.offset]
            for hk, hv in headers.items():
                hkb = hk.encode("utf-8")
                hvb = hv.encode("utf-8")
                append(hkb)
                append(hvb)
                row.append(len(hkb))
                row.append(len(hvb))
                nbytes += len(hkb) + len(hvb) + 24
            ext(row)
        else:
            ext((g, r.partition, flags, klen, vlen, 0, r.offset))
        ts.append(r.timestamp)
        # capacity bound in BYTES: topic_lens holds the UTF-8 byte length
        # (len(t) counts characters — a CJK topic would overflow the buffer
        # and silently disable the native leg)
        cap += nbytes + topic_lens[g]
    if not ts:
        return b""
    blob = b"".join(parts)
    meta_c = (_C.c_int64 * len(meta)).from_buffer(meta)
    lens_c = (_C.c_int64 * len(topic_lens)).from_buffer(topic_lens)
    ts_c = (_C.c_double * len(ts)).from_buffer(ts)
    out = _C.create_string_buffer(cap)
    n = lib.surge_reply_format(meta_c, len(meta), blob, len(blob),
                               b"".join(topic_blob), lens_c,
                               len(topic_lens), ts_c, field, out, cap)
    if n < 0:
        return None
    return out.raw[:n]


def wal_append(fd: int, buf: bytes, do_fsync: bool) -> int:
    """write()+fsync() in one GIL-free native call (the group-sync worker's
    per-round journal append). Raises OSError like os.write/os.fsync would."""
    lib = _load()
    n = lib.surge_wal_append(fd, buf, len(buf), 1 if do_fsync else 0)
    if n < 0:
        import os as _os

        raise OSError(-n, _os.strerror(-n))
    return n


# -- pure-Python format twin (fallback + property-test reference) -----------------------


def py_format_journal(records, bases: Sequence[int],
                      positions: Sequence[int], timestamp: float,
                      embed_max: int):
    """The Python journal formatter — exactly the bytes FileLog's pre-native
    append produced (segment.encode_block per group + json/base64 line), in
    the same ``(line, blocks, gouts, offsets)`` shape as
    :meth:`NativeBatch.format`. The property test asserts bit-identity against
    the native formatter for randomized batches."""
    from surge_tpu.log import segment as seg
    from surge_tpu.log.transport import LogRecord

    grouped = {}
    order: List[Tuple[str, int]] = []
    offsets: List[int] = []
    rec_slots: List[Tuple[int, int]] = []  # (group idx, index within group)
    for r in records:
        gkey = (r.topic, r.partition)
        members = grouped.get(gkey)
        if members is None:
            members = grouped[gkey] = []
            order.append(gkey)
        rec_slots.append((order.index(gkey), len(members)))
        members.append(r)
    entry_parts = []
    entry_blocks = []
    blocks = b""
    gouts = []
    for g, gkey in enumerate(order):
        base = bases[g]
        run = [LogRecord(topic=r.topic, key=r.key, value=r.value,
                         partition=r.partition, headers=dict(r.headers),
                         offset=base + i, timestamp=timestamp)
               for i, r in enumerate(grouped[gkey])]
        block = seg.encode_block(run, base)
        new_pos = positions[g] + len(block)
        embedded = 1 if len(block) <= embed_max else 0
        entry_parts.append([gkey[0], gkey[1], base, len(run), new_pos])
        entry_blocks.append(
            base64.b64encode(block).decode("ascii") if embedded else None)
        gouts.append((len(blocks), len(block), embedded, new_pos))
        blocks += block
    for g, i in rec_slots:
        offsets.append(bases[g] + i)
    line = (json.dumps({"parts": entry_parts, "blk": entry_blocks})
            + "\n").encode()
    return line, blocks, gouts, offsets
