"""Log segment block codec: framing + native compression binding.

The on-disk unit of :class:`surge_tpu.log.file.FileLog` is a **block**: one committed
transaction's records for one topic-partition, length-prefixed and CRC-checked, with
the payload compressed by the C++ SLZ codec (csrc/segment.cc — the first-party stand-in
for the reference's native lz4 producer compression, SURVEY.md §2.9 item 2). When the
native library isn't built, blocks are stored raw (codec byte 0) — files stay readable
either way because the codec is recorded per block.

Block layout (little-endian):
    magic "SSEG" | codec u8 | pad u8[3] | base_offset u64 | record_count u32 |
    uncompressed_len u32 | payload_len u32 | payload_crc32 u32 | payload
Record layout inside the (uncompressed) payload:
    flags u8 (bit0 has_key, bit1 tombstone) | key_len uvarint | key |
    [value_len uvarint | value]  (absent when tombstone) |
    n_headers uvarint | (k_len uvarint | k | v_len uvarint | v)* | timestamp f64
"""

from __future__ import annotations

import ctypes
import struct
import zlib
from typing import List, Optional, Tuple

from surge_tpu.log.common import SegmentRecordView
from surge_tpu.log.transport import LogRecord

MAGIC = b"SSEG"
CODEC_RAW = 0
CODEC_SLZ = 1
_HEADER = struct.Struct("<4sB3xQIIII")
HEADER_SIZE = _HEADER.size

#: ABI contract with csrc/segment.cc (checked by tests/test_abi_drift.py)
SEGMENT_SIGNATURES = {
    "surge_lz_bound": ((ctypes.c_size_t,), ctypes.c_size_t),
    "surge_lz_compress": ((ctypes.c_char_p, ctypes.c_size_t, ctypes.c_char_p,
                           ctypes.c_size_t), ctypes.c_size_t),
    "surge_lz_decompress": ((ctypes.c_char_p, ctypes.c_size_t,
                             ctypes.c_char_p, ctypes.c_size_t),
                            ctypes.c_size_t),
    "surge_crc32": ((ctypes.c_char_p, ctypes.c_size_t), ctypes.c_uint32),
}

_lib = None
_lib_checked = False


def _load():
    global _lib, _lib_checked
    if _lib_checked:
        return _lib
    _lib_checked = True
    from surge_tpu.store.native import load_native_library

    _lib = load_native_library("libsurge_segment.so", SEGMENT_SIGNATURES)
    return _lib


def native_codec_available() -> bool:
    return _load() is not None


def slz_compress(data: bytes) -> Optional[bytes]:
    """Compress via the native codec; None when unavailable or not worthwhile."""
    lib = _load()
    if lib is None or not data:
        return None
    cap = lib.surge_lz_bound(len(data))
    dst = ctypes.create_string_buffer(cap)
    n = lib.surge_lz_compress(data, len(data), dst, cap)
    if n == 0 or n >= len(data):
        return None
    return dst.raw[:n]


def slz_decompress(data: bytes, uncompressed_len: int) -> bytes:
    lib = _load()
    if lib is None:
        raise RuntimeError("native segment codec not built (csrc/build.sh) but a "
                           "compressed block was encountered")
    out = ctypes.create_string_buffer(max(uncompressed_len, 1))
    n = lib.surge_lz_decompress(data, len(data), out, uncompressed_len)
    if n != uncompressed_len:
        raise ValueError(f"block decompression failed ({n} != {uncompressed_len})")
    return out.raw[:uncompressed_len]


# -- record framing ---------------------------------------------------------------------


def _put_uvarint(buf: bytearray, n: int) -> None:
    while n >= 0x80:
        buf.append((n & 0x7F) | 0x80)
        n >>= 7
    buf.append(n)


def _get_uvarint(data: bytes, pos: int) -> Tuple[int, int]:
    shift = n = 0
    while True:
        b = data[pos]
        pos += 1
        n |= (b & 0x7F) << shift
        if not b & 0x80:
            return n, pos
        shift += 7


def encode_records(records) -> bytes:
    buf = bytearray()
    for r in records:
        flags = (1 if r.key is not None else 0) | (2 if r.value is None else 0)
        buf.append(flags)
        if r.key is not None:
            kb = r.key.encode()
            _put_uvarint(buf, len(kb))
            buf += kb
        if r.value is not None:
            _put_uvarint(buf, len(r.value))
            buf += r.value
        _put_uvarint(buf, len(r.headers))
        # headers frame in SORTED key order: a record decoded from protobuf
        # carries its map in backend-dependent iteration order (upb hashes;
        # the wire has yet another order) — canonicalizing here is what makes
        # native and Python appends byte-identical for the same record, and
        # leader/follower segment files converge regardless of which path
        # built them. UTF-8 byte order == codepoint order, so the C++ twin's
        # bytewise sort agrees with Python's str sort.
        for hk, hv in sorted(r.headers.items()):
            hkb, hvb = hk.encode(), hv.encode()
            _put_uvarint(buf, len(hkb))
            buf += hkb
            _put_uvarint(buf, len(hvb))
            buf += hvb
        buf += struct.pack("<d", r.timestamp)
    return bytes(buf)


def _native_index(payload: bytes, count: int, native=None):
    """Record-index table via csrc/txn.cc surge_seg_index (one native call
    replaces the per-byte uvarint walk): 7 int64s per record —
    [flags, key_off, key_len, val_off, val_len, hdr_off, hdr_cnt] — plus the
    timestamp array. None → caller decodes in Python (library unbuilt,
    surge.log.native.enabled=false, or malformed payload). ``native``
    overrides the ambient switch: a FileLog constructed with an explicit
    config passes its own flag so the kill-switch reaches reads too."""
    from surge_tpu.log import native_gate

    if native is None:
        if not native_gate.decode_enabled():
            return None
    elif not native or not native_gate.available():
        return None
    lib = native_gate._load()
    rows = (ctypes.c_int64 * (7 * count))()
    ts = (ctypes.c_double * count)()
    if lib.surge_seg_index(payload, len(payload), count, rows, ts) < 0:
        return None
    # bulk-slice to Python lists: per-element ctypes __getitem__ would cost
    # more than the uvarint walk it replaces
    return rows[:], ts[:]


def decode_records(payload: bytes, topic: str, partition: int,
                   base_offset: int, count: int,
                   native=None) -> List[LogRecord]:
    idx = _native_index(payload, count, native) if count else None
    if idx is not None:
        # lazy views over the indexed payload: key/value/headers decode on
        # access instead of one frozen-dataclass LogRecord per record —
        # observably identical (equality/repr; tests/test_reply_views.py)
        rows, ts = idx
        return [SegmentRecordView(payload, rows, i * 7, topic, partition,
                                  base_offset + i, ts[i])
                for i in range(count)]
    out = []
    pos = 0
    for i in range(count):
        flags = payload[pos]
        pos += 1
        key = None
        if flags & 1:
            klen, pos = _get_uvarint(payload, pos)
            key = payload[pos: pos + klen].decode()
            pos += klen
        value = None
        if not flags & 2:
            vlen, pos = _get_uvarint(payload, pos)
            value = payload[pos: pos + vlen]
            pos += vlen
        nh, pos = _get_uvarint(payload, pos)
        headers = {}
        for _ in range(nh):
            hklen, pos = _get_uvarint(payload, pos)
            hk = payload[pos: pos + hklen].decode()
            pos += hklen
            hvlen, pos = _get_uvarint(payload, pos)
            headers[hk] = payload[pos: pos + hvlen].decode()
            pos += hvlen
        (ts,) = struct.unpack_from("<d", payload, pos)
        pos += 8
        out.append(LogRecord(topic=topic, key=key, value=value, partition=partition,
                             headers=headers, offset=base_offset + i, timestamp=ts))
    return out


# -- block framing ----------------------------------------------------------------------


def encode_block(records, base_offset: int) -> bytes:
    payload = encode_records(records)
    codec = CODEC_RAW
    stored = payload
    compressed = slz_compress(payload)
    if compressed is not None:
        codec, stored = CODEC_SLZ, compressed
    header = _HEADER.pack(MAGIC, codec, base_offset, len(records), len(payload),
                          len(stored), zlib.crc32(stored))
    return header + stored


class BlockCorruptError(Exception):
    """A block failed its magic/CRC/length checks (truncated or damaged segment)."""


def header_payload_len(header: bytes) -> int:
    """Stored payload length from a bare block header (for seek-and-read access)."""
    if len(header) < HEADER_SIZE:
        raise BlockCorruptError("truncated header")
    magic, _, _, _, _, plen, _ = _HEADER.unpack_from(header, 0)
    if magic != MAGIC:
        raise BlockCorruptError("bad magic")
    return plen


def read_block_header(data: bytes, pos: int):
    """Parse the header at ``pos``; returns (codec, base_offset, count,
    uncompressed_len, payload_len, crc, payload_start) or raises BlockCorruptError."""
    if pos + HEADER_SIZE > len(data):
        raise BlockCorruptError("truncated header")
    magic, codec, base, count, unlen, plen, crc = _HEADER.unpack_from(data, pos)
    if magic != MAGIC:
        raise BlockCorruptError(f"bad magic at {pos}")
    if pos + HEADER_SIZE + plen > len(data):
        raise BlockCorruptError("truncated payload")
    return codec, base, count, unlen, plen, crc, pos + HEADER_SIZE


def decode_block(data: bytes, pos: int, topic: str, partition: int,
                 native=None) -> Tuple[List[LogRecord], int]:
    """Decode the block at ``pos``; returns (records, next_pos). ``native``
    (None = ambient config) pins the record decoder's native/Python choice —
    FileLog threads its per-instance kill-switch through here."""
    codec, base, count, unlen, plen, crc, start = read_block_header(data, pos)
    stored = data[start: start + plen]
    if zlib.crc32(stored) != crc:
        raise BlockCorruptError(f"crc mismatch at {pos}")
    payload = slz_decompress(stored, unlen) if codec == CODEC_SLZ else stored
    return (decode_records(payload, topic, partition, base, count, native),
            start + plen)
