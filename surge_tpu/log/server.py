"""Networked log broker: any LogTransport served over gRPC.

The shared durability substrate between engine processes — the role the external
Kafka broker plays for the reference (SURVEY.md §2.9 item 3; KafkaProducer.scala /
KafkaConsumer.scala are thin wrappers over a remote broker exactly like
:class:`surge_tpu.log.client.GrpcLogTransport` is over this server). Wraps any
in-process :class:`~surge_tpu.log.transport.LogTransport` — :class:`FileLog` for a
durable single-node broker, :class:`InMemoryLog` for tests (the EmbeddedKafka
analog, SURVEY.md §4.5).

Runs on the **synchronous** gRPC server (thread pool): the broker's inner logs are
already thread-safe, handlers never touch an event loop, and one process can host
the broker alongside grpc.aio clients/servers without the multi-loop hazards of
grpc.aio-on-a-thread.

Semantics preserved across the wire:

- **Atomic transactions**: the client buffers ``send()`` locally and ships the whole
  transaction in one ``Transact(op="commit")`` request; the server appends it through
  the wrapped log's transactional producer, so multi-topic atomicity and
  read_committed visibility are the inner log's.
- **Producer-epoch fencing**: ``OpenProducer`` opens a server-side producer, fencing
  any earlier holder of the transactional id (including one opened by another
  process); a fenced producer's operations return ``error_kind="fenced"`` which the
  client re-raises as :class:`ProducerFencedError`.
- **Consumer wakeups**: ``WaitForAppend`` long-polls ``end_offset`` with a bounded
  timeout (the client loops, so arbitrarily long waits stay cheap per request).
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict
from concurrent import futures
from typing import Dict, Optional

import grpc

from surge_tpu.common import logger
from surge_tpu.log import log_service_pb2 as pb
from surge_tpu.log import native_gate
from surge_tpu.log.transport import (
    LogRecord,
    ProducerFencedError,
    TopicSpec,
    TransactionStateError,
)

#: how many recent (txn_seq -> reply/locator) entries each producer keeps: a
#: pipelined client can replay any seq in its in-flight window after a reply
#: loss, not just the newest — sized comfortably above any sane
#: surge.producer.max-in-flight
_DEDUP_WINDOW = 128


class _TxnDedup:
    """Idempotency state for ONE transactional id — shared across producer
    re-opens (and, via replication, across broker failover). With pipelined
    transactions up to a WINDOW of commits can be in flight per producer, so
    alongside the newest committed txn_seq (``last_seq``) a bounded window of
    recent replies (``replies``) and committed-record locations (``locators``)
    is kept — a replayed seq anywhere in the window is answered from cache,
    never appended twice. ``applied_seq`` is the in-order apply frontier: the
    highest seq appended LOCALLY (it runs ahead of ``last_seq`` while a
    replicated commit awaits its follower ack)."""

    __slots__ = ("last_seq", "applied_seq", "last_reply", "locator",
                 "replies", "locators", "persist_gen")

    def __init__(self) -> None:
        self.last_seq = 0
        self.applied_seq = 0
        #: monotonic __txn_state payload generation (allocated under the
        #: producer state lock): the lock-free write half drops a payload
        #: that a NEWER generation already persisted past — two pipelined
        #: seqs resolving in one fsync round must never leave the stale
        #: window as the compacted-latest record
        self.persist_gen = 0
        self.last_reply: Optional[pb.TxnReply] = None
        #: committed-record locations [(topic, partition, offset), ...] for
        #: last_seq, recovered from __txn_state after a broker restart — the
        #: lost reply is rebuilt by re-reading the records at these offsets
        self.locator: Optional[list] = None
        #: seq -> cached ok-reply for the recent window
        self.replies: "OrderedDict[int, pb.TxnReply]" = OrderedDict()
        #: seq -> committed-record locator for the recent window (survives
        #: restarts via the "w" field of __txn_state)
        self.locators: "OrderedDict[int, list]" = OrderedDict()

    def cache_reply(self, seq: int, reply: pb.TxnReply) -> None:
        self.replies[seq] = reply
        while len(self.replies) > _DEDUP_WINDOW:
            self.replies.popitem(last=False)


class _ProducerState:
    """Server-side producer handle bound to its txn id's dedup state."""

    __slots__ = ("txn_id", "producer", "dedup", "lock", "cond", "fresh",
                 "alias_floor", "alias_ceiling", "alias_budget",
                 "alias_joins")

    def __init__(self, txn_id: str, producer, dedup: _TxnDedup) -> None:
        self.txn_id = txn_id
        self.producer = producer
        self.dedup = dedup
        self.lock = threading.Lock()
        #: in-order apply gate: a pipelined seq arriving ahead of its
        #: predecessor waits here until the predecessor applies
        self.cond = threading.Condition(self.lock)
        #: True until this producer's first Transact: gates the
        #: duplicate-absorption of a reopen-retried batch at last_seq+1
        self.fresh = True
        #: in-limbo alias window (set by OpenProducer): seqs in
        #: (alias_floor, alias_ceiling] were APPLIED but not ACKED when this
        #: producer opened — its numbering starts past them, so its first
        #: transacts may be verbatim retries of exactly those batches under
        #: NEW seqs. Up to alias_budget such retries are joined/answered
        #: from the original (payload-matched), never appended twice.
        self.alias_floor = 0
        self.alias_ceiling = 0
        self.alias_budget = 0
        #: alias seq -> ORIGINAL in-limbo seq it matched: a retriable-timeout
        #: retry of the alias must re-join the same original, never append
        self.alias_joins: Dict[int, int] = {}


class _ReplItem:
    """One ordered replication unit: a committed batch, a bare topic create,
    or a compaction BARRIER (kind="barrier": the worker runs the leader-side
    pass bounded to the in-sync followers' frontier and ships the manifest so
    every follower applies the identical generational swap)."""

    __slots__ = ("specs", "records", "txn_id", "seq", "done", "error",
                 "kind", "manifest", "result", "index", "cum_records",
                 "acks")

    def __init__(self, specs, records, txn_id: str = "", seq: int = 0,
                 kind: str = "", manifest: Optional[dict] = None) -> None:
        self.specs = specs
        self.records = records
        self.txn_id = txn_id
        self.seq = seq
        self.kind = kind
        self.manifest = manifest
        self.result = None  # barrier: the leader-side CompactionStats
        self.done = threading.Event()
        self.error: Optional[str] = None
        #: enqueue bookkeeping for the per-follower lag gauges: this item's
        #: position in the cumulative enqueue count, and the cumulative
        #: record count THROUGH it (0 until queued — probe/resync ships use
        #: synthetic items that never enter the queue)
        self.index = 0
        self.cum_records = 0
        #: followers that acked THIS item's ship (the quorum the finalize
        #: pass counts; per-target ships are in order, so ack sets are
        #: prefix-closed along the queue)
        self.acks: set = set()


class _TargetState:
    """Leader-side in-sync tracking for one replication target."""

    __slots__ = ("in_sync", "failing_since", "next_probe", "shipped_index",
                 "shipped_records", "probe_failing_since")

    def __init__(self) -> None:
        self.in_sync = True
        self.failing_since: Optional[float] = None
        self.next_probe = 0.0
        #: the reassign sweep's LIVENESS clock (BrokerStatus probes) — kept
        #: apart from ``failing_since``, which the SHIP path owns: a member
        #: whose data plane fails while its control plane answers must still
        #: accrue toward the ISR drop
        self.probe_failing_since: Optional[float] = None
        #: acked-through marks (absolute, idempotent under re-ship): the
        #: enqueue index / cumulative record count of the newest queue item
        #: this follower acked. Doubles as this follower's CURSOR into the
        #: ordered queue — each in-sync target advances independently, so a
        #: quorum of fast followers can ack a commit while a slow one is
        #: still catching the same items — and feeds the per-follower lag
        #: gauges (surge_log_replication_lag_records{follower=...})
        self.shipped_index = 0
        self.shipped_records = 0


#: compacted broker-internal topic persisting (txn_id -> last committed seq +
#: record locations); rebuilt into the dedup table at startup so idempotency
#: survives a broker restart (the Kafka producer-state-snapshot role)
TXN_STATE_TOPIC = "__txn_state"

#: compacted broker-internal topic persisting this broker's leader-epoch view
#: (the KIP-101 leader-epoch-checkpoint file role): key "epoch" -> {"e": N},
#: key "epoch_start" -> the end offsets recorded at promotion, which a fenced
#: ex-leader truncates its divergent tail to
META_TOPIC = "__broker_meta"

#: broker-internal topics are self-maintained on EACH side — never replicated,
#: resynced, compared, or copied by catch_up
INTERNAL_TOPICS = frozenset({TXN_STATE_TOPIC, META_TOPIC})

SERVICE = "surge_tpu.log.LogService"
METHODS = {
    "CreateTopic": (pb.CreateTopicRequest, pb.TopicReply),
    "GetTopic": (pb.TopicRequest, pb.TopicReply),
    "ListTopics": (pb.ListTopicsRequest, pb.ListTopicsReply),
    "OpenProducer": (pb.OpenProducerRequest, pb.OpenProducerReply),
    "Transact": (pb.TxnRequest, pb.TxnReply),
    "Read": (pb.ReadRequest, pb.ReadReply),
    "EndOffset": (pb.OffsetRequest, pb.OffsetReply),
    "LatestByKey": (pb.OffsetRequest, pb.LatestByKeyReply),
    "WaitForAppend": (pb.WaitRequest, pb.WaitReply),
    "Replicate": (pb.ReplicateRequest, pb.ReplicateReply),
    "DedupSnapshot": (pb.DedupSnapshotRequest, pb.DedupSnapshotReply),
    "ApplyDedup": (pb.ApplyDedupRequest, pb.ReplicateReply),
    "ReplicationStatus": (pb.ReplicationStatusRequest,
                          pb.ReplicationStatusReply),
    # broker-side log compaction (surge_tpu.log.compactor). Message reuse —
    # routing is by this table, not the descriptor, so no proto regeneration:
    # ReadRequest carries (topic, partition); the TxnReply answers ok/error
    # and one RecordMsg whose value holds the CompactionStats JSON
    "CompactTopic": (pb.ReadRequest, pb.TxnReply),
    # broker admin plane (message reuse, same convention as CompactTopic):
    # ArmFaults — TxnRequest.op arm|disarm|status, records[0].value carries a
    #   named fault plan or a JSON rule list (surge_tpu.testing.faults); the
    #   TxnReply's record value answers the plane's stats JSON.
    # PromoteFollower — TxnRequest.records[0].value optionally carries
    #   {"replicate_to": [...]}; promotes this broker to leader at epoch+1.
    # BrokerStatus — role/epoch/leader-hint/epoch-start JSON in the reply
    #   record (the failover prober's and a fenced ex-leader's view).
    "ArmFaults": (pb.TxnRequest, pb.TxnReply),
    "PromoteFollower": (pb.TxnRequest, pb.TxnReply),
    "BrokerStatus": (pb.ListTopicsRequest, pb.TxnReply),
    # broker observability plane (message reuse, same convention as above):
    # GetMetricsText — the broker registry (surge.log.replication.*/journal.*/
    #   txn.*) + per-follower lag collector rendered as OpenMetrics text in
    #   the reply record's value (byte-identical to the metrics_port scrape).
    # DumpFlight — the flight recorder's merge-ready dump as JSON in the
    #   reply record's value; ReadRequest.max_records (has_max) limits to the
    #   newest N events (the chaos CLI's tail).
    # DumpTraces — the tail-kept trace ring's merge-ready dump as JSON (same
    #   envelope discipline as DumpFlight: mono↔wall header pair for skew-
    #   proof cross-process assembly, observability/anatomy.py);
    #   ReadRequest.max_records (has_max) limits to the newest N kept traces.
    # PartitionDigest — the consistency auditor's cross-replica integrity
    #   sensor: ReadRequest names (topic, partition) and from_offset carries
    #   the compare offset `upto` (0 = the durable end); the reply record's
    #   value answers the chained-digest JSON {"topic", "partition", "upto",
    #   "base", "chained", "digest"} (surge_tpu.log.digest) so leader and
    #   follower compare at the same offset without shipping records.
    "GetMetricsText": (pb.ListTopicsRequest, pb.TxnReply),
    "DumpFlight": (pb.ReadRequest, pb.TxnReply),
    "DumpTraces": (pb.ReadRequest, pb.TxnReply),
    "PartitionDigest": (pb.ReadRequest, pb.TxnReply),
    # quorum cluster plane (message reuse, same convention as above):
    # VoteLeader — txn_seq carries the CANDIDATE epoch, records[0].value a
    #   JSON {"candidate": addr, "leader": presumed-dead addr}; the reply
    #   record answers {"granted", "epoch", "reason", "role", "leader_hint",
    #   "leader_alive"}. One vote per epoch, persisted in __broker_meta.
    # ClusterMeta — the dynamic-membership / partition-spread plane:
    #   op "status" answers the cluster metadata view (members + membership
    #   epoch, partition->leader assignments + assignment epoch, coordinator);
    #   "apply" installs a coordinator broadcast (epoch-guarded); the
    #   coordinator-only mutations are "add"/"remove" (AddBroker/RemoveBroker:
    #   rewrite the replicated membership record), "assign" (move ONE
    #   partition's leadership) and "spread" (round-robin every partition
    #   index across the membership). Every mutation mints a fresh cluster
    #   epoch, so stale assignment views are fenced, never split-brained.
    # FetchSlice — standby bulk pull: ReadRequest names (topic, partition,
    #   from_offset, max_records); the reply record's value is ONE
    #   checkpoint-codec partition slice (store/checkpoint.py blocks).
    # InstallSlice — handoff bulk push: records[0].value carries slice
    #   bytes; the standby verbatim-ingests them (leader refuses).
    # HandoffPartition — planned leadership transfer: records[0].value =
    #   {"to": target}; bulk slice ship → fence → journal-tail ship → dedup
    #   push → promote dest → demote; the reply record carries the stats.
    "VoteLeader": (pb.TxnRequest, pb.TxnReply),
    "FetchSlice": (pb.ReadRequest, pb.TxnReply),
    "InstallSlice": (pb.TxnRequest, pb.TxnReply),
    "HandoffPartition": (pb.TxnRequest, pb.TxnReply),
    "ClusterMeta": (pb.TxnRequest, pb.TxnReply),
}


def _serialize_reply(msg):
    """Response serializer for every method: native reply legs hand back
    pre-serialized bytes (csrc/txn.cc surge_reply_format) which pass
    through untouched; protobuf messages serialize as before."""
    if isinstance(msg, bytes):
        return msg
    return msg.SerializeToString()


def record_to_msg(r: LogRecord) -> pb.RecordMsg:
    msg = pb.RecordMsg(topic=r.topic, partition=r.partition,
                       offset=r.offset, timestamp=r.timestamp)
    if r.key is not None:
        msg.has_key = True
        msg.key = r.key
    if r.value is not None:
        msg.has_value = True
        msg.value = r.value
    for k, v in r.headers.items():
        msg.headers[k] = v
    return msg


def _same_payload(committed, retried) -> bool:
    """Whether a retried batch is the same logical payload as the committed one
    (offsets ignored: the retry's records carry none)."""
    if len(committed) != len(retried):
        return False
    return all(a.topic == b.topic and a.partition == b.partition
               and a.key == b.key and a.value == b.value
               for a, b in zip(committed, retried))


def _same_payload_and_headers(committed, retried) -> bool:
    """Stricter batch identity for CROSS-seq matching (the reopen alias
    window): a verbatim retry carries identical headers too, while a
    genuinely new batch that merely repeats topic/key/value bytes usually
    differs there (trace context, request ids) — comparing them shrinks the
    false-absorption surface to byte-for-byte-identical batches."""
    return _same_payload(committed, retried) and all(
        dict(a.headers) == dict(b.headers)
        for a, b in zip(committed, retried))


def msg_to_record(m: pb.RecordMsg) -> LogRecord:
    return LogRecord(topic=m.topic, key=m.key if m.has_key else None,
                     value=m.value if m.has_value else None,
                     partition=m.partition, headers=dict(m.headers),
                     offset=m.offset, timestamp=m.timestamp)


class _CommitRef:
    """Committed-record location on the native Transact path — just enough
    for dedup locators (``_persist_txn_state`` reads topic/partition/offset);
    the reply echoes the request messages, so full LogRecords never
    materialize."""

    __slots__ = ("topic", "partition", "offset")

    def __init__(self, topic: str, partition: int, offset: int) -> None:
        self.topic = topic
        self.partition = partition
        self.offset = offset


class LogServer:
    """gRPC facade over an in-process log. One instance per broker process."""

    def __init__(self, log, host: str = "127.0.0.1", port: int = 0,
                 config=None, max_workers: int = 32,
                 replicate_to: Optional[list] = None, tracer=None,
                 follower_of: Optional[str] = None,
                 auto_promote: Optional[bool] = None,
                 advertised: Optional[str] = None,
                 faults=None, metrics=None, broker_metrics=None,
                 flight=None, metrics_port: Optional[int] = None,
                 quorum_peers: Optional[list] = None) -> None:
        from surge_tpu.metrics.broker import broker_metrics as _broker_metrics
        from surge_tpu.observability.flight import FlightRecorder

        self.log = log
        self.tracer = tracer  # broker-side transact spans (None = zero cost)
        #: this broker's own instrument registry (surge.log.replication.* /
        #: journal.* / txn.* — docs/observability.md broker catalog), exposed
        #: over GetMetricsText and the optional metrics_port scrape endpoint
        self.broker_metrics = broker_metrics if broker_metrics is not None \
            else _broker_metrics()
        #: EngineMetrics quiver when an engine hosts this broker; the broker
        #: quiver carries twin failover/fault sensors, so a standalone broker
        #: counts them into its own scrape
        self.metrics = metrics if metrics is not None else self.broker_metrics
        #: bounded black-box event ring (role transitions, epoch bumps,
        #: truncations, barriers, fault firings — DumpFlight RPC / crash dump)
        self.flight = flight if flight is not None else FlightRecorder()
        #: tail-kept trace ring (surge_tpu.tracing.tail — the DumpTraces RPC
        #: source). None unless a tracer is wired AND surge.trace.tail.enabled:
        #: install_tail attaches the tail sampler to the tracer, so broker-side
        #: spans of erred/slow/breach-window traces are retained for the
        #: cross-process anatomy assembly (observability/anatomy.py)
        from surge_tpu.config import default_config as _dc0
        from surge_tpu.tracing.tail import install_tail
        self.trace_ring = install_tail(
            tracer, config or _dc0(), role="broker",
            metrics=self.broker_metrics)
        self._metrics_port = metrics_port
        self._metrics_server = None
        self.metrics_bound_port: Optional[int] = None
        self._host = host
        self._port = port
        self._config = config
        self._max_workers = max_workers
        self._server: Optional[grpc.Server] = None
        self.bound_port: Optional[int] = None
        #: address other nodes should reach this broker at (NOT_LEADER
        #: redirects, ship-carried leader hints); defaulted from the bound
        #: port at start() when not given
        self.advertised = advertised
        self._producers: Dict[int, "_ProducerState"] = {}  # by token
        self._txn_dedup: Dict[str, _TxnDedup] = {}  # by transactional id
        self._fenced_tokens: "OrderedDict[int, None]" = OrderedDict()
        self._next_token = 1
        self._token_lock = threading.Lock()
        # long-poll waiters may not occupy more than half the handler pool, or
        # many tailing indexers would starve the Transact/Read command path
        self._wait_slots = threading.BoundedSemaphore(max(max_workers // 2, 1))
        # -- replication (leader side): one ordered queue per process so the
        # follower's log is always a gap-free prefix of this one
        self._repl_targets = list(replicate_to or [])
        from surge_tpu.config import default_config as _dc
        cfg = config or _dc()
        self._repl_ack_timeout_s = cfg.get_seconds(
            "surge.log.replication-ack-timeout-ms", 5_000)
        self._repl_queue: "list[_ReplItem]" = []
        self._repl_cv = threading.Condition()
        self._repl_pending: Dict[tuple, _ReplItem] = {}  # (txn_id, seq) -> item
        self._repl_thread: Optional[threading.Thread] = None
        self._repl_stop = False
        self._repl_channels: Dict[str, object] = {}
        # ISR analog (min.insync.replicas, common reference.conf:112-124): a
        # follower failing longer than the isr-timeout is dropped from the
        # in-sync set (commits stop waiting on it) while the set stays
        # >= min-insync; it re-joins when a ship succeeds again (after
        # catch_up). min-insync=len(targets)+1 restores strict acks=all.
        self._repl_min_insync = cfg.get_int(
            "surge.log.replication-min-insync", 1)
        # quorum acks: how many replicas (this leader INCLUDED) must hold a
        # commit before the client is acked. 0 = every in-sync follower (the
        # strict PR-4 behavior); 2 in a 3-broker cluster is the classic
        # majority posture — the slowest follower drops off the ack path
        # while the ordered queue still delivers to it. Pair with
        # surge.log.replication-min-insync >= the same quorum, or a shrunken
        # ISR can ack below the intended durability.
        self._repl_min_insync_acks = cfg.get_int(
            "surge.log.replication.min-insync-acks", 0)
        self._repl_isr_timeout_s = cfg.get_seconds(
            "surge.log.replication-isr-timeout-ms", 10_000)
        self._repl_auto_resync_cap = cfg.get_int(
            "surge.log.replication-auto-resync-max-records", 10_000)
        # pipelined transactions: how long the in-order apply gate waits for a
        # missing predecessor seq before answering retriable
        self._inorder_timeout_s = cfg.get_seconds(
            "surge.log.txn-inorder-timeout-ms", 3_000)
        # native Transact hot path (csrc/txn.cc via log/native_gate): batch
        # decode + WAL formatting in one C++ call, gate decisions through the
        # native kernel; None = the bit-identical pure-Python path
        # (library unbuilt or surge.log.native.enabled=false)
        self._native = native_gate if native_gate.enabled(cfg) else None
        self._gate_decide = (native_gate.decide if self._native is not None
                             else native_gate.py_decide)
        #: ops-plane native-path counters (BrokerStatus `native` row: an
        #: operator can tell a silently-degraded broker — stale .so, flag
        #: off — from a native one at a glance)
        self._native_fallback_count = 0
        self._native_ingest_count = 0
        self._repl_target_state: Dict[str, _TargetState] = {
            t: _TargetState() for t in self._repl_targets}
        # rejoin-probe transport: ONE cached channel per target, stubs derived
        self._probe_channels: Dict[str, object] = {}
        self._probe_stubs: Dict[tuple, object] = {}
        # durable idempotency: __txn_state writer + recovery of a previous
        # life's dedup table (in-memory dedup alone reopens the
        # duplicate-append window on every broker restart)
        self._txn_state_producer = None
        self._txn_state_lock = threading.Lock()
        #: txn_id -> newest persisted payload generation (under
        #: _txn_state_lock): orders the hot path's lock-free annotation
        #: writes per producer
        self._txn_persist_gens: Dict[str, int] = {}
        self._recover_txn_state()
        # -- replication (follower side): ordered ingest of leader batches
        self._replica_lock = threading.Lock()
        self._replica_producer = None
        # -- leader epoch & role (KIP-101/KIP-279 role): every replication
        # batch carries the shipper's epoch; a follower refuses stale epochs,
        # a deposed leader learns it was fenced and demotes (truncating its
        # divergent unreplicated tail to the new leader's epoch-start).
        # Explicit roles are OPT-IN (follower_of= / PromoteFollower): a plain
        # LogServer keeps the seed semantics — accepts everything — so
        # existing single-broker and legacy-failover setups are untouched.
        self._role_lock = threading.RLock()
        self._follower_of = follower_of
        self.role = "follower" if follower_of else "leader"
        #: where writes should go when this broker is not the leader: the
        #: configured leader, the last Replicate's advertised source, or the
        #: peer whose higher epoch fenced us
        self.leader_hint: str = follower_of or ""
        self.epoch = 0 if follower_of else 1
        self.epoch_start: Dict[str, Dict[int, int]] = {}  # at OUR promotion
        # -- majority-quorum promotion (the vote layer over the epoch fence):
        # quorum_peers names the cluster membership — pass the SAME full
        # list (this broker included; it is dropped by address wherever the
        # peer set is consulted) to every broker. A prober-driven promotion
        # then needs a strict majority of the cluster — each peer answers
        # one VoteLeader per epoch, after double-checking leader liveness
        # from ITS vantage — so a follower that merely lost its own link to
        # the leader can never mint a second acking leader. Empty = the
        # PR-4 pairwise behavior.
        peers = (list(quorum_peers) if quorum_peers is not None else
                 [t.strip() for t in
                  cfg.get_str("surge.log.quorum.peers", "").split(",")
                  if t.strip()])
        self._quorum_peers = [p for p in peers if p]
        self._vote_timeout_s = cfg.get_seconds(
            "surge.log.quorum.vote-timeout-ms", 1_000)
        self._vote_rounds = max(1, cfg.get_int(
            "surge.log.quorum.vote-rounds", 5))
        # -- dynamic membership & per-partition leadership (cluster plane):
        # the quorum peer list IS the membership record — `_member_epoch`
        # versions it, and AddBroker/RemoveBroker rewrite it at runtime
        # through the coordinator (the role=="leader" broker). Partition
        # leadership spreads by PARTITION INDEX (Surge topics are
        # co-partitioned: commands, events and state of index p live
        # together), so `_assignments` maps str(p) -> leader address; empty =
        # the legacy whole-broker leadership, bit-identical to PR 7.
        self._member_epoch = 0
        self._assignments: Dict[str, str] = {}
        self._assign_epoch = 0
        #: the cluster epoch the current assignment view was applied AT: a
        #: broker whose `epoch` has been raised past it (a fence reply, a
        #: higher-epoch ship) holds a provably-stale map and suspends its
        #: partition leadership until a metadata refresh lands
        self._meta_epoch = self.epoch
        #: partition indices fenced mid-move (per-partition handoff): their
        #: Transacts answer not_leader with an EMPTY hint (clients hold)
        self._part_fence: set = set()
        #: str(p) -> in-flight Transact count (the per-partition drain the
        #: partition handoff waits on; the global counter stays for the
        #: whole-broker handoff)
        self._inflight_parts: Dict[str, int] = {}
        self._spread_cfg = cfg.get_bool("surge.cluster.spread", False)
        self._reassign_grace_s = cfg.get_seconds(
            "surge.cluster.reassign-grace-ms", 5_000)
        self._next_reassign_check = 0.0
        self._meta_refresh_lock = threading.Lock()
        self._meta_refresh_after = 0.0
        #: epoch -> candidate this broker voted for (one vote per epoch,
        #: persisted in __broker_meta so a bounced voter cannot double-vote)
        self._voted: Dict[int, str] = {}
        self._max_vote_epoch = 0  # highest epoch this broker CAMPAIGNED for
        #: a voter that just granted someone else stands its own candidacy
        #: down until here — the winner's first ship repoints it long before
        self._stand_down_until = 0.0
        # -- per-partition high-watermark: the quorum-acked frontier.
        # Leader: advanced by the finalize pass, shipped with every
        # Replicate; follower: the last shipped value, gating what
        # follower-served read_committed reads may observe.
        self._hwm: Dict[tuple, int] = {}
        #: serialized-hwm cache for record-less ships (beacons, rejoin
        #: probes — the high-rate repeat case); None = rebuild on next use
        self._hwm_wire: Optional[str] = None
        # -- live handoff state: while fenced, Transact/OpenProducer answer
        # not_leader (empty hint — clients hold in place) and the handoff
        # waits for the in-flight counter to drain before shipping the tail
        self._handoff_fence = False
        #: claimed atomically with the role check in HandoffPartition — the
        #: fence only goes up at phase 2, so this flag (not the fence) is
        #: what stops a second handoff from racing the long unfenced bulk
        self._handoff_active = False
        self._inflight_txn = 0
        #: catch_up's bulk lane (FetchSlice); flips off permanently after the
        #: first broker that cannot serve slices
        self._catchup_slices = True
        self._meta_producer = None
        self._recover_meta()
        self._demoting = False
        #: armed fault plane (surge_tpu.log.transport.FaultInjector) — param,
        #: else config (surge.log.faults.plan), else None (hooks cost one
        #: attribute check). Runtime arming via the ArmFaults RPC.
        if faults is None:
            from surge_tpu.log.transport import load_fault_plane

            faults = load_fault_plane(cfg)
        self.faults = faults
        if self.faults is not None:
            self.faults.on_crash = lambda point: self.kill()
            self.faults.flight = self.flight  # fault firings join the ring
        # replication progress (cumulative enqueue counters + per-target
        # acked-through marks) — what the per-follower lag gauges read
        self._repl_enq_items = 0
        self._repl_enq_records = 0
        # observer state the BrokerStatus RPC reports: a rejoining fenced
        # ex-leader is visibly mid-catch_up, not indistinguishable from a
        # healthy follower (ISSUE 5 satellite)
        self.catch_up_state: dict = {"state": "idle"}
        self.last_applied_epoch_start: Dict[str, Dict[str, int]] = {}
        self.last_truncation: Optional[dict] = None
        self._flight_first_ack = False  # armed by promote(): the next acked
        # seq-ful commit records txn.first-ack (the failover timeline's close)
        self._flight_dump_dir = cfg.get_str("surge.log.flight.dump-dir", "")
        # inner-log observability hooks (FileLog WAL rounds/rotations); the
        # attributes exist only on logs that instrument them. Overwrite
        # unconditionally: a broker RESTARTED over an already-instrumented
        # log (the rejoin path re-wraps the same FileLog) must re-point the
        # hooks at ITS quiver/ring, or journal metrics freeze on the dead
        # server's registry
        if hasattr(self.log, "broker_metrics"):
            self.log.broker_metrics = self.broker_metrics
        if hasattr(self.log, "flight"):
            self.log.flight = self.flight
        self.broker_metrics.repl_epoch.record(self.epoch)
        self.broker_metrics.repl_insync_replicas.record(self._insync_count())
        self._dead = False  # set by kill(): every later RPC answers UNAVAILABLE
        self._closed = False  # set by stop(): halts an in-flight campaign
        self.kill_done = None  # threading.Event from kill()'s socket close
        # automatic promotion: a follower probing its leader declares it dead
        # after N consecutive failures and promotes itself (the health-prober
        # driven failover path). Opt-in via auto_promote= or config.
        if auto_promote is None:
            auto_promote = cfg.get_bool("surge.log.failover.auto-promote",
                                        False)
        # quorum-peer brokers keep auto-promotion armed across role changes:
        # a deposed leader becomes a follower that must campaign in the NEXT
        # failover too (the prober itself only runs while role=="follower")
        self._auto_promote = bool(auto_promote) and (
            follower_of is not None or bool(self._quorum_peers))
        self._leader_prober = None

    # -- handlers (sync; called on the server thread pool) --------------------------------

    def CreateTopic(self, request: pb.CreateTopicRequest, context) -> pb.TopicReply:
        spec = TopicSpec(request.spec.name, request.spec.partitions or 1,
                         request.spec.compacted)
        self.log.create_topic(spec)
        if self._repl_targets:
            # a record-less topic must still exist on the follower with the RIGHT
            # partition count (auto-create after failover would guess wrong);
            # best-effort wait — the ordered queue guarantees it lands before
            # any subsequent batch either way
            item = _ReplItem([request.spec], [])
            self._enqueue_item(item)
            item.done.wait(self._repl_ack_timeout_s)
        if (self.role == "leader"
                and (self._spread_cfg or self._spread_active())
                and self._quorum_others()):
            # leadership spread (surge.cluster.spread / an active map): new
            # partition indices join the round-robin the moment they exist
            missing = [p for p in range(spec.partitions or 1)
                       if str(p) not in self._assignments]
            if missing:
                try:
                    self._spread_partitions(spec.partitions or 1)
                except Exception:  # noqa: BLE001 — spread is best-effort here
                    logger.exception("partition spread at CreateTopic failed")
        return pb.TopicReply(found=True, spec=request.spec)

    def GetTopic(self, request: pb.TopicRequest, context) -> pb.TopicReply:
        try:
            spec = self.log.topic(request.name)
        except KeyError:
            return pb.TopicReply(found=False)
        return pb.TopicReply(found=True, spec=pb.TopicSpecMsg(
            name=spec.name, partitions=spec.partitions, compacted=spec.compacted))

    def _topic_specs(self) -> list:
        """Snapshot of the inner log's topic specs under its own lock (a live
        leader may be creating topics concurrently on another pool thread)."""
        lock = getattr(self.log, "_lock", None)
        topics = getattr(self.log, "_topics", {})
        if lock is None:
            return list(topics.values())
        with lock:
            return list(topics.values())

    def ListTopics(self, request: pb.ListTopicsRequest,
                   context) -> pb.ListTopicsReply:
        return pb.ListTopicsReply(topics=[
            pb.TopicSpecMsg(name=s.name, partitions=s.partitions,
                            compacted=s.compacted)
            for s in self._topic_specs()])

    def OpenProducer(self, request: pb.OpenProducerRequest,
                     context) -> pb.OpenProducerReply:
        if (self.role != "leader" and not self._leads_any()) \
                or self._handoff_fence:
            # a broker leading nothing must never open producers: accepted
            # writes would fork the log the moment a leader appends —
            # redirect instead. (In spread mode a partition leader accepts
            # opens; the per-partition Transact gate owns routing.) A
            # handoff fence answers with an EMPTY hint: the destination is
            # not promoted yet, so clients hold in place (jittered backoff)
            # for the tail-sized window instead of ping-ponging.
            if self._handoff_fence:
                return pb.OpenProducerReply(
                    error="leadership handing off; retry shortly",
                    error_kind="not_leader", leader_hint="")
            return pb.OpenProducerReply(
                error=f"broker is a {self.role}, not the leader",
                error_kind="not_leader", leader_hint=self.leader_hint)
        producer = self.log.transactional_producer(request.transactional_id)
        with self._token_lock:
            # prune tokens this open just fenced (the inner log fenced their
            # producers); remember them so a zombie client still gets the
            # protocol-correct "fenced" answer rather than "unknown token"
            for stale in [t for t, st in self._producers.items()
                          if st.txn_id == request.transactional_id]:
                del self._producers[stale]
                self._fenced_tokens[stale] = None
            while len(self._fenced_tokens) > 1024:
                self._fenced_tokens.popitem(last=False)
            token = self._next_token
            self._next_token += 1
            # dedup state outlives the producer: a re-open (same process, or a
            # failover to this broker carrying replicated dedup) resumes the
            # idempotency numbering instead of colliding with it
            dedup = self._txn_dedup.setdefault(request.transactional_id,
                                               _TxnDedup())
            state = _ProducerState(request.transactional_id, producer, dedup)
            self._producers[token] = state
        # a seq still awaiting replication counts, as does one applied locally
        # but not yet acked: the new producer must number PAST them, or its
        # first commit could collide with an in-limbo batch
        pending_max = max(
            (s for (tid, s) in list(self._repl_pending)
             if tid == request.transactional_id), default=0)
        last = max(dedup.last_seq, dedup.applied_seq, pending_max)
        # the numbered-past window: the client may now re-send those very
        # batches under fresh seqs — arm the alias absorber for them
        state.alias_floor = dedup.last_seq
        state.alias_ceiling = last
        state.alias_budget = max(0, last - dedup.last_seq)
        self.broker_metrics.txn_alias_window.record(state.alias_budget)
        return pb.OpenProducerReply(producer_token=token, last_txn_seq=last)

    def Transact(self, request: pb.TxnRequest, context) -> pb.TxnReply:
        # fence check and in-flight increment under ONE lock hold: the
        # handoff raises the fence under this lock and then waits for the
        # in-flight count to drain — a lock-free check could pass the fence,
        # park, and commit AFTER the drain declared the log stable (the tail
        # ship would miss an acked record). Post-increment, the fence
        # provably waits for this call.
        parts: list = []
        with self._role_lock:
            refused = self._write_gate(request.records)
            if refused is not None:
                return refused
            self._inflight_txn += 1
            for m in request.records:
                key = str(m.partition)
                if key not in parts:
                    parts.append(key)
                    self._inflight_parts[key] = \
                        self._inflight_parts.get(key, 0) + 1
        try:
            return self._transact_traced(request, context)
        finally:
            with self._role_lock:
                self._inflight_txn -= 1
                for key in parts:
                    left = self._inflight_parts.get(key, 0) - 1
                    if left <= 0:
                        self._inflight_parts.pop(key, None)
                    else:
                        self._inflight_parts[key] = left

    def _transact_traced(self, request: pb.TxnRequest, context) -> pb.TxnReply:
        if self.tracer is None:
            return self._note_first_ack(self._transact_impl(request, context),
                                        request)
        # the client ships its traceparent as call metadata: the broker-side
        # span joins the same trace as the publisher's flush that caused it
        headers = {k: v for k, v in (context.invocation_metadata() or ())
                   if isinstance(v, str)}
        with self.tracer.start_span("log.server.transact",
                                    headers=headers) as span:
            span.set_attribute("op", request.op)
            span.set_attribute("txn_seq", request.txn_seq)
            span.set_attribute("records", len(request.records))
            reply = self._transact_impl(request, context)
            if not reply.ok:
                span.status = "error"
                span.set_attribute("error_kind", reply.error_kind)
            return self._note_first_ack(reply, request)

    def _stamp_leg(self, key: str, ms: float) -> None:
        """Accumulate one measured wait (gate hold, journal round,
        replication ack) onto the ACTIVE broker span — the
        ``log.server.transact`` span entered by _transact_traced on this
        same handler thread. These ``leg.*`` attributes are what the
        command-anatomy attributor (observability/anatomy.py) reads: the
        broker MEASURES its legs instead of the client inferring them.
        No-op (one None check) on an untraced broker."""
        if self.tracer is None:
            return
        from surge_tpu.tracing import active_span

        span = active_span()
        if span is not None:
            span.attributes[key] = float(span.attributes.get(key, 0.0)) + ms

    def _note_first_ack(self, reply: pb.TxnReply,
                        request: pb.TxnRequest) -> pb.TxnReply:
        """Flight-record the first seq-ful commit acked after a promotion —
        the failover timeline's closing phase (clients are provably being
        served by the new leader again)."""
        if self._flight_first_ack and reply.ok and request.txn_seq:
            self._flight_first_ack = False  # benign race: first-match wins
            self.flight.record("txn.first-ack", epoch=self.epoch,
                               txn_seq=request.txn_seq,
                               records=len(reply.records))
        return reply

    def _transact_impl(self, request: pb.TxnRequest, context) -> pb.TxnReply:
        state = self._producers.get(request.producer_token)
        if state is None:
            if request.producer_token in self._fenced_tokens:
                return pb.TxnReply(ok=False, error="producer fenced",
                                   error_kind="fenced")
            # an unknown token is indistinguishable from one lost in a broker
            # restart (tokens are in-memory); answering "fenced" drives the
            # client's re-open ladder, which is the correct recovery in both
            # cases — a "state" error would live-lock a publisher whose broker
            # bounced (entity retries forever, nothing ever re-opens)
            return pb.TxnReply(ok=False,
                               error="unknown producer token "
                                     "(broker restarted?)",
                               error_kind="fenced")
        seq = request.txn_seq
        records: Optional[list] = None

        def _records() -> list:
            # decoded lazily: only gate slow paths (replays, absorption,
            # alias matching, pending joins) compare LogRecords — the native
            # commit path answers from the request messages and never pays
            # the per-record decode
            nonlocal records
            if records is None:
                records = [msg_to_record(m) for m in request.records]
            return records

        deadline = time.monotonic() + self._inorder_timeout_s
        join_item: Optional[_ReplItem] = None
        sync_handle = None  # pipelined inner-log commit awaiting its round
        committed: list = []
        nat_offsets = None  # native path: assigned offsets, arrival order
        nat_refs: Optional[list] = None  # native path: dedup locator refs
        gate_t0: Optional[float] = None  # set when the in-order gate holds us
        with state.lock:
            dedup = state.dedup
            fresh = state.fresh
            if seq:
                self.broker_metrics.txn_pipelined_depth.record(
                    max(0, seq - dedup.last_seq))
                # only a SEQ-FUL transact consumes the reopen-freshness: the
                # publisher's unsequenced epoch flush record must not eat the
                # one-shot absorption window its stashed batch needs
                state.fresh = False
            while True:
                if seq:
                    # the scalar gate decision runs through the native kernel
                    # (csrc/txn.cc surge_txn_decide) when built — the same
                    # classification the Python twin makes, property-tested
                    # bit-identical; window/alias/pending bookkeeping below
                    # stays in Python, which owns that state
                    decision = self._gate_decide(seq, dedup.last_seq,
                                                 dedup.applied_seq, fresh)
                    # idempotency window: a replayed seq means the client lost
                    # our reply and retried — answer from the dedup window
                    # (any seq a pipelined client can still replay), never
                    # append twice. The cache survives broker restarts via
                    # __txn_state (replies are rebuilt from the recorded
                    # offsets on first replay), and a replay is only honored
                    # for the IDENTICAL payload — answering a different batch
                    # from the cache would silently drop its records.
                    if decision == native_gate.REPLAY:
                        return self._replay_answer(dedup, seq, _records())
                    if decision == native_gate.MAYBE_REOPEN:
                        # reopen-retry absorption: a publisher whose commit
                        # landed but whose broker bounced re-opens (numbering
                        # resumes at last+1) and retries the SAME batch under
                        # the new seq. Only a producer's FIRST transact can be
                        # such a replay — later identical consecutive batches
                        # are legitimate traffic (engine payloads embed
                        # monotonic versions, but raw clients may repeat
                        # bytes).
                        reply = (dedup.replies.get(dedup.last_seq)
                                 or dedup.last_reply
                                 or self._rebuild_cached_reply(dedup))
                        if reply is not None and reply.ok:
                            cached = [msg_to_record(m) for m in reply.records]
                            if _same_payload(cached, _records()):
                                self._ack_seq(state.txn_id, dedup, seq,
                                              reply, cached)
                                state.cond.notify_all()
                                return reply
                    orig = state.alias_joins.get(seq)
                    if orig is not None:
                        # a retried alias seq (its earlier join answered
                        # retriable): re-join the SAME original — by pending
                        # item if still replicating, from the cache once the
                        # worker finalized it
                        pending = self._repl_pending.get(
                            (state.txn_id, orig))
                        if pending is not None:
                            join_item = pending
                            break
                        reply = dedup.replies.get(orig)
                        if reply is None:
                            loc = dedup.locators.get(orig)
                            if loc is not None:
                                reply = self._rebuild_from_locator(loc)
                        if reply is not None and reply.ok:
                            self._ack_seq(state.txn_id, dedup, seq, reply,
                                          [msg_to_record(m)
                                           for m in reply.records])
                            state.cond.notify_all()
                            return reply
                        # original vanished without a trace (poisoned +
                        # window-evicted): fall through to the normal path
                    if state.alias_budget > 0 and seq > dedup.applied_seq:
                        # reopen ALIAS window: this producer's numbering was
                        # started PAST seqs that were applied but not acked
                        # at open (replication in flight when the previous
                        # life died). Its first transacts may be verbatim
                        # retries of exactly those batches under new seqs —
                        # payload-match them against the in-limbo items and
                        # the recent-reply window, join/answer, never append
                        # the same batch twice (the failover-bench dup class).
                        alias = self._alias_match(state, _records())
                        if alias is not None:
                            kind, hit = alias
                            state.alias_budget -= 1
                            if kind == "pending":
                                state.alias_joins[seq] = hit.seq
                                join_item = hit
                                break
                            # already resolved: answer from its cached reply,
                            # acked under the NEW seq as well
                            self._ack_seq(state.txn_id, dedup, seq, hit,
                                          [msg_to_record(m)
                                           for m in hit.records])
                            state.cond.notify_all()
                            return hit
                    # a previous attempt of this seq appended locally but
                    # timed out waiting for replication: re-join that item,
                    # never re-append. The payload must MATCH — the client may
                    # only reuse a seq for the identical batch (a different
                    # batch acked from this item's cache would silently lose
                    # its records)
                    pending = self._repl_pending.get((state.txn_id, seq))
                    if pending is not None:
                        if not _same_payload(pending.records, _records()):
                            return pb.TxnReply(
                                ok=False, error_kind="state",
                                error=f"txn_seq {seq} reused with a "
                                      "different payload while its original "
                                      "batch awaits replication")
                        join_item = pending
                        break
                    # in-order apply gate: a pipelined seq whose predecessor
                    # has not applied yet waits its turn (bounded — the client
                    # retries the same seq on a retriable answer, preserving
                    # exactly-once)
                    if decision == native_gate.WAIT:
                        if gate_t0 is None:
                            gate_t0 = time.monotonic()
                        if time.monotonic() >= deadline:
                            return pb.TxnReply(
                                ok=False, error_kind="retriable",
                                error=f"txn_seq {seq} waiting for in-order "
                                      f"predecessor (applied "
                                      f"{dedup.applied_seq}); retry the same "
                                      "txn_seq")
                        state.cond.wait(
                            min(0.1, deadline - time.monotonic()))
                        continue
                    if decision == native_gate.FINALIZING:
                        # applied, but neither the ack window nor the pending
                        # map holds it — the replication worker is finalizing
                        # it right now. Wait for the bookkeeping, then answer
                        # from the cache.
                        if time.monotonic() >= deadline:
                            return pb.TxnReply(
                                ok=False, error_kind="retriable",
                                error=f"txn_seq {seq} applied; ack "
                                      "bookkeeping still in flight — retry "
                                      "the same txn_seq")
                        state.cond.wait(0.05)
                        continue
                if gate_t0 is not None:
                    # the gate released us: how long a pipelined seq stalled
                    # for its predecessor (high values = window too deep or a
                    # predecessor wedged in a slow round)
                    gate_ms = (time.monotonic() - gate_t0) * 1000.0
                    self.broker_metrics.txn_inorder_wait_timer.record_ms(
                        gate_ms)
                    self._stamp_leg("leg.gate-wait-ms", gate_ms)
                    gate_t0 = None
                try:
                    if request.op == "commit":
                        producer = state.producer
                        use_native = (self._native is not None
                                      and bool(request.records)
                                      and not self._repl_targets
                                      and hasattr(producer, "commit_packed"))
                        if use_native:
                            t0 = time.perf_counter()
                            batch = self._native.batch_from_request(request)
                            if batch is None:  # unparseable: Python path
                                self.broker_metrics.native_fallbacks.record()
                                self._native_fallback_count += 1
                                use_native = False
                        if use_native:
                            # native fast path: ONE C++ call decodes the
                            # payload records, a second formats blocks + the
                            # WAL line inside the pipelined apply — no
                            # LogRecord ever materializes. Durability is
                            # awaited outside the lock exactly like the
                            # pipelined branch below.
                            try:
                                sync_handle, nat_offsets, nat_ts = \
                                    producer.commit_packed(batch)
                                # stamp assigned offsets/timestamps onto the
                                # request messages NOW (under the lock): the
                                # reply echoes them, and a promotion racing
                                # in replication targets reads them below
                                for m, off in zip(request.records,
                                                  nat_offsets):
                                    m.offset = off
                                    m.timestamp = nat_ts
                                groups = batch.groups
                                nat_refs = [
                                    _CommitRef(groups[g][0], groups[g][1],
                                               off)
                                    for g, off in zip(batch.rec_groups(),
                                                      nat_offsets)]
                            finally:
                                batch.close()
                            bm = self.broker_metrics
                            bm.native_gate_batches.record()
                            bm.native_batch_decode_timer.record_ms(
                                (time.perf_counter() - t0) * 1000.0)
                        elif (not self._repl_targets
                                and hasattr(producer, "commit_pipelined")):
                            # pipelined inner log (FileLog): APPLY under the
                            # lock, await DURABILITY outside it — the next
                            # pipelined seq of this producer then applies
                            # while this one's journal round runs, so
                            # max-in-flight overlaps the fsync wait too, not
                            # just the network RTT
                            producer.begin()
                            for r in _records():
                                producer.send(r)
                            sync_handle = producer.commit_pipelined()
                            committed = list(sync_handle.records_out)
                        else:
                            # blocking inner-log commit (replicated leader /
                            # non-pipelined transport): append + the WAL
                            # group-commit round ride inside commit() — the
                            # whole call is the journal leg
                            fsync_t0 = time.perf_counter()
                            producer.begin()
                            for r in _records():
                                producer.send(r)
                            committed = producer.commit()
                            self._stamp_leg(
                                "leg.fsync-ms",
                                (time.perf_counter() - fsync_t0) * 1000.0)
                    elif request.op == "abort":
                        # transactions buffer client-side; nothing to discard here
                        committed = []
                    elif request.op == "send_immediate":
                        committed = [state.producer.send_immediate(r)
                                     for r in _records()]
                    else:
                        return pb.TxnReply(ok=False, error_kind="state",
                                           error=f"unknown op {request.op!r}")
                except ProducerFencedError as exc:
                    return pb.TxnReply(ok=False, error=str(exc), error_kind="fenced")
                except TransactionStateError as exc:
                    return pb.TxnReply(ok=False, error=str(exc), error_kind="state")
                except Exception as exc:  # noqa: BLE001 — surface inner-log failures
                    logger.exception("log server transact failed")
                    return pb.TxnReply(ok=False, error=repr(exc), error_kind="other")
                if seq:
                    dedup.applied_seq = seq
                    state.cond.notify_all()  # wake the next pipelined seq
                if self.faults is not None:
                    # applied locally, nothing replicated/acked yet: the
                    # canonical lost-unreplicated-tail crash point
                    self.faults.crash_point("transact.post-apply")
                if self._repl_targets and nat_offsets is not None:
                    # a promotion added replication targets between the
                    # native-eligibility check and here: materialize the
                    # stamped records (rare race path) and ship them
                    committed = [msg_to_record(m) for m in request.records]
                if self._repl_targets and committed:
                    join_item = self._enqueue_replication(committed,
                                                          state.txn_id, seq)
                    if self.faults is not None:
                        # queued for replication, client not yet acked
                        self.faults.crash_point("transact.post-enqueue")
                    break
                if sync_handle is not None:
                    break  # await durability outside the lock
                if committed and self.role != "leader":
                    # demoted BETWEEN the entry role gate and this ack (a
                    # higher epoch fenced us mid-commit, clearing the repl
                    # targets): the records are now part of OUR divergent
                    # tail, destined for truncation — acking them would lose
                    # an acknowledged write. Refuse; the client re-opens on
                    # the new leader and retries (its dedup has no trace of
                    # this batch, so it appends there exactly once).
                    return pb.TxnReply(
                        ok=False, error_kind="not_leader",
                        error="demoted while committing; write NOT "
                              "acknowledged — retry on the leader",
                        leader_hint=self.leader_hint)
                reply = pb.TxnReply(ok=True,
                                    records=[record_to_msg(r) for r in committed])
                if seq:
                    self._ack_seq(state.txn_id, dedup, seq, reply, committed)
                return reply
        # OUTSIDE the producer lock: await the replication ack / the journal
        # group-sync round. Later seqs in the pipelined window apply (and
        # enqueue, in order) meanwhile — the wait overlaps across the window
        # instead of serializing the producer.
        if join_item is not None:
            return self._finish_replicated(state, seq, join_item)
        fsync_t0 = time.perf_counter()
        for attempt in range(3):
            try:
                sync_handle.future.result()  # gc worker always resolves
                self._stamp_leg(
                    "leg.fsync-ms",
                    (time.perf_counter() - fsync_t0) * 1000.0)
                break
            except Exception as exc:  # noqa: BLE001 — fsync round failed
                # the records ARE applied; durability is unknown. Re-join a
                # later round a couple of times (a transient hiccup heals
                # here); persistent fsync failure is a dying disk — surface
                # it, the client's ladder and the operator take over.
                if attempt == 2:
                    logger.error("journal sync failed for txn_seq %d: %r",
                                 seq, exc)
                    return pb.TxnReply(
                        ok=False, error_kind="other",
                        error=f"journal sync failed: {exc!r}")
                state.producer.retry_pipelined(sync_handle)
        persist_value = None  # (payload bytes, generation) built under lock
        with state.lock:
            if self.role != "leader":
                # demoted while awaiting the journal round (see the in-lock
                # twin of this check): never ack a divergent-tail write
                return pb.TxnReply(
                    ok=False, error_kind="not_leader",
                    error="demoted while committing; write NOT "
                          "acknowledged — retry on the leader",
                    leader_hint=self.leader_hint)
            if nat_offsets is not None:
                # native path: offsets/timestamps were stamped onto the
                # request messages at apply time — echo them (no LogRecord →
                # RecordMsg round trip)
                reply = pb.TxnReply(ok=True, records=request.records)
                acked = nat_refs
            else:
                reply = pb.TxnReply(ok=True,
                                    records=[record_to_msg(r)
                                             for r in committed])
                acked = committed
            if seq:
                self._ack_seq(state.txn_id, state.dedup, seq, reply, acked,
                              persist=False)
                persist_value = self._txn_state_payload(state.txn_id, seq,
                                                        acked)
                state.cond.notify_all()  # a replay may be polling for the ack
        if persist_value is not None:
            # the durable __txn_state annotation commits OFF the producer
            # lock: later seqs of this producer's pipelined window flow while
            # its journal round runs. The reply still waits for it — a replay
            # after a broker restart must find the locator.
            self._txn_state_write(state.txn_id, persist_value)
        return reply

    def _ack_seq(self, txn_id: str, dedup: _TxnDedup, seq: int,
                 reply: pb.TxnReply, committed, persist: bool = True) -> None:
        """Acknowledge one committed seq into the dedup window + durable
        __txn_state (non-replicated commits, the replication worker's
        finalize, follower ingest, and reopen absorption all converge here).
        ``persist=False`` callers split the durable half out themselves
        (payload under their lock, write outside it — the hot path's
        de-fattening; see _transact_impl's tail)."""
        dedup.cache_reply(seq, reply)
        if seq > dedup.last_seq:
            dedup.last_reply = reply
            dedup.last_seq = seq
            dedup.locator = None
        if seq > dedup.applied_seq:
            dedup.applied_seq = seq
        self.broker_metrics.txn_dedup_window.record(len(dedup.replies))
        if persist:
            self._persist_txn_state(txn_id, seq, committed)

    def _alias_match(self, state: "_ProducerState", records):
        """Find the in-limbo (or since-resolved) seq in this reopened
        producer's alias window whose batch matches ``records`` verbatim.
        Returns ("pending", _ReplItem) to join, ("reply", TxnReply) to answer
        from cache, or None (a genuinely new batch). Caller holds the state
        lock; advances the floor so one original is never matched twice."""
        dedup = state.dedup
        for s in range(state.alias_floor + 1, state.alias_ceiling + 1):
            pending = self._repl_pending.get((state.txn_id, s))
            if pending is not None and _same_payload_and_headers(
                    pending.records, records):
                state.alias_floor = s
                return ("pending", pending)
            reply = dedup.replies.get(s)
            if reply is None:
                loc = dedup.locators.get(s)
                if loc is not None:
                    reply = self._rebuild_from_locator(loc)
            if reply is not None and reply.ok and _same_payload_and_headers(
                    [msg_to_record(m) for m in reply.records], records):
                state.alias_floor = s
                return ("reply", reply)
        return None

    def _replay_answer(self, dedup: _TxnDedup, seq: int,
                       records) -> pb.TxnReply:
        """Answer a replayed (already-acked) seq from the dedup window."""
        reply = dedup.replies.get(seq)
        if reply is None and seq == dedup.last_seq:
            reply = dedup.last_reply or self._rebuild_cached_reply(dedup)
        if reply is None:
            loc = dedup.locators.get(seq)
            if loc is not None:
                reply = self._rebuild_from_locator(loc)
                if reply is not None:
                    dedup.cache_reply(seq, reply)
        if reply is None:
            if seq < dedup.last_seq:
                return pb.TxnReply(
                    ok=False, error_kind="state",
                    error=f"stale txn_seq {seq} (last {dedup.last_seq})")
            return pb.TxnReply(ok=False, error="duplicate txn_seq with "
                               "no cached reply", error_kind="state")
        if reply.ok:
            cached = [msg_to_record(m) for m in reply.records]
            if not _same_payload(cached, records):
                return pb.TxnReply(
                    ok=False, error_kind="state",
                    error=f"txn_seq {seq} reused with "
                          "a different payload (its original "
                          "batch already committed)")
        return reply

    # -- replication: leader side ---------------------------------------------------------

    def _enqueue_item(self, item: _ReplItem) -> None:
        """The one place items enter the ordered queue: assigns the enqueue
        index / cumulative record count the per-follower lag gauges measure
        against, registers seq-ful items as pending, wakes the worker."""
        with self._repl_cv:
            self._repl_enq_items += 1
            self._repl_enq_records += len(item.records)
            item.index = self._repl_enq_items
            item.cum_records = self._repl_enq_records
            self._repl_queue.append(item)
            if item.seq:
                self._repl_pending[(item.txn_id, item.seq)] = item
            self._repl_cv.notify()

    def _repl_progress(self, target: str) -> tuple:
        """(lag_batches, lag_records) for one follower — enqueue counters
        minus its acked-through marks (the broker_collector scrape view)."""
        st = self._repl_target_state.get(target)
        if st is None:
            return 0, 0
        with self._repl_cv:
            return (max(0, self._repl_enq_items - st.shipped_index),
                    max(0, self._repl_enq_records - st.shipped_records))

    def _enqueue_replication(self, committed, txn_id: str, seq: int) -> _ReplItem:
        specs = []
        seen = set()
        for r in committed:
            if r.topic not in seen:
                seen.add(r.topic)
                spec = self.log.topic(r.topic)
                specs.append(pb.TopicSpecMsg(name=spec.name,
                                             partitions=spec.partitions,
                                             compacted=spec.compacted))
        item = _ReplItem(specs, list(committed), txn_id, seq)
        self._enqueue_item(item)
        return item

    def _finish_replicated(self, state: "_ProducerState", seq: int,
                           item: _ReplItem) -> pb.TxnReply:
        """Wait for the replication ack; only then return the ok reply. An
        acknowledged commit is on every IN-SYNC follower — with the default
        min-insync=1 that set can shrink to the leader alone after a follower
        outage (availability over durability; set min-insync to the full
        replica count for strict acks=all). Dedup-cache and pending-map
        maintenance happen in the replication worker, so an item whose client
        never retries is still cleaned up."""
        repl_t0 = time.perf_counter()
        acked_in_time = item.done.wait(self._repl_ack_timeout_s)
        self._stamp_leg("leg.repl-ms",
                        (time.perf_counter() - repl_t0) * 1000.0)
        if not acked_in_time:
            return pb.TxnReply(
                ok=False, error_kind="retriable",
                error="replication timeout (commit applied locally; retry the "
                      "same txn_seq to await the in-sync-set ack)")
        if item.error:
            return pb.TxnReply(ok=False, error_kind="retriable",
                               error=f"replication failed: {item.error}")
        reply = pb.TxnReply(ok=True,
                            records=[record_to_msg(r) for r in item.records])
        if seq and seq != item.seq:
            # alias join (reopened producer re-sent an in-limbo batch under a
            # NEW seq): the worker finalized the ORIGINAL seq; the alias seq
            # must enter the dedup window too, so its own replays hit cache
            with state.lock:
                self._ack_seq(state.txn_id, state.dedup, seq, reply,
                              item.records)
                state.cond.notify_all()
        return reply

    def _insync_count(self) -> int:
        """Size of the in-sync set, leader included (min.insync semantics)."""
        return 1 + sum(1 for st in self._repl_target_state.values()
                       if st.in_sync)

    def replication_status(self) -> dict:
        """Operator view of the in-sync set — same shape as
        ``GrpcLogTransport.replication_status()`` so code parameterized over
        either works: ``{"replicas": {target: in_sync}, "min_insync",
        "insync_count", "queue_depth"}``."""
        with self._repl_cv:
            depth = len(self._repl_queue)
        return {"replicas": {t: st.in_sync
                             for t, st in self._repl_target_state.items()},
                "min_insync": self._repl_min_insync,
                "min_insync_acks": self._repl_min_insync_acks,
                "insync_count": self._insync_count(),
                "queue_depth": depth}

    def _replication_loop(self) -> None:
        """Single worker: drain the queue IN ORDER, retrying each item until it
        lands on every IN-SYNC follower (head-of-line blocking is the point —
        a follower must stay a prefix of the leader, never a gappy subset).

        Availability under follower death: a follower that keeps failing past
        the isr-timeout is dropped from the in-sync set — provided the set
        stays >= min-insync — so the queue drains and commits ack without it
        instead of livelocking retriable forever (VERDICT r4 missing #5). An
        out-of-sync follower is probed at most once a second: the leader
        pushes any small lag itself (auto-resync — records finalized while
        the follower was out, plus the dedup table; the Kafka replica
        fetch-loop role) and re-admits the follower once it is a complete
        prefix net of the queue. Beyond the auto-resync cap the follower
        stays out until an operator catch_up bulk-copies it.

        The worker itself must be unkillable by a bug: an uncaught exception
        here would end the thread silently and every later replicated commit
        would time out retriable forever — so one iteration's failure logs
        loudly, backs off, and the loop continues. A POISON head item (one
        that deterministically raises) is failed after a bounded number of
        strikes instead of livelocking the queue: its waiter gets a retriable
        error, the queue drains past it, and if the skip leaves the follower
        gappy the next ship's gap error drives the normal ISR-drop/catch_up
        path — degraded loudly, never stuck silently."""
        backoff = 0.05
        poison_item = None
        strikes = 0
        while True:
            try:
                backoff = self._replication_iteration(backoff)
                poison_item, strikes = None, 0
            except Exception:  # noqa: BLE001 — the worker must never die
                logger.exception(
                    "replication worker iteration failed; continuing")
                with self._repl_cv:
                    head = self._repl_queue[0] if self._repl_queue else None
                if head is not None and head is poison_item:
                    strikes += 1
                else:
                    poison_item, strikes = head, 1
                if head is not None and strikes >= 20:
                    logger.error(
                        "replication head item poisoned (%d consecutive "
                        "worker exceptions); failing it past the queue — a "
                        "gappy follower will drop from the in-sync set and "
                        "needs catch_up", strikes)
                    with self._repl_cv:
                        if self._repl_queue and self._repl_queue[0] is head:
                            self._repl_queue.pop(0)
                    self._repl_pending.pop((head.txn_id, head.seq), None)
                    if head.seq:
                        # the records ARE durably applied on this leader (a
                        # skipped ship cannot un-append them; the follower
                        # re-converges via resync/catch_up) — ack the seq
                        # into the dedup cache so the client's verbatim
                        # retry is answered from it instead of livelocking
                        # on "bookkeeping in flight" forever
                        dedup = self._txn_dedup.setdefault(head.txn_id,
                                                           _TxnDedup())
                        if head.seq > dedup.last_seq:
                            self._ack_seq(
                                head.txn_id, dedup, head.seq,
                                pb.TxnReply(ok=True, records=[
                                    record_to_msg(r) for r in head.records]),
                                head.records)
                    head.error = ("poisoned: repeated replication worker "
                                  "exceptions (see broker log)")
                    head.done.set()
                    poison_item, strikes = None, 0
                time.sleep(min(backoff, 1.0))
                backoff = min(backoff * 2, 1.0)
            if self._repl_stop:
                return

    def _try_resync_and_ship(self, target: str, item) -> Optional[str]:
        """Shared probe flow: close any small lag (auto-resync), then PROVE
        the write path with a ship — the head item when one exists, an empty
        Replicate otherwise (an idle-pass rejoin on offset equality alone
        would re-admit a follower whose read path works but whose write path
        is wedged, and every commit would then pay the isr-timeout before it
        drops again). Returns None only when both steps succeeded."""
        err = self._resync_follower(target)
        if err is None:
            probe_item = item if item is not None else _ReplItem([], [])
            err = self._ship(target, probe_item, timeout=1.0)
        return err

    def _replication_iteration(self, backoff: float) -> float:
        """One pass of the per-target replication machinery; returns the next
        backoff (the outer loop repeats and owns the stop check).

        Each in-sync follower advances an independent CURSOR through the
        ordered queue (its ``shipped_index``), so a quorum of fast followers
        can carry a commit to its ack while a slow-but-alive one still
        drains the same items — head-of-line blocking holds PER FOLLOWER
        (a follower stays a gap-free prefix), not across the set. The
        finalize pass then acks every queue-prefix item whose quorum is met
        (``surge.log.replication.min-insync-acks``; 0 = every in-sync
        follower, the strict PR-4 behavior), advances the per-partition
        high-watermark, and GC's items that every in-sync follower holds.

        The wait also breaks WITHOUT ship work when an out-of-sync
        follower's probe is due: rejoin must not depend on traffic (an idle
        broker would otherwise never re-admit a healed follower until the
        next commit) — the Kafka replica fetch loop runs regardless of
        produce activity."""
        with self._repl_cv:
            while not self._repl_queue and not self._repl_stop:
                self._repl_cv.wait(0.5)
                if not self._repl_queue and any(
                        not st.in_sync
                        and time.monotonic() >= st.next_probe
                        for st in self._repl_target_state.values()):
                    break
                if (not self._repl_queue and self._assignments
                        and self.role == "leader"
                        and time.monotonic() >= self._next_reassign_check):
                    # the coordinator's member-liveness sweep must run on an
                    # IDLE cluster too — a dead partition leader with no
                    # traffic would otherwise never fail over
                    break
            if self._repl_stop:
                return backoff
            queue = list(self._repl_queue)
            base = self._repl_enq_items - len(queue)  # items GC'd so far
        if self.faults is not None and queue:
            # deterministic poison-path site: an injected exception here is
            # exactly the "head item makes the worker raise" class the
            # strike counter in _replication_loop bounds
            self.faults.raise_point("repl.iteration")
        head = queue[0] if queue else None
        if head is not None and head.kind == "barrier":
            # a barrier at the queue HEAD has every predecessor on every
            # in-sync follower (GC only passes fully-shipped items) — the
            # invariant its frontier-bounded pass rests on
            err = self._prepare_barrier(head)
            if err is not None:
                if err.startswith("retry:"):
                    head.error = err
                    time.sleep(backoff)
                    return min(backoff * 2, 1.0)
                # a failing leader-side pass is not retriable: fail the
                # barrier past the queue, loudly
                with self._repl_cv:
                    if self._repl_queue and self._repl_queue[0] is head:
                        self._repl_queue.pop(0)
                head.error = err
                head.done.set()
                logger.error("compaction barrier failed leader-side: %s", err)
                return backoff
        now = time.monotonic()
        blocking_err = None
        progress = False
        for target in self._repl_targets:
            st = self._repl_target_state[target]
            if st.in_sync:
                pos = max(0, st.shipped_index - base)
                if pos >= len(queue):
                    continue  # fully caught up; nothing to ship this pass
                item = queue[pos]
                if item.kind == "barrier" and item is not head:
                    continue  # barriers ship only from the head (see above)
                ship_t0 = time.perf_counter()
                err = self._ship(target, item)
                # timer only for a clean first-try ship: a gap-resync rescue
                # below can take seconds and would pollute a histogram
                # documented as ms-per-queue-item-ship
                clean_ship_ms = (None if err is not None else
                                 (time.perf_counter() - ship_t0) * 1000.0)
                if err is not None and "gap:" in err and now >= st.next_probe:
                    # reachable but BEHIND (e.g. restarted empty while the
                    # min-insync floor forbids dropping it): every ship would
                    # gap-fail forever and commits would block — resync it in
                    # place exactly like an out-of-sync probe would, then
                    # retry the ship (rate-limited by the probe clock)
                    st.next_probe = time.monotonic() + 1.0
                    err = self._try_resync_and_ship(target, item)
                    if err is not None:
                        logger.warning(
                            "in-sync follower %s is behind (gap) and resync "
                            "failed (%s); commits block until it heals or "
                            "drops", target, err)
                if err is None:
                    st.failing_since = None
                    progress = True
                    if item.index:  # queued item acked: advance the cursor
                        st.shipped_index = item.index
                        st.shipped_records = item.cum_records
                        item.acks.add(target)
                        if clean_ship_ms is not None:
                            self.broker_metrics.repl_ship_timer.record_ms(
                                clean_ship_ms)
                        if self._repl_min_insync_acks > 0:
                            # quorum acks: wake waiters the moment THIS ack
                            # completes a quorum — the remaining targets
                            # (including a stalling one whose ship blocks on
                            # its timeout) ship after, off the ack path
                            self._finalize_pass(queue)
                    continue
                item.error = err  # visible to a waiter that times out
                if st.failing_since is None:
                    st.failing_since = now
                insync_after_drop = self._insync_count() - 1
                if (now - st.failing_since >= self._repl_isr_timeout_s
                        and insync_after_drop >= self._repl_min_insync):
                    st.in_sync = False
                    st.next_probe = now + 1.0
                    self.broker_metrics.repl_isr_churn.record()
                    self.broker_metrics.repl_insync_replicas.record(
                        self._insync_count())
                    self.flight.record("isr.drop", follower=target,
                                       error=err[:200])
                    logger.error(
                        "follower %s dropped from the in-sync set after "
                        "%.0fs of failures (%s); commits proceed with "
                        "%d/%d in-sync replicas — it must catch_up to "
                        "re-join", target, now - st.failing_since, err,
                        insync_after_drop, len(self._repl_targets) + 1)
                else:
                    blocking_err = err
            elif now >= st.next_probe:
                # budgeted probe: push any small lag (auto-resync — a
                # one-shot catch_up can never converge under live traffic),
                # then prove the write path with a ship (this follower's
                # next queue item, or an empty Replicate on the idle pass)
                pos = max(0, st.shipped_index - base)
                probe_item = (queue[pos] if pos < len(queue)
                              and (queue[pos].kind != "barrier"
                                   or queue[pos] is head) else None)
                err = self._try_resync_and_ship(target, probe_item)
                if err is None:
                    st.in_sync = True
                    st.failing_since = None
                    # resync proved a complete prefix net of the queue: the
                    # follower holds everything not still queued — its
                    # cursor restarts at the queue tail's base (idempotent
                    # gap-checked re-ships absorb any overlap)
                    with self._repl_cv:
                        st.shipped_index = (self._repl_enq_items
                                            - len(self._repl_queue))
                        st.shipped_records = self._repl_enq_records - sum(
                            len(it.records) for it in self._repl_queue)
                    self.broker_metrics.repl_isr_churn.record()
                    self.broker_metrics.repl_insync_replicas.record(
                        self._insync_count())
                    self.flight.record("isr.rejoin", follower=target)
                    logger.warning("follower %s re-joined the in-sync set",
                                   target)
                else:
                    # operators need the remedy the leader is demanding
                    # ("run catch_up" / "wipe and catch_up"): log it — the
                    # probe interval rate-limits this to ~1/s per target
                    logger.warning("follower %s rejoin probe: %s", target,
                                   err)
                    if st.failing_since is None:
                        # the reassign-grace clock for members that were
                        # ALREADY out of sync when we started watching them
                        # (a post-promotion probe of a corpse) — without it
                        # their led partitions would never fail over
                        st.failing_since = now
                    # fresh clock, not the iteration's `now`: a slow probe
                    # (blackholed peer) must not be due again immediately,
                    # or every commit in degraded mode pays it
                    st.next_probe = time.monotonic() + 1.0
        self._maybe_reassign_failed(now)
        if not queue:
            return backoff  # idle probe pass: nothing to finalize
        finalized = self._finalize_pass(queue)
        if finalized or progress:
            return 0.05
        if blocking_err is not None:
            logger.warning("replication attempt failed: %s", blocking_err)
            time.sleep(backoff)
            return min(backoff * 2, 1.0)
        # nothing shipped, nothing finalized, no error: every reachable
        # cursor is past the queue but a quorum is still outstanding (e.g.
        # min-insync-acks above the live replica count) — wait, don't spin
        # (the top-of-pass cv wait returns immediately on a non-empty queue)
        time.sleep(min(backoff, 0.1))
        return min(backoff * 2, 1.0)

    def _quorum_needed(self, item: _ReplItem, insync_targets: list) -> bool:
        """Whether this queue item's ack set satisfies its quorum. Barriers
        and topic creates always need every in-sync follower (their
        correctness rests on set-wide convergence); data batches ack at
        ``min-insync-acks`` replicas (leader included), 0 = all in-sync."""
        quorum = self._repl_min_insync_acks
        if quorum <= 0 or item.kind == "barrier" or not item.records:
            return all(t in item.acks for t in insync_targets)
        return 1 + len(item.acks) >= quorum

    def _finalize_pass(self, queue: list) -> bool:
        """Ack every queue-prefix item whose quorum is met (dedup cache
        advanced, per-partition high-watermark raised), beacon the fresh hwm
        to fully-caught-up followers, and only THEN wake the waiters — a
        client whose commit just acked may immediately read a follower, so
        the follower's read gate must already admit the records when the ack
        reply leaves this broker. Finally GC items every in-sync follower
        holds. Per-target ships are in order, so quorum satisfaction is
        prefix-monotone — the scan stops at the first unsatisfied item."""
        insync = [t for t in self._repl_targets
                  if self._repl_target_state[t].in_sync]
        finalized: list = []
        for item in queue:
            if item.done.is_set():
                continue
            if not self._quorum_needed(item, insync):
                break
            if item.seq:
                dedup = self._txn_dedup.setdefault(item.txn_id, _TxnDedup())
                if item.seq > dedup.last_seq:
                    # reply BEFORE seq: a lock-free reader that observes the
                    # new last_seq must never see the previous reply
                    self._ack_seq(item.txn_id, dedup, item.seq, pb.TxnReply(
                        ok=True,
                        records=[record_to_msg(r) for r in item.records]),
                        item.records)
                self._repl_pending.pop((item.txn_id, item.seq), None)
            item.error = None
            self._advance_hwm(item.records)
            finalized.append(item)
        if finalized:
            # hwm beacon BEFORE waking waiters: a follower that acked before
            # the quorum completed carries a stale high-watermark — an empty
            # ship refreshes its gate, so read-your-committed-writes holds on
            # followers the moment the client's ack lands (best-effort: a
            # failed beacon only delays visibility until the next data ship)
            with self._repl_cv:
                depth0 = len(self._repl_queue)
                base0 = self._repl_enq_items - depth0
            for t in insync:
                st = self._repl_target_state[t]
                if st.shipped_index - base0 >= depth0:
                    self._ship(t, _ReplItem([], []), timeout=1.0)
            for item in finalized:
                item.done.set()
        # GC: pop items that are finalized AND on every in-sync follower —
        # out-of-sync followers never pin the queue (they re-converge via
        # resync/catch_up, which reads the log directly)
        with self._repl_cv:
            while self._repl_queue:
                h = self._repl_queue[0]
                if not h.done.is_set() or any(
                        self._repl_target_state[t].shipped_index < h.index
                        for t in insync):
                    break
                self._repl_queue.pop(0)
            depth = len(self._repl_queue)
        self.broker_metrics.repl_queue_depth.record(depth)
        return bool(finalized)

    def _advance_hwm(self, records) -> None:
        """Raise the per-partition high-watermark past a quorum-acked batch
        (the min acked-through frontier the quorum provably holds); gauges
        the hwm lag of the partitions the batch touched."""
        lag = 0
        touched = set()
        for r in records:
            if not r.topic or r.topic in INTERNAL_TOPICS:
                continue
            tp = (r.topic, r.partition)
            touched.add(tp)
            if r.offset + 1 > self._hwm.get(tp, 0):
                self._hwm[tp] = r.offset + 1
                self._hwm_wire = None  # serialized map cache is stale
        for tp in touched:
            lag += max(0, self._applied_end(*tp) - self._hwm.get(tp, 0))
        if touched:
            self.broker_metrics.hwm_lag_records.record(lag)

    def _prepare_barrier(self, item: _ReplItem) -> Optional[str]:
        """Leader half of the compaction barrier, run by the worker when the
        barrier reaches the queue head (every item enqueued before it is on
        every in-sync follower): bound the pass to the in-sync followers'
        minimum frontier, compact the leader, and stage the manifest the
        followers will replay identically. Idempotent across ship retries —
        the bound, timestamp and expected outcome are pinned on first run."""
        import json as _json

        m = item.manifest
        if "upto" in m:
            return None  # already prepared; ships are retrying
        topic, p = m["topic"], int(m["partition"])
        ends = []
        for target, st in self._repl_target_state.items():
            if not st.in_sync:
                continue  # out-of-sync followers re-converge via catch_up
            try:
                ends.append(self._remote_end_offset(target, topic, p))
            except Exception as exc:  # noqa: BLE001 — follower hiccup: retry
                self._drop_probe_transport(target)
                return f"retry: barrier frontier probe failed: {target}: {exc!r}"
        upto = min(ends) if ends else self._applied_end(topic, p)
        try:
            stats = self.log.compact_partition(
                topic, p, tombstone_retention_s=float(m["retention_s"]),
                now=float(m["now"]), upto_offset=upto)
        except Exception as exc:  # noqa: BLE001 — surfaced to the operator
            return f"leader compaction failed: {exc!r}"
        m["upto"] = upto
        m["expect_clean_count"] = \
            self.log.compaction_state(topic, p)["clean_count"]
        self.flight.record("compaction.barrier", topic=topic, partition=p,
                           upto=upto, clean_count=m["expect_clean_count"])
        item.result = stats
        # the manifest rides a topic-less record so _queued_counts never
        # mistakes it for a queued data record
        item.records = [LogRecord(topic="", key="barrier",
                                  value=_json.dumps(m).encode())]
        return None

    def _queued_counts(self) -> Dict[tuple, int]:
        """(topic, partition) -> records still in the replication queue (the
        head item included — commits apply locally BEFORE they enqueue)."""
        with self._repl_cv:
            queued: Dict[tuple, int] = {}
            for it in self._repl_queue:
                for r in it.records:
                    tp = (r.topic, r.partition)
                    queued[tp] = queued.get(tp, 0) + 1
        return queued

    def _probe_stub(self, target: str, method: str, req_cls, reply_cls):
        stub = self._probe_stubs.get((target, method))
        if stub is None:
            channel = self._probe_channels.get(target)
            if channel is None:
                from surge_tpu.remote.security import secure_sync_channel

                channel = secure_sync_channel(target, self._config)
                self._probe_channels[target] = channel
            stub = channel.unary_unary(
                f"/{SERVICE}/{method}",
                request_serializer=req_cls.SerializeToString,
                response_deserializer=reply_cls.FromString)
            self._probe_stubs[(target, method)] = stub
        return stub

    def _drop_probe_transport(self, target: str) -> None:
        channel = self._probe_channels.pop(target, None)
        if channel is not None:
            try:
                channel.close()
            except Exception:  # noqa: BLE001 — already broken
                pass
        for key in [k for k in self._probe_stubs if k[0] == target]:
            self._probe_stubs.pop(key, None)

    def _remote_end_offset(self, target: str, topic: str, p: int) -> int:
        return self._probe_stub(target, "EndOffset", pb.OffsetRequest,
                                pb.OffsetReply)(
            pb.OffsetRequest(topic=topic, partition=p),
            timeout=1.0).end_offset

    def _probe_call(self, target: str, method: str, req_cls, reply_cls,
                    request, timeout: float):
        """One probe-stub RPC with a single fresh-channel retry on
        UNAVAILABLE: a cached channel that broke while the peer was down
        sits in gRPC connect-backoff and answers its stale error for
        seconds after the peer is back — exactly wrong for control-plane
        calls that must reach a live peer NOW."""
        try:
            return self._probe_stub(target, method, req_cls, reply_cls)(
                request, timeout=timeout)
        except grpc.RpcError as exc:
            if exc.code() != grpc.StatusCode.UNAVAILABLE:
                raise
            self._drop_probe_transport(target)
            return self._probe_stub(target, method, req_cls, reply_cls)(
                request, timeout=timeout)

    def _resync_follower(self, target: str,
                         deadline: Optional[float] = None) -> Optional[str]:
        """Leader-driven re-sync of a SMALL lag (the Kafka replica fetch
        loop's role): push the follower's missing suffix through the ordered
        gap-checked Replicate stream, then its dedup table. A one-shot
        operator catch_up cannot converge while commits keep landing — the
        pull is always behind by whatever finalized since — so the leader
        closes the live gap itself. Returning None PROVES the follower is a
        complete prefix net of the queue (the lag scan saw zero and anything
        newer sits in the ordered queue behind the probe), so no separate
        verify pass is needed. Bounded two ways: beyond
        ``surge.log.replication-auto-resync-max-records`` total lag
        (fresh/empty replicas) the follower stays out until catch_up
        bulk-copies it, and the whole probe — lag scan included — runs under
        one deadline so a slow-but-alive peer with many partitions cannot
        stall the single replication worker past it (commits are waiting). A
        follower AHEAD of the leader (diverged) is refused outright."""
        cap = self._repl_auto_resync_cap
        if deadline is None:
            deadline = time.monotonic() + 2.5
        if cap <= 0:
            return self._verify_caught_up(target, deadline)
        try:
            queued = self._queued_counts()
            lags: list = []  # (spec, partition, theirs, ours)
            total = 0
            for spec in self._topic_specs():
                if spec.name in INTERNAL_TOPICS:
                    # broker-internal dedup annotations are self-maintained on
                    # EACH side (one record per locally-observed commit), so
                    # their offsets legitimately differ — comparing or pushing
                    # them would read as permanent lag or false divergence;
                    # the dedup content itself travels via ApplyDedup /
                    # Replicate piggyback / catch_up instead
                    continue
                for p in range(spec.partitions or 1):
                    if not self._shippable(spec.name, p):
                        # spread mode: another leader's partition — ITS
                        # stream owns lag/divergence there, and a peer
                        # running ahead of us on it is normal, not diverged
                        continue
                    if time.monotonic() >= deadline:
                        return f"{target}: probe budget exhausted (lag scan)"
                    theirs = self._remote_end_offset(target, spec.name, p)
                    raw_end = self.log.end_offset(spec.name, p)
                    ours = raw_end - queued.get((spec.name, p), 0)
                    if theirs > raw_end:
                        # only records the LEADER ITSELF lacks prove
                        # divergence; a follower holding queued-but-unshipped
                        # records (catch_up raced the queue) is merely early —
                        # the queue's gap-checked ships idempotent-skip them
                        return (f"{target} AHEAD on {spec.name}[{p}] "
                                f"({theirs} > {raw_end}): diverged — wipe "
                                "and catch_up")
                    if theirs < ours:
                        lags.append((spec, p, theirs, ours))
                        total += ours - theirs
            if total > cap:
                return (f"{target} lags {total} records (> auto-resync cap "
                        f"{cap}); run catch_up")
            for spec, p, theirs, ours in lags:
                while theirs < ours:
                    if time.monotonic() >= deadline:
                        return (f"{target}: resync budget exhausted at "
                                f"{spec.name}[{p}]@{theirs}; continuing "
                                "next probe")
                    batch = self.log.read(
                        spec.name, p, from_offset=theirs,
                        max_records=min(1000, ours - theirs))[: ours - theirs]
                    if not batch:
                        return (f"{target}: leader log read returned nothing "
                                f"at {spec.name}[{p}]@{theirs}")
                    spec_msg = pb.TopicSpecMsg(name=spec.name,
                                               partitions=spec.partitions,
                                               compacted=spec.compacted)
                    err = self._ship(target,
                                     _ReplItem([spec_msg], list(batch)),
                                     timeout=1.0)
                    if err is not None:
                        return err
                    self.broker_metrics.repl_catchup_records.record(len(batch))
                    theirs = batch[-1].offset + 1
            if total:
                # dedup table rides along: the pushed records' (txn_id, seq)
                # advanced on the leader only while the follower was out
                err = self._push_dedup_to(target, deadline=deadline)
                if err is not None:
                    return err
            return None
        except Exception as exc:  # noqa: BLE001 — still down / transport error
            self._drop_probe_transport(target)
            return f"{target}: {exc!r}"

    def _verify_caught_up(self, target: str,
                          deadline: Optional[float] = None) -> Optional[str]:
        """Equality check used when auto-resync is DISABLED (cap <= 0): the
        follower may only re-join once its log matches the leader's current
        end offset on EVERY topic-partition — records still sitting in the
        replication queue (the head item included — commits apply locally
        BEFORE they enqueue) are subtracted, since the follower cannot have
        them yet and the ordered gap-checked ships deliver them right after
        the re-join. Deadline-bounded like the resync scan."""
        if deadline is None:
            deadline = time.monotonic() + 2.0
        try:
            queued = self._queued_counts()
            for spec in self._topic_specs():
                if spec.name in INTERNAL_TOPICS:
                    continue  # self-maintained per side; see _resync_follower
                for p in range(spec.partitions or 1):
                    if not self._shippable(spec.name, p):
                        continue  # another spread leader's partition
                    if time.monotonic() >= deadline:
                        return f"{target}: probe budget exhausted (verify)"
                    theirs = self._remote_end_offset(target, spec.name, p)
                    ours = (self.log.end_offset(spec.name, p)
                            - queued.get((spec.name, p), 0))
                    if theirs != ours:
                        return (f"{target} behind on {spec.name}[{p}]: "
                                f"{theirs} != {ours}")
            return None
        except Exception as exc:  # noqa: BLE001 — still down / transport error
            self._drop_probe_transport(target)
            return f"{target}: {exc!r}"

    def _push_dedup_to(self, target: str,
                       deadline: Optional[float] = None) -> Optional[str]:
        """Chunked DedupSnapshot → ApplyDedup push (resync rejoin AND the
        handoff's phase 4 — the exactly-once-critical transfer lives in ONE
        place). Chunked because a long-lived leader's table can be large
        (each entry embeds its cached reply); ``deadline`` budgets the whole
        push (the resync probe's budget), else each chunk gets a fixed 5s.
        Returns an error string (None = fully pushed)."""
        snap = self.DedupSnapshot(pb.DedupSnapshotRequest(), None)
        push = self._probe_stub(target, "ApplyDedup", pb.ApplyDedupRequest,
                                pb.ReplicateReply)
        entries = list(snap.entries)
        for lo in range(0, len(entries), 500):
            if deadline is not None:
                left = deadline - time.monotonic()
                if left <= 0:
                    return (f"{target}: probe budget exhausted "
                            "(dedup push); continuing next probe")
                timeout = max(left, 0.2)
            else:
                timeout = 5.0
            reply = push(pb.ApplyDedupRequest(entries=entries[lo: lo + 500]),
                         timeout=timeout)
            if not reply.ok:
                return f"{target}: dedup push failed: {reply.error}"
        return None

    def _ship_hwm_json(self, item: _ReplItem) -> str:
        """The high-watermark map this ship carries. When a SINGLE follower
        ack completes the quorum (one in-sync follower under acks=all, or
        min-insync-acks=2), the shipped batch's own end offsets are included
        optimistically: the moment the receiving follower applies it, leader
        + itself ARE the quorum, so it may serve those records immediately —
        follower reads then never lag the ack by a beacon round."""
        import json as _json

        optimistic = bool(item.records) and (
            self._repl_min_insync_acks == 2 or (
                self._repl_min_insync_acks <= 0
                and self._insync_count() <= 2))
        if not optimistic:
            # the map is byte-identical between hwm advances (beacons,
            # rejoin probes, AND data ships outside the single-ack-quorum
            # shapes) — serialize once per advance, not once per ship
            if self._hwm_wire is None:
                hwm = {f"{t}|{p}": off for (t, p), off in self._hwm.items()}
                self._hwm_wire = _json.dumps(hwm) if hwm else ""
            return self._hwm_wire
        hwm = {f"{t}|{p}": off for (t, p), off in self._hwm.items()}
        for r in item.records:
            if not r.topic or r.topic in INTERNAL_TOPICS:
                continue
            key = f"{r.topic}|{r.partition}"
            if r.offset + 1 > hwm.get(key, 0):
                hwm[key] = r.offset + 1
        return _json.dumps(hwm) if hwm else ""

    def _ship(self, target: str, item: _ReplItem,
              timeout: Optional[float] = None) -> Optional[str]:
        if self.faults is not None:
            err = self.faults.on_ship(target)
            if err is not None:
                return f"{target}: {err}"
        try:
            call = self._repl_channels.get(target)
            if call is None:
                from surge_tpu.remote.security import secure_sync_channel

                channel = secure_sync_channel(target, self._config)
                call = channel.unary_unary(
                    f"/{SERVICE}/Replicate",
                    request_serializer=pb.ReplicateRequest.SerializeToString,
                    response_deserializer=pb.ReplicateReply.FromString)
                self._repl_channels[target] = call
            reply = call(pb.ReplicateRequest(
                topics=item.specs,
                records=[record_to_msg(r) for r in item.records],
                transactional_id=item.txn_id, txn_seq=item.seq,
                leader_epoch=self.epoch, kind=item.kind,
                # only the COORDINATOR's ships carry a repoint target: a
                # spread partition leader shipping its slice must not drag
                # every follower's prober/leader-hint onto itself
                leader_target=(self._my_target() if self.role == "leader"
                               else ""),
                high_watermarks=self._ship_hwm_json(item)),
                timeout=timeout or self._repl_ack_timeout_s)
            if not reply.ok:
                if reply.leader_epoch > self.epoch:
                    if self.role != "leader" and self._spread_active():
                        # a spread partition leader shipping at a stale
                        # cluster epoch: adopt the fence, SUSPEND (the write
                        # gate refuses until a metadata refresh proves we
                        # still lead our slice), and retry the ship at the
                        # new epoch — never the whole-broker demotion, our
                        # led partitions' tails are authoritative
                        with self._role_lock:
                            if reply.leader_epoch > self.epoch:
                                self.epoch = reply.leader_epoch
                                self._persist_meta("epoch", {"e": self.epoch})
                                self.broker_metrics.repl_epoch.record(
                                    self.epoch)
                        self._kick_meta_refresh()
                        return (f"{target}: cluster epoch raised to "
                                f"{reply.leader_epoch}; re-shipping after "
                                "the metadata refresh")
                    # the peer fenced us: a newer leader exists — this broker
                    # is deposed. Demote NOW (truncate the divergent tail,
                    # rejoin as a follower) instead of retrying forever.
                    self._demote(reply.leader_epoch, target)
                    return (f"{target}: fenced by epoch {reply.leader_epoch} "
                            "(this broker is deposed)")
                return f"{target}: {reply.error}"
            return None
        except Exception as exc:  # noqa: BLE001 — follower down / transport error
            self._repl_channels.pop(target, None)
            return f"{target}: {exc!r}"

    # -- replication: follower side -------------------------------------------------------

    def Replicate(self, request: pb.ReplicateRequest, context) -> pb.ReplicateReply:
        # epoch fence BEFORE ingest (KIP-101 role): a batch from a stale
        # epoch is a deposed leader still shipping — refuse it and tell it
        # the epoch that fenced it. A HIGHER epoch is the live leader: adopt
        # it (persisted, so the fence survives a restart) and remember its
        # address for NOT_LEADER redirects.
        if request.leader_epoch:
            with self._role_lock:
                if request.leader_epoch < self.epoch:
                    return pb.ReplicateReply(
                        ok=False, leader_epoch=self.epoch,
                        error=f"stale leader epoch {request.leader_epoch} "
                              f"(current {self.epoch}) — fenced")
                if request.leader_epoch > self.epoch:
                    was_active_leader = (self.role == "leader"
                                         and bool(self._repl_targets))
                    deposed_epoch = self.epoch
                    self.flight.record("epoch.bump",
                                       old_epoch=self.epoch,
                                       new_epoch=request.leader_epoch,
                                       source=request.leader_target or "ship")
                    self.epoch = request.leader_epoch
                    self._persist_meta("epoch", {"e": self.epoch})
                    self.broker_metrics.repl_epoch.record(self.epoch)
                    if was_active_leader:
                        # split-brain resolution: higher epoch wins — this
                        # replicating leader is deposed by the inbound stream
                        self._demote(request.leader_epoch,
                                     request.leader_target or None,
                                     adopt_epoch=False,
                                     old_epoch=deposed_epoch)
        repoint = False
        if request.leader_epoch:
            with self._role_lock:
                if request.leader_target:
                    self.leader_hint = request.leader_target
                    if (self.role == "follower"
                            and request.leader_target != self._my_target()
                            and request.leader_target != self._follower_of):
                        # cluster repoint: a DIFFERENT broker won promotion —
                        # follow its stream, and aim the liveness prober at
                        # it (fresh streak + bootstrap grace) so the next
                        # failover campaigns about the right leader
                        self._follower_of = request.leader_target
                        repoint = True
        if repoint:
            # outside the role lock: retargeting joins the old prober thread
            # (bounded, but a post-promotion first ship must not serialize
            # behind it)
            self._ensure_prober()
        if request.kind == "barrier":
            # a barrier's hwm map carries no optimistic entries (its records
            # are the manifest, not data): safe to adopt up front
            self._adopt_shipped_hwm(request.high_watermarks)
            return self._apply_compaction_barrier(request)
        with self._replica_lock:
            try:
                known = getattr(self.log, "_topics", {})
                for spec in request.topics:
                    # membership check, not .topic(): inner logs auto-create
                    # unknown topics with 1 partition, which would silently
                    # mis-partition the replica
                    if spec.name not in known:
                        self.log.create_topic(TopicSpec(
                            spec.name, spec.partitions or 1, spec.compacted))
                # idempotent ingest: a re-shipped batch (reply loss, or overlap
                # with catch_up) skips records this log already holds; a record
                # AHEAD of our end offset is a gap — out of sync, loud error.
                # The scan runs on the pb messages directly — LogRecords
                # materialize only for the records actually applied (the
                # native verbatim path then packs them once, off the GIL)
                expected: Dict[tuple, int] = {}
                to_apply = []
                for m in request.records:
                    tp = (m.topic, m.partition)
                    if tp not in expected:
                        expected[tp] = self._applied_end(m.topic, m.partition)
                    if m.offset < expected[tp]:
                        continue  # already applied
                    if m.offset > expected[tp]:
                        return pb.ReplicateReply(
                            ok=False,
                            error=f"gap: leader record {m.topic}"
                                  f"[{m.partition}]@{m.offset} but replica end "
                                  f"is {expected[tp]} — re-sync via catch_up")
                    to_apply.append(msg_to_record(m))
                    expected[tp] += 1
                if to_apply:
                    if self.faults is not None:
                        # corrupt.segment-payload: rot one ingested record's
                        # value — a silent below-hwm replica divergence only
                        # the cross-replica digest compare can see
                        to_apply = self.faults.corrupt_records(
                            "corrupt.segment-payload", to_apply)
                    # verbatim ingest: leader-assigned offsets AND timestamps
                    # preserved, so replica segments converge byte-identically
                    # (the compaction barrier's golden-compare rests on this)
                    self._append_replica(to_apply)
                # carry the idempotency dedup so failover retries hit the cache
                if request.transactional_id and request.txn_seq:
                    dedup = self._txn_dedup.setdefault(
                        request.transactional_id, _TxnDedup())
                    if request.txn_seq > dedup.last_seq:
                        self._ack_seq(
                            request.transactional_id, dedup, request.txn_seq,
                            pb.TxnReply(ok=True, records=list(request.records)),
                            [msg_to_record(m) for m in request.records])
                # adopt the shipped hwm only now that the batch is APPLIED:
                # a quorum-completing ship's optimistic entries vouch for
                # THIS replica holding the records — adopting before a
                # gap-refused ingest would park the read gate above records
                # this replica never got, and the gate is monotonic
                self._adopt_shipped_hwm(request.high_watermarks)
                return pb.ReplicateReply(ok=True)
            except Exception as exc:  # noqa: BLE001
                logger.exception("replica ingest failed")
                return pb.ReplicateReply(ok=False, error=repr(exc))

    def _applied_end(self, topic: str, partition: int) -> int:
        """The applied frontier (FileLog's runs ahead of its durable
        ``end_offset`` while a group-sync round is open) — replica gap checks
        must measure against what is APPLIED, not what is readable."""
        fn = getattr(self.log, "applied_end_offset", None)
        return fn(topic, partition) if fn is not None else \
            self.log.end_offset(topic, partition)

    def _append_replica(self, records, allow_gaps: bool = False):
        """Verbatim append with the inner log's native support, falling back
        to the producer path for third-party LogTransport implementations
        (offsets then re-checked by the caller's gap scan)."""
        verbatim = getattr(self.log, "append_verbatim", None)
        if verbatim is not None:
            out = verbatim(records, allow_gaps=allow_gaps)
            if getattr(self.log, "_native", None) is not None:
                # the follower half of the PR-10 headroom note: shipped
                # batches applied through the native batch path off the GIL
                self._native_ingest_count += 1
                self.broker_metrics.native_ingest_batches.record()
            return out
        if self._replica_producer is None:
            self._replica_producer = self.log.transactional_producer(
                "__replica__")
        self._replica_producer.begin()
        for r in records:
            self._replica_producer.send(r)
        applied = self._replica_producer.commit()
        for got, want in zip(applied, records):
            if (got.offset != want.offset or got.partition != want.partition
                    or got.topic != want.topic):
                raise RuntimeError(
                    f"offset mismatch: applied {got.topic}"
                    f"[{got.partition}]@{got.offset} != leader @{want.offset}")
        return applied

    def _apply_compaction_barrier(self, request: pb.ReplicateRequest
                                  ) -> pb.ReplicateReply:
        """Follower half of the barrier: run the SAME bounded compaction pass
        the leader ran (same upto/now/retention against identical records —
        select_retained is pure, so the generational swap converges
        byte-identically) and verify the outcome against the manifest."""
        import json as _json

        try:
            manifest = _json.loads(request.records[0].value)
            topic = manifest["topic"]
            p = int(manifest["partition"])
            upto = int(manifest["upto"])
            with self._replica_lock:
                have = self._applied_end(topic, p)
                if have < upto:
                    return pb.ReplicateReply(
                        ok=False,
                        error=f"barrier ahead of replica: {topic}[{p}] at "
                              f"{have} < {upto} — retry after the gap heals")
                if not hasattr(self.log, "compact_partition"):
                    return pb.ReplicateReply(
                        ok=False, error=f"{type(self.log).__name__} does not "
                                        "support compaction")
                self.log.compact_partition(
                    topic, p,
                    tombstone_retention_s=float(manifest["retention_s"]),
                    now=float(manifest["now"]), upto_offset=upto)
                mine = self.log.compaction_state(topic, p)["clean_count"]
                want = int(manifest["expect_clean_count"])
                if mine != want:
                    return pb.ReplicateReply(
                        ok=False,
                        error=f"barrier divergence on {topic}[{p}]: replica "
                              f"retained {mine} records, leader {want} — "
                              "wipe and catch_up")
            self.flight.record("compaction.barrier-apply", topic=topic,
                               partition=p, upto=upto, clean_count=mine)
            return pb.ReplicateReply(ok=True)
        except Exception as exc:  # noqa: BLE001
            logger.exception("compaction barrier failed")
            return pb.ReplicateReply(ok=False, error=repr(exc))

    def ReplicationStatus(self, request: pb.ReplicationStatusRequest,
                          context) -> pb.ReplicationStatusReply:
        """Operator view of the in-sync set (the under-replicated-partitions
        metric analog): a follower with in_sync=false needs catch_up."""
        status = self.replication_status()
        return pb.ReplicationStatusReply(
            replicas=[pb.ReplicaStatus(target=t, in_sync=s)
                      for t, s in status["replicas"].items()],
            min_insync=status["min_insync"],
            insync_count=status["insync_count"],
            queue_depth=status["queue_depth"])

    # -- leader epoch, roles & failover ---------------------------------------------------

    def _my_target(self) -> str:
        if self.advertised:
            return self.advertised
        if self.bound_port:
            return f"{self._host}:{self.bound_port}"
        return ""

    def _recover_meta(self) -> None:
        """Rebuild this broker's epoch view from the compacted __broker_meta
        topic (the KIP-101 leader-epoch-checkpoint role): a restarted deposed
        leader must come back already knowing the epoch that fenced it."""
        import json as _json

        known = getattr(self.log, "_topics", {})
        if META_TOPIC not in known:
            return
        try:
            latest = self.log.latest_by_key(META_TOPIC, 0)
            rec = latest.get("epoch")
            if rec is not None:
                self.epoch = max(self.epoch, int(_json.loads(rec.value)["e"]))
            rec = latest.get("epoch_start")
            if rec is not None:
                obj = _json.loads(rec.value)
                if int(obj.get("e", 0)) == self.epoch:
                    self.epoch_start = {
                        t: {int(p): int(off) for p, off in parts.items()}
                        for t, parts in obj.get("starts", {}).items()}
            rec = latest.get("cluster")
            if rec is not None:
                obj = _json.loads(rec.value)
                self._member_epoch = int(obj.get("me", 0))
                self._assign_epoch = int(obj.get("ae", 0))
                members = [str(m) for m in obj.get("m", []) if m]
                if members:
                    self._quorum_peers = members
                self._assignments = {str(k): str(v)
                                     for k, v in obj.get("a", {}).items()}
                # the epoch this view was applied at: a restarted broker
                # whose epoch record outran it (fenced after the last meta
                # persist) comes back SUSPENDED until a refresh lands —
                # never serving a partition the cluster moved while it slept
                self._meta_epoch = int(obj.get("e", 0))
            else:
                self._meta_epoch = self.epoch
            rec = latest.get("vote")
            if rec is not None:
                obj = _json.loads(rec.value)
                e = int(obj.get("e", 0))
                if e:
                    # one vote per epoch survives the restart: a bounced
                    # voter must not grant the SAME epoch to a second
                    # candidate (the double-vote split-brain). Only the
                    # newest vote is compacted-latest, which suffices —
                    # VoteLeader also refuses epochs at or below it.
                    self._voted[e] = str(obj.get("c", ""))
                    self._max_vote_epoch = max(self._max_vote_epoch, e)
        except Exception:  # noqa: BLE001 — a broken meta topic must not
            logger.exception("broker meta recovery failed")  # block startup

    def _persist_meta(self, key: str, obj: dict) -> None:
        """Durably annotate the broker's epoch state. Best-effort like
        __txn_state: a failure only weakens fence persistence across a
        restart, never the live protocol (epochs re-propagate on the next
        Replicate)."""
        import json as _json

        try:
            with self._txn_state_lock:
                known = getattr(self.log, "_topics", {})
                if META_TOPIC not in known:
                    self.log.create_topic(TopicSpec(META_TOPIC, 1,
                                                    compacted=True))
                if self._meta_producer is None:
                    self._meta_producer = self.log.transactional_producer(
                        "__broker_meta_writer__")
                self._meta_producer.begin()
                self._meta_producer.send(LogRecord(
                    topic=META_TOPIC, key=key,
                    value=_json.dumps(obj).encode(), partition=0))
                self._meta_producer.commit()
        except Exception:  # noqa: BLE001
            logger.exception("broker meta persist failed")

    def broker_status(self) -> dict:
        """Role/epoch view (the BrokerStatus RPC payload): what the failover
        prober, the chaos CLI, and a fenced ex-leader's truncation read."""
        with self._role_lock:
            return {"role": self.role, "epoch": self.epoch,
                    "leader_hint": self.leader_hint,
                    "target": self._my_target(),
                    # str partition keys: identical shape whether read
                    # in-process or through the RPC's JSON roundtrip
                    "epoch_start": {t: {str(p): off for p, off in parts.items()}
                                    for t, parts in self.epoch_start.items()},
                    "replicate_to": list(self._repl_targets),
                    # rejoin observability (ISSUE 5 satellite): a fenced
                    # ex-leader is visibly mid-recovery — catch_up progress
                    # plus the epoch-start offsets its truncation last
                    # applied, vs indistinguishable from a healthy follower
                    "catch_up": dict(self.catch_up_state),
                    "last_applied_epoch_start":
                        {t: dict(p) for t, p in
                         self.last_applied_epoch_start.items()},
                    "last_truncation": (dict(self.last_truncation)
                                        if self.last_truncation else None),
                    # quorum-plane observability (chaos.py status reads
                    # these to explain WHY a follower read is servable):
                    # the per-partition quorum-acked frontier this broker
                    # gates reads on, and the vote-cluster shape
                    "high_watermarks": self._hwm_by_topic(),
                    "quorum": self._quorum_view(),
                    # per-partition leadership view (the exactly-one-leader-
                    # per-partition invariant is checkable from status alone:
                    # chaos.py cluster / surgetop read these)
                    "partitions_led": self.partitions_led(),
                    "membership": {"epoch": self._member_epoch,
                                   "members": list(self._quorum_peers)},
                    "assignments": dict(self._assignments),
                    "assign_epoch": self._assign_epoch,
                    "meta_epoch": self._meta_epoch,
                    "handoff_fence": self._handoff_fence,
                    # flight-ring occupancy + dropped-event count: whether
                    # the bounded ring wrapped mid-incident (a truncated
                    # DumpFlight story is tellable from the status alone)
                    "flight": self.flight.stats(),
                    # native-path health (ISSUE 12 satellite): a broker
                    # silently degraded to the Python fallback (stale .so,
                    # flag off) is distinguishable from a native one
                    "native": self.native_status()}

    def native_status(self) -> dict:
        """The ops-plane native row: whether the C++ hot path is live on
        THIS broker, and how often it fell back. ``library`` False with
        ``enabled`` True is the silently-degraded case (unbuilt/stale .so)
        surgetop's `native` column and `chaos.py status` surface."""
        return {"enabled": self._native is not None,
                "library": native_gate.available(),
                # the inner log's PINNED read-decode switch (FileLog ties
                # reads to its own flag); ambient-config logs report the
                # module-level switch
                "decode": (getattr(self.log, "_native", None) is not None
                           if hasattr(self.log, "_native")
                           else native_gate.decode_enabled()),
                "fallbacks": self._native_fallback_count,
                "ingest_batches": self._native_ingest_count}

    def _hwm_by_topic(self) -> Dict[str, Dict[str, int]]:
        out: Dict[str, Dict[str, int]] = {}
        for (t, p), off in sorted(self._hwm.items()):
            out.setdefault(t, {})[str(p)] = off
        return out

    def _applied_ends(self) -> Dict[str, int]:
        """Per-partition applied frontiers ("topic|p" -> end), internal
        topics excluded (self-maintained per side, offsets incomparable) —
        the campaign's log-completeness evidence."""
        out: Dict[str, int] = {}
        for spec in self._topic_specs():
            if spec.name in INTERNAL_TOPICS:
                continue
            for p in range(spec.partitions or 1):
                out[f"{spec.name}|{p}"] = self._applied_end(spec.name, p)
        return out

    def _quorum_others(self) -> list:
        """The quorum peer set minus this broker (configs pass the same
        full cluster list to every member)."""
        me = self._my_target()
        return [p for p in self._quorum_peers if p and p != me]

    def _quorum_view(self) -> dict:
        others = self._quorum_others()
        cluster = len(others) + 1 if others else 1
        return {"peers": others,
                "cluster_size": cluster,
                "majority": cluster // 2 + 1,
                "min_insync_acks": self._repl_min_insync_acks,
                "max_vote_epoch": self._max_vote_epoch}

    # -- dynamic membership & per-partition leadership spread -----------------------------

    def _spread_active(self) -> bool:
        return bool(self._assignments)

    def _leads(self, topic: str, partition: int) -> bool:
        """Whether THIS broker is the write authority for one partition:
        the assigned leader in spread mode, the whole-broker leader
        otherwise (and always for unassigned indices / internal topics)."""
        if topic in INTERNAL_TOPICS:
            return True  # self-maintained per side, never routed
        owner = self._assignments.get(str(partition))
        if owner is None:
            return self.role == "leader"
        return owner == self._my_target()

    def _leads_any(self) -> bool:
        return (self._spread_active()
                and self._my_target() in self._assignments.values())

    def _shippable(self, topic: str, partition: int) -> bool:
        """Whether THIS broker's replication stream owns (topic, p): every
        partition in legacy mode; only the led slice in spread mode —
        another leader's partitions would read as false lag or divergence
        in our resync/verify scans."""
        if not self._spread_active():
            return True
        return self._leads(topic, partition)

    def partitions_led(self) -> list:
        """Sorted partition indices this broker currently leads (the
        BrokerStatus / surgetop / chaos-CLI spread view)."""
        if not self._spread_active():
            return []
        me = self._my_target()
        return sorted((int(k) for k, v in self._assignments.items()
                       if v == me))

    def _write_gate(self, records) -> Optional[pb.TxnReply]:
        """None = this broker may commit the batch; else the refusing reply.
        Caller holds the role lock. Legacy (no assignments): the whole-broker
        role check. Spread mode: every record's partition index must be
        assigned HERE — a miss redirects with that partition's leader as the
        hint (per-partition NOT_LEADER), a mid-move fence or a stale
        metadata view answers an empty hint (hold in place)."""
        if self._handoff_fence:
            # empty hint: the handoff destination is not promoted yet — the
            # client holds in place for the tail window
            return pb.TxnReply(
                ok=False, error_kind="not_leader",
                error="leadership handing off; retry shortly",
                leader_hint="")
        if not self._spread_active():
            if self.role != "leader":
                return pb.TxnReply(
                    ok=False, error_kind="not_leader",
                    error=f"broker is a {self.role}, not the leader",
                    leader_hint=self.leader_hint)
            return None
        me = self._my_target()
        stale = self.epoch > self._meta_epoch
        for m in records:
            if m.topic in INTERNAL_TOPICS:
                continue
            key = str(m.partition)
            owner = self._assignments.get(key)
            if owner is None:
                if self.role != "leader":
                    return pb.TxnReply(
                        ok=False, error_kind="not_leader",
                        error=f"partition {key} is unassigned; the "
                              "coordinator leads it",
                        leader_hint=self.leader_hint)
                continue
            if key in self._part_fence:
                return pb.TxnReply(
                    ok=False, error_kind="not_leader",
                    error=f"partition {key} handing off; retry shortly",
                    leader_hint="")
            if owner != me:
                return pb.TxnReply(
                    ok=False, error_kind="not_leader",
                    error=f"partition {key} is led by {owner}",
                    leader_hint=owner)
            if stale:
                # our epoch outran the metadata view (a fence reply, a
                # higher-epoch ship): the cluster may have MOVED this
                # partition — refuse until a refresh proves we still lead it
                self._kick_meta_refresh()
                return pb.TxnReply(
                    ok=False, error_kind="not_leader",
                    error="cluster metadata stale (epoch "
                          f"{self.epoch} > view {self._meta_epoch}); "
                          "refresh in flight — retry shortly",
                    leader_hint="")
        return None

    def _cluster_meta_view(self) -> dict:
        """The ClusterMeta payload: everything a broker or client needs to
        route — who is in the cluster, who leads which partition index, and
        the epochs guarding both."""
        me = self._my_target()
        return {"coordinator": me if self.role == "leader"
                else self.leader_hint,
                "epoch": self.epoch,
                "member_epoch": self._member_epoch,
                "members": list(self._quorum_peers) or [me],
                "assign_epoch": self._assign_epoch,
                "assignments": dict(self._assignments)}

    def _persist_cluster_meta(self) -> None:
        self._persist_meta("cluster", {
            "me": self._member_epoch, "ae": self._assign_epoch,
            "e": self._meta_epoch, "m": list(self._quorum_peers),
            "a": dict(self._assignments)})

    def _record_cluster_gauges(self) -> None:
        bm = self.broker_metrics
        bm.cluster_member_epoch.record(self._member_epoch)
        bm.cluster_members.record(len(self._quorum_peers))
        bm.cluster_assign_epoch.record(self._assign_epoch)
        bm.cluster_partitions_led.record(len(self.partitions_led()))

    def _mutate_cluster_meta(self, members: Optional[list] = None,
                             assign: Optional[Dict[str, str]] = None,
                             reason: str = "") -> dict:
        """Coordinator-only metadata mutation: rewrite the membership record
        and/or move partition assignments, mint a FRESH cluster epoch (the
        fence that suspends every stale assignment view), persist, broadcast
        to every member. Returns the new view."""
        with self._role_lock:
            if self.role != "leader":
                raise RuntimeError(
                    "cluster metadata mutations run on the coordinator "
                    f"({self.leader_hint or 'unknown'}); this broker is a "
                    f"{self.role}")
            if members is not None:
                self._quorum_peers = [m for m in members if m]
                self._member_epoch += 1
            if assign:
                for key, addr in assign.items():
                    if addr:
                        self._assignments[str(key)] = addr
                    else:
                        self._assignments.pop(str(key), None)
                self._assign_epoch += 1
            self.epoch += 1
            self._meta_epoch = self.epoch
            self._persist_meta("epoch", {"e": self.epoch})
            self._persist_cluster_meta()
            self.broker_metrics.repl_epoch.record(self.epoch)
            # replication targets track the membership: new members are
            # probed in (out-of-sync until proven), removed ones dropped
            targets = self._quorum_others()
            for t in targets:
                if t not in self._repl_target_state:
                    st = _TargetState()
                    st.in_sync = False
                    st.next_probe = time.monotonic() + 0.2
                    # cursor starts at the queue tail's base: a joiner owes
                    # nothing queued before it existed (resync covers holes)
                    with self._repl_cv:
                        st.shipped_index = (self._repl_enq_items
                                            - len(self._repl_queue))
                    self._repl_target_state[t] = st
            for gone in [t for t in self._repl_targets if t not in targets]:
                self._repl_target_state.pop(gone, None)
            self._repl_targets = targets
            view = self._cluster_meta_view()
            self._record_cluster_gauges()
            self.broker_metrics.repl_insync_replicas.record(
                self._insync_count())
        self.flight.record("cluster.meta", reason=reason or "mutate",
                           epoch=view["epoch"],
                           member_epoch=view["member_epoch"],
                           assign_epoch=view["assign_epoch"],
                           members=len(view["members"]))
        self._broadcast_cluster_meta(view)
        if self._repl_targets and self._server is not None:
            self._start_repl_worker()
        return view

    def _start_repl_worker(self) -> None:
        """(Re)arm the replication worker after a role/assignment change —
        safe against the demote-stopped thread still draining, and against
        being called FROM the worker itself (a mid-iteration demotion)."""
        thread = self._repl_thread
        if (thread is not None and thread.is_alive()
                and thread is threading.current_thread()):
            # running ON the worker (ship-fence demotion path): clearing the
            # stop flag keeps this very thread looping — never join(self)
            self._repl_stop = False
            return
        if thread is not None and thread.is_alive() and self._repl_stop:
            with self._repl_cv:
                self._repl_cv.notify_all()
            thread.join(2.0)
        with self._role_lock:
            if self._dead or self._closed:
                return
            if self._repl_thread is not None and self._repl_thread.is_alive():
                if not self._repl_stop:
                    return  # live worker — keep it
                return  # still draining its stop; a later ensure() retries
            self._repl_stop = False
            self._repl_thread = threading.Thread(
                target=self._replication_loop,
                name="surge-log-replication", daemon=True)
            self._repl_thread.start()

    def _broadcast_cluster_meta(self, view: dict) -> None:
        """Best-effort push of the new metadata to every other member (an
        unreachable member learns it from its fence-driven refresh, its
        catch_up, or the next broadcast)."""
        import json as _json

        value = _json.dumps(view).encode()
        delivered = 0
        for peer in self._quorum_others():
            try:
                reply = self._probe_call(
                    peer, "ClusterMeta", pb.TxnRequest, pb.TxnReply,
                    pb.TxnRequest(op="apply", records=[pb.RecordMsg(
                        has_value=True, value=value)]), timeout=2.0)
                if reply.ok:
                    delivered += 1
            except Exception:  # noqa: BLE001 — the member learns it later
                self._drop_probe_transport(peer)
        self.flight.record("cluster.broadcast", delivered=delivered,
                           members=len(self._quorum_others()))

    def _apply_cluster_meta(self, meta: dict, source: str = "") -> bool:
        """Install a coordinator's metadata view (broadcast push or refresh
        pull). Epoch-guarded: stale membership/assignment epochs are refused.
        A partition this broker LED that the view moved elsewhere gets its
        un-quorum-acked tail truncated to the high-watermark — the orphan
        records a dead-then-relit leader may hold must never shadow the new
        leader's timeline (the per-partition KIP-101 rollback)."""
        lost: list = []
        repoint = False
        with self._role_lock:
            member_epoch = int(meta.get("member_epoch", 0))
            assign_epoch = int(meta.get("assign_epoch", 0))
            epoch = int(meta.get("epoch", 0))
            if (member_epoch < self._member_epoch
                    or assign_epoch < self._assign_epoch):
                return False
            if self.role == "leader" and epoch <= self.epoch:
                # we are the authoritative coordinator; only a HIGHER-epoch
                # view (a newer coordinator) may overrule us — and that path
                # runs through the demotion fence, not a bare apply
                return False
            me = self._my_target()
            old = dict(self._assignments)
            members = [str(m) for m in meta.get("members", []) if m]
            self._quorum_peers = members
            self._member_epoch = member_epoch
            self._assignments = {str(k): str(v) for k, v in
                                 (meta.get("assignments") or {}).items()}
            self._assign_epoch = assign_epoch
            if epoch > self.epoch:
                self.epoch = epoch
                self._persist_meta("epoch", {"e": self.epoch})
                self.broker_metrics.repl_epoch.record(self.epoch)
            self._meta_epoch = max(self._meta_epoch, epoch)
            coordinator = str(meta.get("coordinator", ""))
            if coordinator and coordinator != me and self.role != "leader":
                self.leader_hint = coordinator
                if self._follower_of != coordinator:
                    self._follower_of = coordinator
                    repoint = True
            self._persist_cluster_meta()
            self._record_cluster_gauges()
            lost = [key for key, owner in old.items()
                    if owner == me
                    and self._assignments.get(key) not in (me, None)]
        self.flight.record("cluster.meta-apply", source=source or "peer",
                           epoch=epoch, member_epoch=member_epoch,
                           assign_epoch=assign_epoch,
                           lost=lost if lost else None)
        for key in lost:
            self._truncate_partition_to_hwm(int(key))
        if repoint:
            self._ensure_prober()
        self._ensure_spread_replication()
        return True

    def _truncate_partition_to_hwm(self, partition: int) -> None:
        """Roll one partition index back to its quorum-acked frontier on
        every topic: records beyond the high-watermark were never provably
        acked, and the partition's NEW leader will re-ship anything we
        dropped that actually survived (gap-checked resync)."""
        fn = getattr(self.log, "truncate_partition", None)
        if fn is None:
            return
        truncated = 0
        for spec in self._topic_specs():
            if spec.name in INTERNAL_TOPICS or \
                    partition >= (spec.partitions or 1):
                continue
            hwm = self._hwm.get((spec.name, partition))
            if hwm is None:
                continue
            if self._applied_end(spec.name, partition) > hwm:
                truncated += fn(spec.name, partition, hwm)
        if truncated:
            self.metrics.failover_truncated_records.record(truncated)
            self.flight.record("cluster.truncate", partition=partition,
                               records=truncated)
            logger.warning(
                "partition %d moved away: truncated %d record(s) past the "
                "high-watermark (un-quorum-acked orphan tail)",
                partition, truncated)

    def _ensure_spread_replication(self) -> None:
        """A spread partition leader ships its commits to every other member
        exactly like the coordinator does — start/retarget its replication
        worker whenever the assignment view changes."""
        start = False
        with self._role_lock:
            if self.role == "leader" or self._dead or self._closed:
                return  # the coordinator path owns its own targets
            if not self._leads_any():
                self._repl_targets = []
                return
            targets = self._quorum_others()
            for t in targets:
                if t not in self._repl_target_state:
                    st = _TargetState()
                    with self._repl_cv:
                        st.shipped_index = (self._repl_enq_items
                                            - len(self._repl_queue))
                    self._repl_target_state[t] = st
            self._repl_targets = targets
            start = bool(targets) and self._server is not None
        if start:
            self._start_repl_worker()

    def _kick_meta_refresh(self) -> None:
        """Rate-limited async metadata refresh (the suspended-write-gate
        path): at most one in flight, at most ~2/s."""
        now = time.monotonic()
        if now < self._meta_refresh_after:
            return
        if not self._meta_refresh_lock.acquire(blocking=False):
            return
        self._meta_refresh_after = now + 0.5
        threading.Thread(target=self._refresh_cluster_meta_locked,
                         name="surge-cluster-meta-refresh",
                         daemon=True).start()

    def _refresh_cluster_meta_locked(self) -> None:
        try:
            self._refresh_cluster_meta()
        finally:
            self._meta_refresh_lock.release()

    def _refresh_cluster_meta(self) -> bool:
        """Pull the current metadata view from the coordinator (falling back
        to any member) and install it."""
        import json as _json

        sources = [self.leader_hint] + self._quorum_others()
        seen = set()
        for src in sources:
            if not src or src in seen or src == self._my_target():
                continue
            seen.add(src)
            try:
                reply = self._probe_call(src, "ClusterMeta", pb.TxnRequest,
                                         pb.TxnReply,
                                         pb.TxnRequest(op="status"),
                                         timeout=2.0)
                if not reply.ok or not reply.records:
                    continue
                meta = _json.loads(reply.records[0].value)
            except Exception:  # noqa: BLE001 — try the next member
                self._drop_probe_transport(src)
                continue
            # only a view from the coordinator itself (or one at least as
            # fresh as our suspension epoch) can prove our map current
            if self._apply_cluster_meta(meta, source=src):
                return True
        return False

    def ClusterMeta(self, request: pb.TxnRequest, context) -> pb.TxnReply:
        """The dynamic-membership / partition-spread RPC (METHODS table)."""
        import json as _json

        obj = {}
        if request.records and request.records[0].has_value:
            try:
                obj = _json.loads(request.records[0].value or b"{}")
            except ValueError:
                return pb.TxnReply(ok=False, error_kind="state",
                                   error="malformed ClusterMeta payload")

        def ok(view: dict) -> pb.TxnReply:
            return pb.TxnReply(ok=True, records=[pb.RecordMsg(
                has_key=True, key="cluster", has_value=True,
                value=_json.dumps(view).encode())])

        op = request.op or "status"
        try:
            if op == "status":
                with self._role_lock:
                    return ok(self._cluster_meta_view())
            if op == "apply":
                applied = self._apply_cluster_meta(obj, source="rpc")
                with self._role_lock:
                    view = self._cluster_meta_view()
                view["applied"] = applied
                return ok(view)
            # coordinator-only mutations below
            if self.role != "leader":
                return pb.TxnReply(
                    ok=False, error_kind="not_leader",
                    error=f"ClusterMeta {op!r} runs on the coordinator",
                    leader_hint=self.leader_hint)
            if op == "add":
                return ok(self._add_broker(str(obj.get("addr", ""))))
            if op == "remove":
                return ok(self._remove_broker(str(obj.get("addr", ""))))
            if op == "assign":
                key = str(obj.get("partition", ""))
                to = str(obj.get("to", ""))
                if not key or not to:
                    return pb.TxnReply(ok=False, error_kind="state",
                                       error='assign needs {"partition", '
                                             '"to"}')
                if to not in self._quorum_peers:
                    return pb.TxnReply(ok=False, error_kind="state",
                                       error=f"{to} is not a member")
                return ok(self._mutate_cluster_meta(assign={key: to},
                                                    reason="assign"))
            if op == "spread":
                return ok(self._spread_partitions(
                    int(obj.get("partitions", 0))))
            return pb.TxnReply(ok=False, error_kind="state",
                               error=f"unknown ClusterMeta op {op!r}")
        except Exception as exc:  # noqa: BLE001 — operator gets it back
            logger.exception("ClusterMeta %s failed", op)
            return pb.TxnReply(ok=False, error_kind="other", error=repr(exc))

    def _known_partition_count(self) -> int:
        count = 0
        for spec in self._topic_specs():
            if spec.name in INTERNAL_TOPICS:
                continue
            count = max(count, spec.partitions or 1)
        return count

    def _spread_partitions(self, partitions: int = 0) -> dict:
        """Round-robin every partition index across the membership (the
        initial leadership spread; later skew is the autobalancer's job).
        Members are ordered by current lead count so repeated calls stay
        stable."""
        count = partitions or self._known_partition_count()
        if count <= 0:
            raise RuntimeError("no topics known and no partition count "
                               "given; create topics first or pass "
                               '{"partitions": N}')
        members = self._spread_members()
        if not members:
            raise RuntimeError("no membership configured "
                               "(quorum_peers / AddBroker first)")
        assign = {}
        for p in range(count):
            key = str(p)
            if self._assignments.get(key) in members:
                continue  # already placed on a live member: keep it
            members.sort(key=lambda m: self._lead_counts(assign).get(m, 0))
            assign[key] = members[0]
        if not assign:
            with self._role_lock:
                return self._cluster_meta_view()
        return self._mutate_cluster_meta(assign=assign, reason="spread")

    def _spread_members(self) -> list:
        """Members eligible to lead partitions: self plus every in-sync
        target (an out-of-sync member must not be handed leadership)."""
        me = self._my_target()
        members = [me]
        for t in self._quorum_others():
            st = self._repl_target_state.get(t)
            if st is None or st.in_sync:
                members.append(t)
        return members

    def _lead_counts(self, extra: Optional[Dict[str, str]] = None
                     ) -> Dict[str, int]:
        counts: Dict[str, int] = {}
        merged = dict(self._assignments)
        if extra:
            merged.update(extra)
        for addr in merged.values():
            counts[addr] = counts.get(addr, 0) + 1
        return counts

    def _add_broker(self, addr: str) -> dict:
        """AddBroker: admit a caught-up broker into the membership. The
        joiner must already be within the auto-resync cap of this
        coordinator (catch_up first — the PR-7 slice lane), so it never
        counts toward a quorum it could not honor."""
        if not addr:
            raise RuntimeError('add needs {"addr": "host:port"}')
        if addr in self._quorum_peers:
            with self._role_lock:
                return self._cluster_meta_view()
        # reachability + catch-up proof: the joiner's applied ends must be
        # within the auto-resync cap (the leader can close that much itself)
        lag = 0
        try:
            for spec in self._topic_specs():
                if spec.name in INTERNAL_TOPICS:
                    continue
                for p in range(spec.partitions or 1):
                    theirs = self._remote_end_offset(addr, spec.name, p)
                    lag += max(0, self._applied_end(spec.name, p) - theirs)
        except Exception as exc:  # noqa: BLE001 — joiner not serving yet
            self._drop_probe_transport(addr)
            raise RuntimeError(
                f"{addr} is unreachable — start it and run catch_up "
                f"before AddBroker ({exc!r})") from exc
        cap = max(self._repl_auto_resync_cap, 0)
        if cap and lag > cap:
            raise RuntimeError(
                f"{addr} lags {lag} records (> auto-resync cap {cap}); "
                "run catch_up before AddBroker")
        members = list(self._quorum_peers)
        if self._my_target() not in members:
            members.append(self._my_target())
        members.append(addr)
        view = self._mutate_cluster_meta(members=members, reason="add")
        self.flight.record("cluster.add", addr=addr, lag=lag,
                           member_epoch=view["member_epoch"])
        return view

    def _remove_broker(self, addr: str) -> dict:
        """RemoveBroker: retire a member. Its led partitions fail over to
        the surviving member holding the most log for each (the same
        up-to-date posture the vote layer enforces)."""
        if not addr:
            raise RuntimeError('remove needs {"addr": "host:port"}')
        if addr == self._my_target():
            raise RuntimeError("the coordinator cannot remove itself; "
                               "hand off leadership first")
        if addr not in self._quorum_peers:
            with self._role_lock:
                return self._cluster_meta_view()
        members = [m for m in self._quorum_peers if m != addr]
        reassign = self._pick_heirs(
            [k for k, v in self._assignments.items() if v == addr],
            exclude=addr)
        view = self._mutate_cluster_meta(members=members, assign=reassign,
                                         reason="remove")
        if reassign:
            self.broker_metrics.cluster_reassignments.record(len(reassign))
        self.flight.record("cluster.remove", addr=addr,
                           reassigned=sorted(reassign) if reassign else None,
                           member_epoch=view["member_epoch"])
        # best-effort: tell the removed broker directly so it stops serving
        # (it is no longer in the membership the broadcast walks)
        import json as _json

        try:
            self._probe_call(addr, "ClusterMeta", pb.TxnRequest, pb.TxnReply,
                             pb.TxnRequest(op="apply", records=[pb.RecordMsg(
                                 has_value=True,
                                 value=_json.dumps(view).encode())]),
                             timeout=2.0)
        except Exception:  # noqa: BLE001 — it learns via the fence instead
            self._drop_probe_transport(addr)
        return view

    def _pick_heirs(self, keys: list, exclude: str) -> Dict[str, str]:
        """For each partition index, pick the successor leader: the eligible
        member holding the MOST applied log for it (ties to the least-loaded
        member) — an acked commit lives on the quorum, and the longest log
        among the survivors provably holds every quorum-acked record."""
        heirs: Dict[str, str] = {}
        candidates = [m for m in self._spread_members() if m != exclude]
        if not candidates:
            return heirs
        me = self._my_target()
        for key in keys:
            p = int(key)
            best, best_end = None, -1
            counts = self._lead_counts(heirs)
            for member in sorted(candidates,
                                 key=lambda m: counts.get(m, 0)):
                end = 0
                for spec in self._topic_specs():
                    if spec.name in INTERNAL_TOPICS or \
                            p >= (spec.partitions or 1):
                        continue
                    try:
                        end += (self._applied_end(spec.name, p)
                                if member == me else
                                self._remote_end_offset(member, spec.name, p))
                    except Exception:  # noqa: BLE001 — unreachable heir
                        self._drop_probe_transport(member)
                        end = -1
                        break
                if end > best_end:
                    best, best_end = member, end
            if best is not None:
                heirs[key] = best
        return heirs

    def _maybe_reassign_failed(self, now: float) -> None:
        """Coordinator sweep (replication-worker cadence, ~1/s): a member
        whose ships have been failing past the reassign grace — over and
        above the ISR drop — loses its led partitions to the surviving
        members. This is the per-partition failover leg of self-healing:
        broker death moves ITS slice, not the whole cluster."""
        if self.role != "leader" or not self._spread_active():
            return
        if now < self._next_reassign_check:
            return
        self._next_reassign_check = now + 1.0
        me = self._my_target()
        for addr in set(self._assignments.values()):
            if addr == me:
                continue
            st = self._repl_target_state.get(addr)
            if st is None:
                continue
            if st.failing_since is None or st.in_sync:
                # the ISR machinery only observes SHIP failures — an idle
                # cluster would never notice a dead partition leader. Probe
                # liveness directly on this sweep's cadence (short timeout:
                # this runs on the replication worker; a blackholed member
                # must not stall the ship loop); a false alarm only costs a
                # planned move, never correctness. The probe tracks its OWN
                # clock — it must never reset the ship path's
                # ``failing_since``, or a member whose data plane fails
                # while its control plane answers would dodge the ISR drop
                # forever.
                try:
                    self._remote_broker_status(addr, timeout=0.75)
                    st.probe_failing_since = None
                    continue
                except Exception:  # noqa: BLE001 — unreachable member
                    self._drop_probe_transport(addr)
                    if st.probe_failing_since is None:
                        st.probe_failing_since = now
                down_since = st.probe_failing_since
            else:
                down_since = st.failing_since
            if down_since is None or now - down_since \
                    < self._reassign_grace_s:
                continue
            keys = [k for k, v in self._assignments.items() if v == addr]
            heirs = self._pick_heirs(keys, exclude=addr)
            if not heirs:
                continue
            self.broker_metrics.cluster_reassignments.record(len(heirs))
            self.flight.record("cluster.reassign", addr=addr,
                               partitions=sorted(heirs),
                               reason="member-failed",
                               failing_s=round(now - down_since, 2))
            logger.error(
                "member %s failing for %.1fs: reassigning its partitions "
                "%s", addr, now - down_since, sorted(heirs.items()))
            try:
                self._mutate_cluster_meta(assign=heirs,
                                          reason="member-failed")
            except Exception:  # noqa: BLE001 — retried next sweep
                logger.exception("failed-member reassignment failed")

    def promote(self, replicate_to: Optional[list] = None,
                at_epoch: Optional[int] = None) -> dict:
        """Follower → leader promotion (admin PromoteFollower RPC, the
        leader-death prober, or a won campaign). Bumps the epoch past every
        one this broker has seen — or mints exactly ``at_epoch``, the epoch a
        quorum campaign collected its votes FOR (votes are per-epoch; a
        higher self-chosen epoch would be one nobody granted) — records the
        EPOCH-START offsets — the truncation floor a fenced ex-leader rolls
        its divergent tail back to — persists both, and starts replicating to
        ``replicate_to`` (default: every quorum peer when configured, else
        the old leader, so the pair inverts; each re-joins through the
        fence → truncate → catch_up → ISR-rejoin path). Idempotent on an
        existing leader."""
        with self._role_lock:
            if self.role == "leader":
                return self.broker_status()
            self._adopt_leader_epoch()
            if at_epoch is not None and self.epoch >= at_epoch:
                # the campaign's mandate went stale between the vote count
                # and this lock: another winner's epoch already reached us.
                # Minting max(seen)+1 here would be an epoch NOBODY voted
                # for — it would fence the legitimately elected leader and
                # get its quorum-acked tail truncated. Abort; the caller
                # stands down and the prober re-arms.
                raise RuntimeError(
                    f"stale campaign mandate: voted epoch {at_epoch} but "
                    f"epoch {self.epoch} already seen")
            # floor of 2: every ACTIVE leader initializes at epoch 1, so a
            # follower that never learned its leader's epoch (leader down
            # since before this follower's first probe) must still mint an
            # epoch that FENCES it — promoting 0 -> 1 would collide, and
            # equal epochs pass every fence (silent two-leader split brain)
            self.epoch = max(self.epoch + 1, 2,
                             at_epoch if at_epoch is not None else 0)
            starts: Dict[str, Dict[int, int]] = {}
            for spec in self._topic_specs():
                if spec.name in INTERNAL_TOPICS:
                    continue
                starts[spec.name] = {
                    p: self._applied_end(spec.name, p)
                    for p in range(spec.partitions or 1)}
            self.epoch_start = starts
            self._persist_meta("epoch", {"e": self.epoch})
            self._persist_meta("epoch_start",
                               {"e": self.epoch,
                                "starts": {t: {str(p): off
                                               for p, off in parts.items()}
                                           for t, parts in starts.items()}})
            if replicate_to is not None:
                targets = list(replicate_to)
            elif self._quorum_peers:
                # cluster promotion: replicate to EVERY peer (the deposed
                # leader included — it re-joins through the fence path)
                targets = self._quorum_others()
            else:
                targets = [self._follower_of] if self._follower_of else []
            self._repl_targets = [t for t in targets if t]
            for t in self._repl_targets:
                st = self._repl_target_state.setdefault(t, _TargetState())
                # presumed dead until a probe proves otherwise: commits must
                # not block the isr-timeout on a corpse
                st.in_sync = False
                st.failing_since = None
                st.next_probe = time.monotonic() + 1.0
            self.role = "leader"
            self.leader_hint = self._my_target()
            if self._leader_prober is not None:
                self._leader_prober.stop()
                self._leader_prober = None
            if self._repl_targets and self._server is not None and (
                    self._repl_thread is None
                    or not self._repl_thread.is_alive()):
                self._repl_stop = False
                self._repl_thread = threading.Thread(
                    target=self._replication_loop,
                    name="surge-log-replication", daemon=True)
                self._repl_thread.start()
            logger.warning("PROMOTED to leader at epoch %d (epoch-start %s)",
                           self.epoch,
                           {t: p for t, p in list(starts.items())[:4]})
            self.metrics.failover_promotions.record()
            self.broker_metrics.repl_epoch.record(self.epoch)
            self.broker_metrics.repl_insync_replicas.record(
                self._insync_count())
            self._flight_first_ack = True
            self.flight.record(
                "role.promote", epoch=self.epoch,
                replicate_to=list(self._repl_targets),
                epoch_start={t: {str(p): off for p, off in parts.items()}
                             for t, parts in list(starts.items())[:8]})
            spread = self._spread_active()
            if spread:
                # claim coordinatorship of the metadata plane: re-stamp the
                # (unchanged) membership/assignment view at OUR epoch, so
                # partition leaders suspended by the election fence resume
                # the moment the broadcast (or their refresh) lands
                self._meta_epoch = self.epoch
                self._persist_cluster_meta()
                self._record_cluster_gauges()
                view = self._cluster_meta_view()
            status = self.broker_status()
        if spread:
            self._broadcast_cluster_meta(view)
        return status

    def _demote(self, new_epoch: int, fencer: Optional[str],
                adopt_epoch: bool = True,
                old_epoch: Optional[int] = None) -> None:
        """A higher epoch fenced this leader: stop writing, fail the queue,
        truncate the divergent unreplicated tail to the new leader's
        epoch-start offsets (KIP-101), wipe the local dedup view and re-pull
        log + dedup from the new leader (catch_up), then serve as a follower.
        Never raises — a failing step leaves the broker demoted-but-behind,
        which the new leader's rejoin probe (or operator catch_up) heals.
        ``old_epoch``: the DEPOSED epoch, for callers (Replicate's inbound
        split-brain path) that already adopted the fencing epoch before
        demoting — without it the fence would log/record N deposed by N."""
        with self._role_lock:
            if self._demoting:
                return
            self._demoting = True
        try:
            with self._role_lock:
                deposed = old_epoch if old_epoch is not None else self.epoch
                logger.error(
                    "FENCED: leader epoch %d deposed by epoch %d (%s); "
                    "demoting to follower", deposed, new_epoch,
                    fencer or "unknown peer")
                self.flight.record("role.fence", old_epoch=deposed,
                                   new_epoch=new_epoch,
                                   fencer=fencer or "unknown")
                if adopt_epoch and new_epoch > self.epoch:
                    self.epoch = new_epoch
                    self._persist_meta("epoch", {"e": self.epoch})
                self.role = "follower"
                if fencer:
                    self.leader_hint = fencer
                self._repl_targets = []
                self._repl_stop = True
                # fail every queued item: their waiters answer retriable and
                # the clients' redirect/reopen ladder moves to the new leader
                with self._repl_cv:
                    stranded, self._repl_queue = self._repl_queue, []
                    self._repl_cv.notify_all()
                self._repl_pending.clear()
                for it in stranded:
                    it.error = f"fenced by epoch {new_epoch}"
                    it.done.set()
            self.metrics.failover_fencings.record()
            self.broker_metrics.repl_epoch.record(self.epoch)
            if fencer:
                self._follower_of = fencer
                self._truncate_to_leader(fencer)
                # a deposed leader re-enters the failover rotation: probe the
                # broker that fenced it, so the NEXT leader death finds every
                # surviving broker campaigning (not just the original pair)
                self._ensure_prober()
        finally:
            with self._role_lock:
                self._demoting = False
        # spread mode: a deposed COORDINATOR usually still leads its slice —
        # restart the (demote-stopped) replication worker for it, and pull a
        # fresh metadata view from the new coordinator
        if self._spread_active():
            self._ensure_spread_replication()
            self._kick_meta_refresh()

    def _truncate_to_leader(self, leader_target: str) -> None:
        """KIP-101 divergence repair: roll every partition back to the new
        leader's epoch-start offset (the shared prefix — the follower held
        exactly that much when it promoted, and this broker holds at least as
        much), then re-pull records + dedup from the leader."""
        try:
            status = self._remote_broker_status(leader_target)
            starts = status.get("epoch_start", {})
            truncated = 0
            fn = getattr(self.log, "truncate_partition", None)
            for topic, parts in starts.items():
                if topic in INTERNAL_TOPICS:
                    continue
                for p, start in parts.items():
                    p = int(p)
                    if self._spread_active() and self._leads(topic, p):
                        # our led slice's tail is authoritative — the new
                        # COORDINATOR's epoch-start says nothing about it
                        continue
                    mine = self._applied_end(topic, p)
                    if mine > int(start) and fn is not None:
                        truncated += fn(topic, p, int(start))
            # observable rejoin state (BrokerStatus): which epoch-start this
            # fenced ex-leader rolled back to, and how much it dropped
            self.last_applied_epoch_start = {
                t: {str(p): int(off) for p, off in parts.items()}
                for t, parts in starts.items()}
            self.last_truncation = {"records": truncated,
                                    "epoch": int(status.get("epoch", 0)),
                                    "leader": leader_target,
                                    "wall": time.time()}
            self.flight.record("log.truncate", records=truncated,
                               leader=leader_target,
                               epoch=int(status.get("epoch", 0)))
            if truncated:
                logger.warning(
                    "truncated %d divergent unreplicated record(s) to the "
                    "new leader's epoch-start offsets", truncated)
                self.metrics.failover_truncated_records.record(truncated)
            # the truncated seqs' dedup entries point at dropped records; the
            # new leader's table is authoritative — rebuild from it
            with self._replica_lock:
                self._txn_dedup.clear()
                with self._txn_state_lock:
                    # fresh _TxnDedup objects restart persist_gen at 0: a
                    # surviving high-water here would silently drop every
                    # later __txn_state write until the counter caught up
                    self._txn_persist_gens.clear()
            self.catch_up(leader_target)
        except Exception:  # noqa: BLE001 — demoted-but-behind is recoverable
            logger.exception(
                "post-fence truncation/catch-up from %s failed; this "
                "follower stays behind until the leader's rejoin probe or an "
                "operator catch_up heals it", leader_target)

    def _remote_broker_status(self, target: str,
                              timeout: float = 2.0) -> dict:
        import json as _json

        reply = self._probe_stub(target, "BrokerStatus",
                                 pb.ListTopicsRequest, pb.TxnReply)(
            pb.ListTopicsRequest(), timeout=timeout)
        if not reply.ok or not reply.records:
            raise RuntimeError(f"BrokerStatus on {target} failed: "
                               f"{reply.error}")
        return _json.loads(reply.records[0].value)

    def _adopt_leader_epoch(self) -> None:
        """Best-effort raise of this follower's epoch view to its leader's
        (normally the Replicate stream carries it; a follower that never saw
        a batch would otherwise promote to an epoch EQUAL to the live
        leader's, and the fence could not tell them apart). Unreachable
        leader — the usual promotion trigger — keeps the known epoch."""
        if not self._follower_of:
            return
        try:
            status = self._remote_broker_status(self._follower_of)
            remote = int(status.get("epoch", 0))
            if remote > self.epoch:
                self.epoch = remote
                self._persist_meta("epoch", {"e": self.epoch})
        except Exception:  # noqa: BLE001 — leader dead: promote past known
            # drop the channel: a follower starting BEFORE its leader would
            # otherwise cache a connect-backoff channel here that fails
            # every later probe-stub RPC to the leader (votes, metadata
            # refreshes, per-partition handoff flips) until gRPC's backoff
            # deigns to reconnect
            self._drop_probe_transport(self._follower_of)

    def _confirm_leadership(self) -> None:
        """Split-brain guard at start (KIP-279 flavor): a restarting broker
        configured as leader asks its replication targets whether a higher
        epoch exists BEFORE serving writes — a deposed leader that crashed
        and came back must not accept commits it would later truncate.
        Unreachable targets are presumed dead followers (serve on)."""
        for target in list(self._repl_targets):
            try:
                status = self._remote_broker_status(target)
            except Exception:  # noqa: BLE001 — dead follower: fine
                continue
            if int(status.get("epoch", 0)) > self.epoch:
                self._demote(int(status["epoch"]),
                             status.get("target") or target)
                return

    def kill(self) -> None:
        """Hard-stop (the fault plane's crash action): close the socket NOW,
        no grace — in-flight calls answer UNAVAILABLE, exactly what a killed
        process looks like to clients. The inner log is left as-is (a crash
        does not flush)."""
        self._dead = True
        self.flight.record("broker.kill", role=self.role, epoch=self.epoch)
        server, self._server = self._server, None
        #: threading.Event set once the socket is fully closed (grpc's stop
        #: is non-blocking, so this is safe even from a handler thread —
        #: never WAIT on it from one, the in-flight call is part of what it
        #: tracks). Tests wait on it before rebinding the port.
        self.kill_done = server.stop(0) if server is not None else None
        with self._repl_cv:
            self._repl_stop = True
            self._repl_cv.notify_all()
        if self._leader_prober is not None:
            self._leader_prober.stop()
            self._leader_prober = None
        self._stop_metrics_server()
        if self._flight_dump_dir:
            # the black-box survives the "crash": the recorder's ring is
            # dumped where a post-mortem (or the timeline merge) finds it
            import os as _os

            self.flight.dump_to(_os.path.join(
                self._flight_dump_dir,
                f"flight-{self.bound_port or id(self)}.json"))

    def _stop_metrics_server(self) -> None:
        server, self._metrics_server = self._metrics_server, None
        if server is not None:
            try:
                server.stop()
            except Exception:  # noqa: BLE001 — teardown best-effort
                pass

    # -- broker admin RPCs ----------------------------------------------------------------

    def BrokerStatus(self, request: pb.ListTopicsRequest,
                     context) -> pb.TxnReply:
        import json as _json

        return pb.TxnReply(ok=True, records=[pb.RecordMsg(
            has_key=True, key="status", has_value=True,
            value=_json.dumps(self.broker_status()).encode())])

    def metrics_text(self) -> str:
        """The broker's OpenMetrics payload: its registry (journal/txn/
        replication instruments + failover counters) plus the live
        per-follower lag collector — what the scrape port serves and the
        GetMetricsText RPC ships."""
        from surge_tpu.metrics.broker import broker_collector
        from surge_tpu.metrics.exposition import render_openmetrics

        return render_openmetrics(self.broker_metrics.registry,
                                  collectors=[broker_collector(self)])

    def GetMetricsText(self, request: pb.ListTopicsRequest,
                       context) -> pb.TxnReply:
        try:
            text = self.metrics_text()
        except Exception as exc:  # noqa: BLE001 — a scrape must answer
            return pb.TxnReply(ok=False, error_kind="other", error=repr(exc))
        return pb.TxnReply(ok=True, records=[pb.RecordMsg(
            has_key=True, key="metrics", has_value=True,
            value=text.encode())])

    def DumpFlight(self, request: pb.ReadRequest, context) -> pb.TxnReply:
        import json as _json

        last = request.max_records if request.has_max else None
        return pb.TxnReply(ok=True, records=[pb.RecordMsg(
            has_key=True, key="flight", has_value=True,
            value=_json.dumps(self.flight.dump(last)).encode())])

    def DumpTraces(self, request: pb.ReadRequest, context) -> pb.TxnReply:
        """The tail-kept trace ring's merge-ready dump (DumpFlight's trace
        twin). An untraced broker (no tracer / tail sampling off) answers a
        state error rather than an empty envelope — "nothing kept" and
        "nothing could ever be kept" must be tellable apart."""
        import json as _json

        if self.trace_ring is None:
            return pb.TxnReply(
                ok=False, error_kind="state",
                error="no trace ring (broker has no tracer, or "
                      "surge.trace.tail.enabled=false)")
        last = request.max_records if request.has_max else None
        return pb.TxnReply(ok=True, records=[pb.RecordMsg(
            has_key=True, key="traces", has_value=True,
            value=_json.dumps(self.trace_ring.dump(last)).encode())])

    def PartitionDigest(self, request: pb.ReadRequest,
                        context) -> pb.TxnReply:
        """Chained per-partition digest (surge_tpu.log.digest): the
        consistency auditor compares leader vs follower answers at the same
        ``upto`` (ReadRequest.from_offset; 0 = this broker's durable end)
        below the high-watermark without shipping records. Incremental: the
        backend folds only the records appended since its last answer."""
        import json as _json

        try:
            upto = request.from_offset if request.from_offset > 0 else None
            digest = self.log.partition_digest(request.topic,
                                               request.partition, upto)
        except Exception as exc:  # noqa: BLE001 — an audit probe must answer
            return pb.TxnReply(ok=False, error_kind="other", error=repr(exc))
        return pb.TxnReply(ok=True, records=[pb.RecordMsg(
            has_key=True, key="digest", has_value=True,
            value=_json.dumps(digest).encode())])

    def PromoteFollower(self, request: pb.TxnRequest, context) -> pb.TxnReply:
        import json as _json

        try:
            replicate_to = None
            if request.records and request.records[0].has_value:
                obj = _json.loads(request.records[0].value or b"{}")
                replicate_to = obj.get("replicate_to")
            status = self.promote(replicate_to)
            return pb.TxnReply(ok=True, records=[pb.RecordMsg(
                has_key=True, key="status", has_value=True,
                value=_json.dumps(status).encode())])
        except Exception as exc:  # noqa: BLE001 — operator gets it back
            logger.exception("promotion failed")
            return pb.TxnReply(ok=False, error_kind="other", error=repr(exc))

    def VoteLeader(self, request: pb.TxnRequest, context) -> pb.TxnReply:
        """One quorum-promotion vote (txn_seq = the CANDIDATE epoch). Granted
        only when ALL of: this broker is not itself a live leader, the
        candidate epoch exceeds every epoch this broker has seen or voted,
        this epoch's one vote is unspent, and the presumed-dead leader is
        unreachable from THIS broker's vantage too (the prober's verdict
        when it has one, else a direct probe) — a candidate that merely lost
        its own link to the leader fails that last check on every healthy
        peer and can never reach a majority. Votes persist in __broker_meta:
        a bounced voter cannot double-vote."""
        import json as _json

        self.broker_metrics.quorum_vote_requests.record()
        obj = {}
        if request.records and request.records[0].has_value:
            try:
                obj = _json.loads(request.records[0].value or b"{}")
            except ValueError:
                pass
        candidate = str(obj.get("candidate", ""))
        presumed_dead = str(obj.get("leader", ""))
        cand_epoch = int(request.txn_seq)

        def answer(granted: bool, reason: str,
                   leader_alive: bool = False, hint: str = "") -> pb.TxnReply:
            self.flight.record("quorum.vote", candidate=candidate,
                               epoch=cand_epoch, granted=granted,
                               reason=reason)
            return pb.TxnReply(ok=True, records=[pb.RecordMsg(
                has_key=True, key="vote", has_value=True,
                value=_json.dumps({
                    "granted": granted, "reason": reason,
                    "epoch": max(self.epoch, self._max_vote_epoch),
                    "role": self.role, "leader_alive": leader_alive,
                    "leader_hint": hint or self.leader_hint}).encode())])

        if not candidate or cand_epoch <= 0:
            return answer(False, "malformed")
        with self._role_lock:
            if self.role == "leader":
                # an ACKING leader answering RPCs is alive by construction —
                # the candidate's liveness view is wrong, not ours
                return answer(False, "voter-is-leader", leader_alive=True,
                              hint=self._my_target())
            already = self._voted.get(cand_epoch)
            if already is not None:
                if already == candidate:
                    # idempotent re-grant: the candidate's first reply was
                    # lost — our vote at this epoch is already its
                    return answer(True, "granted")
                return answer(False, "already-voted")
            if cand_epoch <= max(self.epoch, self._max_vote_epoch):
                return answer(False, "stale-epoch")
            # up-to-date check (the Raft §5.4.1 safety role): deny a
            # candidate whose log is BEHIND this voter's. Every quorum-acked
            # commit lives on at least one member of any majority, so with
            # this check the elected leader provably holds all of them — a
            # freshly-restarted broker still mid-catch-up cannot win over a
            # complete peer and silently drop acked records.
            cand_ends = obj.get("ends")
            if isinstance(cand_ends, dict):
                for key, mine in self._applied_ends().items():
                    if mine > int(cand_ends.get(key, 0)):
                        return answer(False, "log-behind")
        # leader-liveness double-check OUTSIDE the role lock (network probe):
        # our own prober's standing verdict when it watches that address,
        # else one direct probe, budgeted under the candidate's vote timeout
        if presumed_dead and presumed_dead != candidate:
            prober = self._leader_prober
            if (prober is not None and prober.target == presumed_dead
                    and prober.declared_dead):
                pass  # we independently concluded dead — grant path
            else:
                try:
                    # FRESH channel for the verdict: a cached probe channel
                    # that failed while the leader was booting sits in gRPC
                    # connect-backoff and would report a LIVE leader dead —
                    # the exact wrong answer to cast a vote on
                    self._drop_probe_transport(presumed_dead)
                    self._probe_stub(presumed_dead, "BrokerStatus",
                                     pb.ListTopicsRequest, pb.TxnReply)(
                        pb.ListTopicsRequest(),
                        timeout=max(0.2, 0.75 * self._vote_timeout_s))
                    return answer(False, "leader-alive", leader_alive=True,
                                  hint=presumed_dead)
                except Exception:  # noqa: BLE001 — unreachable from here too
                    self._drop_probe_transport(presumed_dead)
        with self._role_lock:
            already = self._voted.get(cand_epoch)
            if already is not None and already != candidate:
                return answer(False, "already-voted")  # raced another grant
            self._voted[cand_epoch] = candidate
            self._max_vote_epoch = max(self._max_vote_epoch, cand_epoch)
            self._persist_meta("vote", {"e": cand_epoch, "c": candidate})
            # our vote promised the candidate this epoch: hold our own
            # candidacy down long enough for its promotion (its first ship
            # repoints us much sooner)
            self._stand_down_until = time.monotonic() + max(
                2.0, self._vote_timeout_s * self._vote_rounds)
        self.broker_metrics.quorum_votes_granted.record()
        return answer(True, "granted")

    def ArmFaults(self, request: pb.TxnRequest, context) -> pb.TxnReply:
        """Runtime fault-plane arming (the chaos CLI's RPC): op "arm" with a
        named plan or JSON rule list in records[0].value, "disarm", or
        "status". The armed plane hooks this broker AND its inner log."""
        import json as _json

        from surge_tpu.testing.faults import FaultPlane

        try:
            if request.op == "arm":
                spec = (request.records[0].value or b"").decode()
                seed = int(request.txn_seq)
                plane = FaultPlane.from_spec(spec, seed=seed,
                                             metrics=self.metrics)
                if self.faults is None:
                    self.faults = plane
                    self.faults.on_crash = lambda point: self.kill()
                    self.faults.flight = self.flight
                else:
                    self.faults.arm(plane.rules, seed=seed)
                if hasattr(self.log, "faults"):
                    self.log.faults = self.faults  # FileLog WAL sites
            elif request.op == "disarm":
                if self.faults is not None:
                    self.faults.disarm()
            elif request.op == "kill":
                # remote hard-stop (chaos CLI `cluster --kill`): same crash
                # semantics as a fault-plane kill — socket closes NOW, this
                # very reply races the shutdown (the caller treats
                # UNAVAILABLE as success)
                self.kill()
                return pb.TxnReply(ok=True, records=[pb.RecordMsg(
                    has_key=True, key="faults", has_value=True,
                    value=b'{"killed": true}')])
            elif request.op != "status":
                return pb.TxnReply(ok=False, error_kind="state",
                                   error=f"unknown op {request.op!r}")
            stats = self.faults.stats() if self.faults is not None else {
                "rules": [], "injected": 0, "crashed": None}
            return pb.TxnReply(ok=True, records=[pb.RecordMsg(
                has_key=True, key="faults", has_value=True,
                value=_json.dumps(stats).encode())])
        except Exception as exc:  # noqa: BLE001 — operator gets it back
            return pb.TxnReply(ok=False, error_kind="other", error=repr(exc))

    # -- durable idempotency (__txn_state) ------------------------------------------------

    def _recover_txn_state(self) -> None:
        """Rebuild the dedup table from the __txn_state records a previous
        life of this broker persisted with each seq-ful commit: last_seq (and
        the recent-seq locator WINDOW a pipelined client can still replay)
        survives the restart — OpenProducer resumes the client's numbering,
        and a replayed seq anywhere in the window is answered by re-reading
        the committed records at their recorded offsets instead of appending
        them a second time."""
        import json as _json

        known = getattr(self.log, "_topics", {})
        if TXN_STATE_TOPIC not in known:
            return
        recovered = 0
        for key, rec in self.log.latest_by_key(TXN_STATE_TOPIC, 0).items():
            try:
                obj = _json.loads(rec.value)
                seq = int(obj.get("s", 0))
            except (ValueError, TypeError):
                continue
            dedup = self._txn_dedup.setdefault(key, _TxnDedup())
            if seq > dedup.last_seq:
                dedup.last_seq = seq
                dedup.applied_seq = max(dedup.applied_seq, seq)
                dedup.last_reply = None
                dedup.locator = [tuple(x) for x in obj.get("r", [])]
                for s, loc in obj.get("w", []):
                    dedup.locators[int(s)] = [tuple(x) for x in loc]
                recovered += 1
        if recovered:
            logger.info("recovered %d txn dedup entries from %s",
                        recovered, TXN_STATE_TOPIC)

    def _persist_txn_state(self, txn_id: str, seq: int, records) -> None:
        """Durably record (txn_id -> seq, committed-record locations) in the
        inner log — plus the recent-seq locator window ("w"), so a pipelined
        client's replay of a non-newest seq survives a broker restart too.
        Best-effort: a failure only re-opens the restart-window duplicate
        risk, it must never fail the commit it annotates. ``records`` carry
        their committed offsets (LogRecord, RecordMsg or _CommitRef)."""
        payload = self._txn_state_payload(txn_id, seq, records)
        if payload is not None:
            self._txn_state_write(txn_id, payload)

    def _txn_state_payload(self, txn_id: str, seq: int, records):
        """Locator-window bookkeeping half of the txn-state persist (run
        under the producer state lock — it mutates ``dedup.locators``).
        Returns ``(value, generation)`` — the generation orders lock-free
        writes of this txn_id's annotations."""
        import json as _json

        try:
            locator = [[r.topic, r.partition, r.offset] for r in records]
            dedup = self._txn_dedup.get(txn_id)
            window: list = []
            newest = seq
            gen = 0
            if dedup is not None:
                dedup.persist_gen += 1
                gen = dedup.persist_gen
                dedup.locators[seq] = locator
                while len(dedup.locators) > _DEDUP_WINDOW:
                    dedup.locators.popitem(last=False)
                # persist only the newest few locators: __txn_state is written
                # per commit, so an O(_DEDUP_WINDOW) payload would be serious
                # write amplification on the hot path. 16 comfortably covers
                # any sane surge.producer.max-in-flight (restart replays can
                # only reach back one in-flight window).
                window = [[s, loc] for s, loc in dedup.locators.items()][-16:]
                # out-of-order acks (pipelined durability waits) must never
                # leave a LOWER "s" as the compacted-latest record: persist
                # the acked frontier (paired with ITS locator), not this
                # call's seq
                newest = max(seq, dedup.last_seq)
                locator = dedup.locators.get(newest, locator)
            return (_json.dumps(
                {"s": int(newest), "r": locator, "w": window}).encode(), gen)
        except Exception:  # noqa: BLE001 — annotation only, never fail commits
            logger.exception("txn-state payload failed "
                             "(restart dedup window open)")
            return None

    def _txn_state_write(self, txn_id: str, payload) -> None:
        """Inner-log append half of the txn-state persist (safe outside the
        producer state lock — serialized by its own lock). ``payload`` is a
        ``(value, generation)`` pair from _txn_state_payload: a payload whose
        generation an already-written NEWER one superseded is dropped, so
        two pipelined seqs resolving in one fsync round can never leave the
        stale window as the compacted-latest record."""
        value, gen = payload
        t0 = time.perf_counter()
        try:
            with self._txn_state_lock:
                if gen:
                    if gen < self._txn_persist_gens.get(txn_id, 0):
                        return
                    self._txn_persist_gens[txn_id] = gen
                known = getattr(self.log, "_topics", {})
                if TXN_STATE_TOPIC not in known:
                    self.log.create_topic(
                        TopicSpec(TXN_STATE_TOPIC, 1, compacted=True))
                if self._txn_state_producer is None:
                    self._txn_state_producer = self.log.transactional_producer(
                        "__txn_state_writer__")
                self._txn_state_producer.begin()
                self._txn_state_producer.send(LogRecord(
                    topic=TXN_STATE_TOPIC, key=txn_id, value=value,
                    partition=0))
                self._txn_state_producer.commit()
        except Exception:  # noqa: BLE001 — annotation only, never fail commits
            logger.exception("txn-state persist failed "
                             "(restart dedup window open)")
        finally:
            # this inner-log commit rides its own journal round: count it
            # into the command's journal-fsync leg (the Transact handler's
            # span is active on this thread), not the unattributed residue
            self._stamp_leg("leg.fsync-ms",
                            (time.perf_counter() - t0) * 1000.0)

    def _rebuild_from_locator(self, locator) -> Optional[pb.TxnReply]:
        """Reconstruct a lost reply by re-reading the committed records at
        their recorded (topic, partition, offset) locations."""
        msgs = []
        for t, part, off in locator:
            recs = self.log.read(str(t), int(part), from_offset=int(off),
                                 max_records=1)
            if not recs or recs[0].offset != int(off):
                return None  # locator points past a truncated/foreign log
            msgs.append(record_to_msg(recs[0]))
        return pb.TxnReply(ok=True, records=msgs)

    def _rebuild_cached_reply(self, dedup: _TxnDedup) -> Optional[pb.TxnReply]:
        """Reconstruct a recovered last_seq's lost reply from its locator."""
        if dedup.locator is None:
            return None
        reply = self._rebuild_from_locator(dedup.locator)
        if reply is not None:
            dedup.last_reply = reply
        return reply

    def DedupSnapshot(self, request: pb.DedupSnapshotRequest,
                      context) -> pb.DedupSnapshotReply:
        entries = []
        for txn_id, dedup in list(self._txn_dedup.items()):
            entry = pb.DedupEntry(transactional_id=txn_id,
                                  last_seq=dedup.last_seq)
            if dedup.last_reply is not None:
                entry.last_reply.CopyFrom(dedup.last_reply)
            entries.append(entry)
        return pb.DedupSnapshotReply(entries=entries)

    def _merge_dedup_entries(self, entries) -> None:
        """Forward-only merge of a peer's (txn_id -> last_seq, reply) table —
        shared by catch_up's pull and the leader's auto-resync push, which can
        run CONCURRENTLY (fuzz scenario: operator catch_up racing the probe);
        the replica lock keeps each (last_seq, last_reply) pair atomic."""
        with self._replica_lock:
            self._merge_dedup_entries_locked(entries)

    def _merge_dedup_entries_locked(self, entries) -> None:
        for entry in entries:
            dedup = self._txn_dedup.setdefault(entry.transactional_id,
                                               _TxnDedup())
            if entry.last_seq > dedup.last_seq:
                if entry.HasField("last_reply"):
                    dedup.last_reply = pb.TxnReply()
                    dedup.last_reply.CopyFrom(entry.last_reply)
                    dedup.cache_reply(entry.last_seq, dedup.last_reply)
                dedup.last_seq = entry.last_seq
                if entry.last_seq > dedup.applied_seq:
                    dedup.applied_seq = entry.last_seq
                dedup.locator = None
                if dedup.last_reply is not None and dedup.last_reply.ok:
                    self._persist_txn_state(
                        entry.transactional_id, entry.last_seq,
                        [msg_to_record(m) for m in dedup.last_reply.records])

    def ApplyDedup(self, request: pb.ApplyDedupRequest,
                   context) -> pb.ReplicateReply:
        try:
            self._merge_dedup_entries(request.entries)
            return pb.ReplicateReply(ok=True)
        except Exception as exc:  # noqa: BLE001
            logger.exception("dedup apply failed")
            return pb.ReplicateReply(ok=False, error=repr(exc))

    def catch_up(self, leader_target: str) -> int:
        """Follower bootstrap: copy everything the leader has that this log does
        not (topics + records per partition, in offset order) PLUS the leader's
        txn-dedup table. Returns the number of records copied. Run BEFORE
        start() on an empty/behind follower; ship-on-commit keeps it current
        afterwards.

        The dedup copy matters for exactly-once across failover: the records
        this pull lands may include commits the leader acked while this
        follower was out of the in-sync set. Without the leader's
        (txn_id -> last_seq, cached reply) state, a client failing over here
        and retrying such an in-flight seq would miss the dedup cache and
        append the same records AGAIN (advisor r5)."""
        from surge_tpu.log.client import GrpcLogTransport

        leader = GrpcLogTransport(leader_target, config=self._config)
        copied = 0
        self.catch_up_state = {"state": "running", "from": leader_target,
                               "records": 0, "wall": time.time()}
        self.flight.record("catchup.start", leader=leader_target)
        try:
            reply = leader._calls["ListTopics"](pb.ListTopicsRequest())
            known = getattr(self.log, "_topics", {})
            for spec_msg in reply.topics:
                if spec_msg.name in INTERNAL_TOPICS:
                    continue  # self-maintained per side (see _resync_follower)
                if spec_msg.name not in known:
                    self.log.create_topic(TopicSpec(
                        spec_msg.name, spec_msg.partitions or 1,
                        spec_msg.compacted))
                for p in range(spec_msg.partitions or 1):
                    while True:  # page: one unbounded Read would blow the gRPC
                        start = self._applied_end(spec_msg.name, p)
                        records = self._pull_page(leader, spec_msg.name, p,
                                                  start)
                        if not records:
                            break
                        with self._replica_lock:
                            # verbatim, gaps allowed: a compacted leader
                            # partition legitimately has offset holes
                            self._append_replica(records, allow_gaps=True)
                        copied += len(records)
            # dedup table AFTER records: any commit finalized before this
            # point is either in the copied records (its seq then also in
            # this snapshot) or will be gap-checked-shipped post-rejoin
            snap = leader._calls["DedupSnapshot"](pb.DedupSnapshotRequest())
            self._merge_dedup_entries(snap.entries)
            # cluster metadata rides along: a joiner/rejoiner must route and
            # gate against the CURRENT membership + assignment view, not the
            # one it last persisted before going down
            try:
                meta = leader.cluster_meta()
                self._apply_cluster_meta(meta, source="catch_up")
            except Exception:  # noqa: BLE001 — pre-spread leader: fine
                pass
            self.catch_up_state = {"state": "done", "from": leader_target,
                                   "records": copied, "wall": time.time()}
            self.flight.record("catchup.done", leader=leader_target,
                               records=copied)
            if copied:
                self.broker_metrics.repl_catchup_records.record(copied)
        except BaseException as exc:
            self.catch_up_state = {"state": "failed", "from": leader_target,
                                   "records": copied, "wall": time.time(),
                                   "error": repr(exc)[:200]}
            raise
        finally:
            leader.close()
        return copied

    def _pull_page(self, leader, topic: str, p: int, start: int) -> list:
        """One catch_up page: the FetchSlice bulk lane first (ONE RPC hands
        back a block-encoded CRC-checked slice of up to 2000 records — the
        standby resume path, paying the block codec instead of per-record
        protobuf), degrading permanently to paged Read against a broker
        without the RPC."""
        if self._catchup_slices:
            from surge_tpu.store.checkpoint import decode_partition_slice

            try:
                req = pb.ReadRequest(topic=topic, partition=p,
                                     from_offset=start, has_max=True,
                                     max_records=2000)
                reply = leader._calls["FetchSlice"](req, timeout=10.0)
                if reply.ok and reply.records:
                    _header, records = decode_partition_slice(
                        bytes(reply.records[0].value))
                    return records
                if not reply.ok:
                    # the broker HAS the RPC but this page failed (a racing
                    # compaction, a transient read error): page via Read and
                    # keep the bulk lane for the next page
                    logger.info("FetchSlice %s[%d]@%d refused by %s (%s); "
                                "paging via Read", topic, p, start,
                                leader.target, reply.error)
            except grpc.RpcError as exc:
                if exc.code() == grpc.StatusCode.UNIMPLEMENTED:
                    # an older broker without the RPC: every page would fail
                    # the same way — degrade permanently
                    logger.info("FetchSlice unsupported by %s; catch_up "
                                "falls back to paged Read permanently",
                                leader.target)
                    self._catchup_slices = False
                else:
                    # DEADLINE_EXCEEDED / UNAVAILABLE etc.: this page over
                    # this link, not the lane — Read pages it, the next page
                    # tries the slice lane again
                    logger.info("FetchSlice %s[%d]@%d failed transiently "
                                "(%s); paging via Read", topic, p, start,
                                exc.code())
            except Exception:  # noqa: BLE001 — codec mismatch
                logger.info("FetchSlice slice from %s undecodable; catch_up "
                            "falls back to paged Read permanently",
                            leader.target)
                self._catchup_slices = False
        return list(leader.read(topic, p, from_offset=start,
                                max_records=1000))

    # -- partition slices & live handoff --------------------------------------------------

    def FetchSlice(self, request: pb.ReadRequest, context) -> pb.TxnReply:
        """Standby bulk pull: one checkpoint-codec partition slice (the
        segment block codec — CRC-checked pages, leader-assigned offsets
        preserved) from ``from_offset``, at most ``max_records`` records.
        This is a replication-plane RPC, NOT a consumer read: it serves the
        APPLIED frontier ungated (a standby must mirror records the quorum
        has not acked yet, exactly like the Replicate stream)."""
        from surge_tpu.store.checkpoint import encode_partition_slice

        try:
            cap = request.max_records if request.has_max else 2000
            recs = self.log.read(request.topic, request.partition,
                                 from_offset=request.from_offset,
                                 max_records=cap)
            data = encode_partition_slice(list(recs), request.topic,
                                          request.partition,
                                          base=request.from_offset)
            return pb.TxnReply(ok=True, records=[pb.RecordMsg(
                topic=request.topic, partition=request.partition,
                has_key=True, key="slice", has_value=True, value=data)])
        except Exception as exc:  # noqa: BLE001 — puller gets it back
            logger.exception("FetchSlice failed")
            return pb.TxnReply(ok=False, error_kind="other", error=repr(exc))

    def InstallSlice(self, request: pb.TxnRequest, context) -> pb.TxnReply:
        """Handoff bulk push: verbatim-ingest one partition slice. Refused on
        a leader (ingesting foreign offsets there would fork the log — the
        same reason followers refuse producer opens, inverted) and on gaps:
        a slice must start at or below this replica's applied end (holes
        INSIDE it are legitimate compaction gaps; records already held are
        idempotent-skipped). Topics must exist first — the shipper creates
        them with the right partition count via CreateTopic."""
        from surge_tpu.store.checkpoint import decode_partition_slice

        try:
            header, records = decode_partition_slice(
                bytes(request.records[0].value))
            topic, p = header["topic"], int(header["partition"])
            if self._leads(topic, p):
                # the write authority for this partition never ingests
                # foreign offsets for it — that would fork its own log
                # (whole-broker leader in legacy mode; per-partition in
                # spread mode, where the coordinator CAN receive slices
                # for partitions another broker is handing it)
                return pb.TxnReply(ok=False, error_kind="state",
                                   error="a leader does not ingest slices")
            spec = getattr(self.log, "_topics", {}).get(topic)
            if spec is None:
                return pb.TxnReply(
                    ok=False, error_kind="state",
                    error=f"unknown topic {topic!r}: CreateTopic first "
                          "(auto-create would guess the partition count)")
            with self._replica_lock:
                end = self._applied_end(topic, p)
                to_apply = [r for r in records if r.offset >= end]
                # the slice's read base anchors the gap check: a head hole in
                # [base, first record) is a compaction gap the SOURCE vouches
                # for (it read from base and found nothing below the first
                # record) — only a slice whose whole extent starts above our
                # end hides genuinely missing records
                base = int(header.get("base",
                                      records[0].offset if records else 0))
                if to_apply and base > end and not any(
                        r.offset <= end for r in records):
                    return pb.TxnReply(
                        ok=False, error_kind="state",
                        error=f"gap: slice base {base} (first record "
                              f"{to_apply[0].offset}) but replica end is "
                              f"{end}")
                if to_apply:
                    self._append_replica(to_apply, allow_gaps=True)
            return pb.TxnReply(ok=True, records=[pb.RecordMsg(
                topic=topic, partition=p, has_key=True, key="installed",
                has_value=True,
                value=str(len(to_apply)).encode())])
        except Exception as exc:  # noqa: BLE001 — shipper gets it back
            logger.exception("InstallSlice failed")
            return pb.TxnReply(ok=False, error_kind="other", error=repr(exc))

    def _ship_slices_to(self, target: str, page: int = 2000) -> int:
        """Push every record ``target`` lacks as checkpoint-codec slices
        (InstallSlice), topic specs first — the bulk lane of standby sync
        and handoff. Returns records shipped. Raises on a refused install
        (the caller owns retry/abort policy)."""
        shipped = 0
        create = self._probe_stub(target, "CreateTopic",
                                  pb.CreateTopicRequest, pb.TopicReply)
        for spec in self._topic_specs():
            if spec.name in INTERNAL_TOPICS:
                continue  # self-maintained per side (see _resync_follower)
            create(pb.CreateTopicRequest(spec=pb.TopicSpecMsg(
                name=spec.name, partitions=spec.partitions,
                compacted=spec.compacted)), timeout=2.0)
            for p in range(spec.partitions or 1):
                shipped += self._ship_partition_slices(target, spec, p,
                                                       page=page)
        return shipped

    def _ship_partition_slices(self, target: str, spec, p: int,
                               page: int = 2000) -> int:
        """Push what ``target`` lacks of ONE partition as checkpoint-codec
        slices — the whole-broker handoff's inner loop, and the spread
        handoff's per-partition tail ship. The topic must already exist on
        the target (CreateTopic is idempotent; callers send it first)."""
        from surge_tpu.store.checkpoint import encode_partition_slice

        install = self._probe_stub(target, "InstallSlice", pb.TxnRequest,
                                   pb.TxnReply)
        shipped = 0
        # bounded passes, not while-True: under sustained append a moving
        # frontier must not pin the bulk phase forever — the fenced tail
        # pass finishes whatever is left
        for _pass in range(1000):
            theirs = self._remote_end_offset(target, spec.name, p)
            ours = self._applied_end(spec.name, p)
            if theirs >= ours:
                break
            batch = list(self.log.read(spec.name, p, from_offset=theirs,
                                       max_records=page))
            if not batch:
                break  # compacted hole at the tail
            # base=theirs: a head hole in [theirs, batch[0]) is a
            # compaction gap this read vouches for — the installer
            # may ingest past it (state topics ARE compacted)
            data = encode_partition_slice(batch, spec.name, p, base=theirs)
            reply = install(pb.TxnRequest(
                op="install", records=[pb.RecordMsg(
                    topic=spec.name, partition=p, has_key=True,
                    key="slice", has_value=True, value=data)]),
                timeout=self._repl_ack_timeout_s)
            if not reply.ok:
                raise RuntimeError(
                    f"InstallSlice {spec.name}[{p}] on {target} "
                    f"refused: {reply.error}")
            shipped += len(batch)
        if shipped:
            self.broker_metrics.handoff_shipped_records.record(shipped)
        return shipped

    def HandoffPartition(self, request: pb.TxnRequest, context) -> pb.TxnReply:
        """Planned leadership transfer (admin RPC): move this leader's role
        to ``{"to": target}`` deliberately — bulk slice ship (unfenced:
        clients keep committing), fence + drain, journal-tail slice ship,
        dedup push, promote the destination (which fences us at the handoff
        epoch), demote in place. Planned unavailability is the FENCED span —
        bounded by the tail appended during the bulk phase, never by log
        size."""
        import json as _json

        obj = {}
        if request.records and request.records[0].has_value:
            try:
                obj = _json.loads(request.records[0].value or b"{}")
            except ValueError:
                pass
        to = str(obj.get("to", ""))
        if not to:
            return pb.TxnReply(ok=False, error_kind="state",
                               error='HandoffPartition needs {"to": target}')
        if "partition" in obj:
            # spread mode: move ONE partition index's leadership (the
            # autobalancer's unit of work), not the whole broker
            try:
                stats = self._handoff_partition_to(to, int(obj["partition"]))
                return pb.TxnReply(ok=True, records=[pb.RecordMsg(
                    has_key=True, key="handoff", has_value=True,
                    value=_json.dumps(stats).encode())])
            except Exception as exc:  # noqa: BLE001 — operator gets it back
                logger.exception("partition handoff to %s failed", to)
                return pb.TxnReply(ok=False, error_kind="other",
                                   error=repr(exc))
        with self._role_lock:
            if self.role != "leader":
                return pb.TxnReply(ok=False, error_kind="not_leader",
                                   error=f"broker is a {self.role}",
                                   leader_hint=self.leader_hint)
            if self._handoff_active or self._handoff_fence:
                return pb.TxnReply(ok=False, error_kind="state",
                                   error="a handoff is already in progress")
            # claim INSIDE the role lock: a second HandoffPartition arriving
            # during the (long, unfenced) bulk phase must refuse here — two
            # overlapping handoffs would race their fences and epochs
            self._handoff_active = True
        try:
            stats = self._handoff_to(to)
            return pb.TxnReply(ok=True, records=[pb.RecordMsg(
                has_key=True, key="handoff", has_value=True,
                value=_json.dumps(stats).encode())])
        except Exception as exc:  # noqa: BLE001 — operator gets it back
            logger.exception("handoff to %s failed", to)
            return pb.TxnReply(ok=False, error_kind="other", error=repr(exc))
        finally:
            with self._role_lock:
                self._handoff_active = False

    def _handoff_to(self, to: str) -> dict:
        me = self._my_target()
        stats: dict = {"from": me, "to": to}
        self.flight.record("handoff.start", to=to)
        # phase 1: BULK — unfenced; the destination converges to within the
        # live append rate while clients keep committing
        t0 = time.perf_counter()
        stats["bulk_records"] = self._ship_slices_to(to)
        stats["bulk_ms"] = round((time.perf_counter() - t0) * 1000.0, 2)
        # phase 2: FENCE — stop intake (Transact/OpenProducer answer
        # not_leader with an EMPTY hint: clients hold in place), drain
        # in-flight commits and the replication queue so the log is stable
        fence_t0 = time.perf_counter()
        with self._role_lock:
            self._handoff_fence = True
        self.flight.record("handoff.fence", to=to)
        try:
            deadline = time.monotonic() + 2.0 * self._repl_ack_timeout_s
            while time.monotonic() < deadline:
                with self._role_lock:
                    inflight = self._inflight_txn
                with self._repl_cv:
                    # quorum-FINALIZED is the drain bar, not queue-empty:
                    # under min-insync-acks a slow in-sync follower pins
                    # finalized items in the queue until its cursor passes
                    # them, and the tail slice ship reads the log directly —
                    # undelivered ships to OTHER followers don't matter
                    undone = sum(1 for i in self._repl_queue
                                 if not i.done.is_set())
                if inflight == 0 and undone == 0:
                    break
                time.sleep(0.01)
            else:
                raise RuntimeError(
                    "handoff drain timed out (in-flight commits or "
                    "unfinalized replication items never quiesced)")
            # phase 3: TAIL — everything appended since the bulk pass (the
            # journal tail; this, not log size, bounds the fenced span)
            stats["tail_records"] = self._ship_slices_to(to)
            # phase 4: dedup push — the destination answers in-flight seq
            # replays from cache, exactly-once across the handoff
            err = self._push_dedup_to(to)
            if err is not None:
                raise RuntimeError(f"dedup push refused: {err}")
            if self.faults is not None:
                self.faults.crash_point("handoff.pre-promote")
            # phase 5: promote the destination — it fences us at the handoff
            # epoch; every other peer repoints off its first ship
            reply = self._probe_stub(to, "PromoteFollower", pb.TxnRequest,
                                     pb.TxnReply)(
                pb.TxnRequest(op="promote"),
                timeout=2.0 * self._repl_ack_timeout_s)
            if not reply.ok:
                raise RuntimeError(f"destination refused promotion: "
                                   f"{reply.error}")
            import json as _json

            status = _json.loads(reply.records[0].value)
            new_epoch = int(status.get("epoch", 0))
            stats["epoch"] = new_epoch
            if self.faults is not None:
                self.faults.crash_point("handoff.post-promote")
            # phase 6: demote in place (truncation is a no-op — everything
            # shipped pre-promotion; catch_up pulls the nothing we lack)
            self._demote(new_epoch, to)
        finally:
            with self._role_lock:
                self._handoff_fence = False
        fence_ms = round((time.perf_counter() - fence_t0) * 1000.0, 2)
        stats["fence_ms"] = fence_ms
        self.broker_metrics.handoff_fence_timer.record_ms(fence_ms)
        self.flight.record("handoff.done", **{k: v for k, v in stats.items()
                                              if k != "from"})
        logger.warning("handoff to %s complete: %s", to, stats)
        return stats

    def _handoff_partition_to(self, to: str, partition: int) -> dict:
        """Planned PER-PARTITION leadership transfer (spread mode): fence
        one partition index, drain its in-flight commits + queued ships,
        tail-sync the destination on every topic at that index, push the
        dedup table, flip the assignment through the coordinator, unfence.
        The fenced span covers one partition's tail — every other partition
        this broker leads keeps committing throughout."""
        import json as _json

        key = str(partition)
        me = self._my_target()
        with self._role_lock:
            if not self._spread_active():
                raise RuntimeError("per-partition handoff needs an active "
                                   "assignment map (ClusterMeta spread)")
            owner = self._assignments.get(key, me if self.role == "leader"
                                          else "")
            if owner != me:
                raise RuntimeError(f"partition {key} is led by "
                                   f"{owner or 'nobody'}, not this broker")
            if to == me:
                raise RuntimeError("destination is this broker")
            if self._quorum_peers and to not in self._quorum_peers:
                raise RuntimeError(f"{to} is not a cluster member")
            if key in self._part_fence or self._handoff_fence:
                raise RuntimeError("a handoff is already in progress for "
                                   f"partition {key}")
            self._part_fence.add(key)
        stats: dict = {"from": me, "to": to, "partition": partition}
        self.flight.record("handoff.partition.start", partition=partition,
                           to=to)
        fence_t0 = time.perf_counter()
        try:
            # drain: in-flight commits touching THIS partition, and queued
            # replication items still awaiting their quorum for it
            deadline = time.monotonic() + 2.0 * self._repl_ack_timeout_s
            while time.monotonic() < deadline:
                with self._role_lock:
                    inflight = self._inflight_parts.get(key, 0)
                with self._repl_cv:
                    undone = sum(
                        1 for i in self._repl_queue
                        if not i.done.is_set() and any(
                            r.partition == partition
                            and r.topic not in INTERNAL_TOPICS
                            for r in i.records))
                if inflight == 0 and undone == 0:
                    break
                time.sleep(0.01)
            else:
                raise RuntimeError(
                    f"partition {key} handoff drain timed out")
            # tail-sync the destination on every topic at this index (the
            # continuous spread replication keeps it near; this closes the
            # last records + any resync hole), then push dedup so in-flight
            # seq replays answer from cache on the new leader
            create = self._probe_stub(to, "CreateTopic",
                                      pb.CreateTopicRequest, pb.TopicReply)
            shipped = 0
            for spec in self._topic_specs():
                if spec.name in INTERNAL_TOPICS or \
                        partition >= (spec.partitions or 1):
                    continue
                create(pb.CreateTopicRequest(spec=pb.TopicSpecMsg(
                    name=spec.name, partitions=spec.partitions,
                    compacted=spec.compacted)), timeout=2.0)
                shipped += self._ship_partition_slices(to, spec, partition)
            stats["tail_records"] = shipped
            err = self._push_dedup_to(to)
            if err is not None:
                raise RuntimeError(f"dedup push refused: {err}")
            if self.faults is not None:
                self.faults.crash_point("handoff.partition.pre-assign")
            # flip the assignment through the coordinator (ourselves, when
            # this broker IS the coordinator) and adopt the new view NOW —
            # the unfence below must reveal the new owner, not us
            if self.role == "leader":
                view = self._mutate_cluster_meta(assign={key: to},
                                                 reason="handoff")
            else:
                reply = self._probe_call(
                    self.leader_hint, "ClusterMeta", pb.TxnRequest,
                    pb.TxnReply,
                    pb.TxnRequest(op="assign", records=[pb.RecordMsg(
                        has_value=True, value=_json.dumps(
                            {"partition": key, "to": to}).encode())]),
                    timeout=2.0 * self._repl_ack_timeout_s)
                if not reply.ok:
                    raise RuntimeError(
                        f"coordinator refused the assignment flip: "
                        f"{reply.error}")
                view = _json.loads(reply.records[0].value)
                self._apply_cluster_meta(view, source="handoff")
            stats["assign_epoch"] = int(view.get("assign_epoch", 0))
            stats["epoch"] = int(view.get("epoch", 0))
        finally:
            with self._role_lock:
                self._part_fence.discard(key)
        fence_ms = round((time.perf_counter() - fence_t0) * 1000.0, 2)
        stats["fence_ms"] = fence_ms
        self.broker_metrics.handoff_fence_timer.record_ms(fence_ms)
        self.flight.record("handoff.partition.done",
                           **{k: v for k, v in stats.items() if k != "from"})
        logger.warning("partition %d handed off to %s: %s", partition, to,
                       stats)
        return stats

    def _adopt_shipped_hwm(self, high_watermarks: str) -> None:
        """Follower half of the high-watermark protocol: every Replicate
        (data, rejoin probe, or post-finalize beacon) carries the leader's
        quorum-acked frontier — adopt it monotonically. The gate may run
        AHEAD of this replica's applied end harmlessly (reads only ever see
        applied records); it must never run backwards, or a record already
        served to a consumer would turn invisible."""
        if not high_watermarks:
            return
        import json as _json

        try:
            shipped = _json.loads(high_watermarks)
        except ValueError:
            return
        for key, off in shipped.items():
            topic, _, p = key.rpartition("|")
            tp = (topic, int(p))
            if int(off) > self._hwm.get(tp, 0):
                self._hwm[tp] = int(off)
                self._hwm_wire = None  # this replica may promote and ship

    def _read_gate(self, topic: str, partition: int) -> Optional[int]:
        """The follower-served read ceiling for one partition: the shipped
        high-watermark, or None when this partition is ungated (leader
        reads; a follower that never received a hwm ship keeps the PR-4
        serve-everything behavior — legacy pairs, operator catch_up
        replicas). In spread mode the gate is PER PARTITION: a broker is
        authoritative for its led slice and hwm-gated for everyone else's."""
        if self._spread_active():
            if topic in INTERNAL_TOPICS or self._leads(topic, partition):
                return None
            return self._hwm.get((topic, partition))
        if self.role == "leader":
            return None
        return self._hwm.get((topic, partition))

    def Read(self, request: pb.ReadRequest, context) -> pb.ReadReply:
        max_records = request.max_records if request.has_max else None
        recs = self.log.read(request.topic, request.partition,
                             from_offset=request.from_offset,
                             max_records=max_records)
        gate = self._read_gate(request.topic, request.partition)
        if gate is not None and recs and recs[-1].offset >= gate:
            # hwm gate: records applied here but not provably quorum-held
            # stay invisible — like records of an open transaction. A
            # failover that truncates them can then never un-serve a read.
            recs = [r for r in recs if r.offset < gate]
            self.broker_metrics.hwm_gated_reads.record()
        return self._format_read_reply(recs)

    def _format_read_reply(self, recs, fallback_cls=pb.ReadReply):
        """Serialize a record list as ReadReply-shaped bytes (records =
        field 1; LatestByKeyReply shares the wire shape) through the native
        reply formatter (one C++ call, no per-record RecordMsg) — protobuf
        path when native is off (bit-identical on the wire up to map
        order, which protobuf readers ignore)."""
        if self._native is not None and recs:
            t0 = time.perf_counter()
            data = self._native.reply_format(recs, 1)
            if data is not None:
                self.broker_metrics.native_reply_timer.record_ms(
                    (time.perf_counter() - t0) * 1000.0)
                return data
        return fallback_cls(records=[record_to_msg(r) for r in recs])

    def EndOffset(self, request: pb.OffsetRequest, context) -> pb.OffsetReply:
        # NON-mutating membership check, not .topic(): inner logs auto-create
        # unknown topics with a DEFAULT partition count, so a mere offset
        # probe of an empty replica (the leader's rejoin lag scan) would pin
        # the topic at the wrong partitioning and the later resync ship's
        # create-if-missing would skip it — a silently mis-partitioned
        # replica. Unknown topic/partition simply holds nothing: offset 0.
        # end_offset stays the APPLIED frontier (the leader's gap checks and
        # lag scans measure against it); high_watermark reports the
        # quorum-acked frontier alongside — what follower-served
        # read_committed reads are gated on.
        known = getattr(self.log, "_topics", None)
        if known is not None:
            spec = known.get(request.topic)
            if spec is None or request.partition >= spec.partitions:
                return pb.OffsetReply(end_offset=0)
        end = self.log.end_offset(request.topic, request.partition)
        gate = self._read_gate(request.topic, request.partition)
        if gate is None:
            hwm = self._hwm.get((request.topic, request.partition))
            # an ungated partition serves everything it has applied; a
            # replicating leader reports its live quorum frontier
            gate = end if hwm is None else hwm
        return pb.OffsetReply(end_offset=end, high_watermark=min(gate, end))

    def LatestByKey(self, request: pb.OffsetRequest,
                    context) -> pb.LatestByKeyReply:
        latest = self.log.latest_by_key(request.topic, request.partition)
        recs = list(latest.values())
        gate = self._read_gate(request.topic, request.partition)
        if gate is not None and any(r.offset >= gate for r in recs):
            # same hwm gate as Read: a key whose newest version is not
            # provably quorum-held stays invisible (an older below-gate
            # version may already be compacted away — hiding the key beats
            # serving a record a failover could erase)
            recs = [r for r in recs if r.offset < gate]
            self.broker_metrics.hwm_gated_reads.record()
        return self._format_read_reply(recs, fallback_cls=pb.LatestByKeyReply)

    def CompactTopic(self, request: pb.ReadRequest, context) -> pb.TxnReply:
        """Compact one partition of a compacted topic broker-side (the
        operator/CLI trigger). On a replicating leader the pass rides the
        replication stream as a BARRIER item, so every in-sync follower
        applies the identical generational swap — the pre-barrier refusal is
        gone (ROADMAP item closed)."""
        import json as _json

        if not hasattr(self.log, "compact_partition"):
            return pb.TxnReply(ok=False, error_kind="state",
                               error=f"{type(self.log).__name__} does not "
                                     "support compaction")
        # NON-mutating lookup: log.topic() would auto-create, persisting a
        # junk topic from a mistyped operator request
        spec = getattr(self.log, "_topics", {}).get(request.topic)
        if spec is None:
            return pb.TxnReply(ok=False, error_kind="state",
                               error=f"unknown topic {request.topic!r}")
        if not spec.compacted:
            return pb.TxnReply(ok=False, error_kind="state",
                               error=f"topic {request.topic!r} is not "
                                     "compacted")
        try:
            stats = self.compact_partition(request.topic, request.partition)
        except Exception as exc:  # noqa: BLE001 — operator gets it back
            return pb.TxnReply(ok=False, error_kind="other", error=repr(exc))
        msg = pb.RecordMsg(topic=request.topic, partition=request.partition,
                           has_key=True, key="stats", has_value=True,
                           value=_json.dumps(stats.as_dict()).encode())
        return pb.TxnReply(ok=True, records=[msg])

    # -- compactor surface: a LogCompactor can schedule THIS SERVER as its
    # log, so the dirty-ratio scheduler on a replicated leader routes every
    # pass through the barrier instead of compacting the inner log behind
    # the replication stream's back

    @property
    def _topics(self):
        return getattr(self.log, "_topics", {})

    def end_offset(self, topic: str, partition: int,
                   isolation: str = "read_committed") -> int:
        return self.log.end_offset(topic, partition, isolation=isolation)

    def compaction_state(self, topic: str, partition: int) -> Dict[str, int]:
        return self.log.compaction_state(topic, partition)

    def compact_partition(self, topic: str, partition: int, *,
                          tombstone_retention_s: Optional[float] = None,
                          now: Optional[float] = None):
        """Replication-aware compaction entry (CompactTopic RPC, LogCompactor
        scheduler): barrier-replicated on a leader with followers, direct on
        an unreplicated broker. Refused on a follower — its leader drives
        compaction through the stream."""
        from surge_tpu.config import default_config as _dc

        if tombstone_retention_s is None:
            tombstone_retention_s = (self._config or _dc()).get_seconds(
                "surge.log.compaction.tombstone-retention-ms", 60_000)
        if self._spread_active():
            # per-partition leadership spread: the write authority for THIS
            # partition drives its compaction (the whole-broker role is
            # meaningless under a spread — a "follower"-role broker may lead
            # this slice, and the legacy check would refuse it while letting
            # a non-owner compact behind the real leader's stream)
            if not self._leads(topic, partition):
                owner = self._assignments.get(str(partition))
                raise RuntimeError(
                    f"compaction of {topic}[{partition}] must run on its "
                    f"slice leader ({owner or 'unknown'}); this broker does "
                    f"not lead it")
        elif self.role != "leader":
            raise RuntimeError(
                f"compaction must run on the leader ({self.leader_hint or 'unknown'}); "
                f"this broker is a {self.role}")
        if not self._repl_targets:
            return self.log.compact_partition(
                topic, partition, tombstone_retention_s=tombstone_retention_s,
                now=now)
        item = _ReplItem([], [], kind="barrier", manifest={
            "topic": topic, "partition": partition,
            "retention_s": tombstone_retention_s,
            "now": now if now is not None else time.time()})
        self._enqueue_item(item)
        if not item.done.wait(2 * self._repl_ack_timeout_s):
            raise RuntimeError(
                "compaction barrier timed out awaiting the in-sync set "
                f"({item.error or 'still queued'})")
        if item.error:
            raise RuntimeError(f"compaction barrier failed: {item.error}")
        return item.result

    def WaitForAppend(self, request: pb.WaitRequest, context) -> pb.WaitReply:
        def check() -> bool:
            return (self.log.end_offset(request.topic, request.partition)
                    > request.after_offset)

        if not self._wait_slots.acquire(blocking=False):
            # pool contended: answer immediately (the client paces its retry)
            return pb.WaitReply(appended=check())
        try:
            deadline = time.monotonic() + max(request.timeout_s, 0.01)
            while time.monotonic() < deadline:
                if check():
                    return pb.WaitReply(appended=True)
                time.sleep(0.02)
            return pb.WaitReply(appended=False)
        finally:
            self._wait_slots.release()

    # -- lifecycle ------------------------------------------------------------------------

    def _wrap_handler(self, name: str, fn):
        """Per-RPC interception: a killed broker answers UNAVAILABLE (its
        socket may still be draining), the fault plane's rpc.* sites apply
        (drop / delay / reorder / dup / error), and a SimulatedCrash escaping
        a handler surfaces as UNAVAILABLE — exactly what a crashed process
        looks like from the client side."""

        def handler(request, context):
            if self._dead:
                context.abort(grpc.StatusCode.UNAVAILABLE,
                              "broker killed (fault injection)")
            plane = self.faults
            if plane is not None:
                rule = plane.on_rpc(name)
                if rule is not None:
                    if rule.action == "drop":
                        context.abort(grpc.StatusCode.UNAVAILABLE,
                                      "fault injected: message dropped")
                    elif rule.action == "error":
                        context.abort(grpc.StatusCode.UNAVAILABLE,
                                      f"fault injected: {rule.error}")
                    elif rule.action == "dup":
                        fn(request, context)  # duplicate delivery: run twice
            try:
                return fn(request, context)
            except Exception as exc:
                if type(exc).__name__ == "SimulatedCrash":
                    context.abort(grpc.StatusCode.UNAVAILABLE,
                                  f"broker crashed: {exc}")
                raise

        return handler

    def start(self) -> int:
        from surge_tpu.remote.security import server_credentials, tls_enabled

        rpc = {}
        for name, (req_cls, reply_cls) in METHODS.items():
            rpc[name] = grpc.unary_unary_rpc_method_handler(
                self._wrap_handler(name, getattr(self, name)),
                request_deserializer=req_cls.FromString,
                response_serializer=_serialize_reply)
        self._server = grpc.server(
            futures.ThreadPoolExecutor(max_workers=self._max_workers))
        self._server.add_generic_rpc_handlers(
            (grpc.method_handlers_generic_handler(SERVICE, rpc),))
        address = f"{self._host}:{self._port}"
        if tls_enabled(self._config):
            self.bound_port = self._server.add_secure_port(
                address, server_credentials(self._config))
        else:
            self.bound_port = self._server.add_insecure_port(address)
        if not self.bound_port:
            raise RuntimeError(f"could not bind log server to {address}")
        if self.advertised is None:
            self.advertised = f"{self._host}:{self.bound_port}"
        if not self.flight.name:
            self.flight.name = self.advertised
        if self.trace_ring is not None and not self.trace_ring.name:
            self.trace_ring.name = self.advertised
        if self._metrics_port is not None and self._metrics_server is None:
            from surge_tpu.metrics.broker import broker_collector
            from surge_tpu.metrics.exposition import MetricsHTTPServer

            self._metrics_server = MetricsHTTPServer(
                self.broker_metrics.registry, host=self._host,
                port=self._metrics_port,
                collectors=[broker_collector(self)])
            self.metrics_bound_port = self._metrics_server.start()
        # the surgetop `native` column: live C++ hot path vs silent fallback
        self.broker_metrics.native_active.record(
            1 if self._native is not None and native_gate.available() else 0)
        if self.role == "leader" and not self.leader_hint:
            self.leader_hint = self._my_target()
        if self._repl_targets:
            # split-brain guard, BEFORE the socket serves: a restarting
            # "leader" may have been deposed while down — ask the peers, so
            # not even one write can land on a stale epoch
            self._confirm_leadership()
        self._server.start()
        if self._repl_targets and self._repl_thread is None \
                and not self._repl_stop:
            self._repl_stop = False
            self._repl_thread = threading.Thread(
                target=self._replication_loop, name="surge-log-replication",
                daemon=True)
            self._repl_thread.start()
        if self._follower_of:
            # learn the leader's current epoch up front (best effort): the
            # fence must hold even if this follower promotes before ever
            # receiving a batch
            with self._role_lock:
                self._adopt_leader_epoch()
        self._ensure_prober()
        self._record_cluster_gauges()
        if self._spread_active() and self.role != "leader":
            # a restarted broker's recovered assignment view may predate
            # moves made while it was down — and its recovered epoch was
            # persisted at the same staleness, so the epoch fence alone
            # cannot catch it. Come back SUSPENDED: the write gate refuses
            # until a metadata refresh (or a coordinator broadcast) proves
            # the view current, so a relit ex-leader can never serve a
            # partition the cluster moved while it slept.
            with self._role_lock:
                self._meta_epoch = min(self._meta_epoch, self.epoch - 1)
            self._kick_meta_refresh()
        elif self._spread_active():
            self._ensure_spread_replication()
        return self.bound_port

    def _ensure_prober(self) -> None:
        """Aim the leader-liveness prober at the CURRENT leader (start(),
        demotion, and cluster repoints all land here): started fresh when
        missing, retargeted — fresh failure streak, bootstrap grace
        re-applied — when the leader moved. No-op on leaders, on brokers
        without auto-promotion, and on dead brokers."""
        if not self._auto_promote or self._dead:
            return
        if self.role != "follower" or not self._follower_of:
            return
        prober = self._leader_prober
        if prober is None:
            from surge_tpu.health.prober import BrokerLivenessProber

            def _ping() -> None:
                try:
                    self._remote_broker_status(self._follower_of)
                except Exception:
                    # drop the cached channel NOW: one probe that failed
                    # while the leader was booting would otherwise leave a
                    # connect-backoff channel poisoning every later probe
                    # AND every other probe-stub RPC to the same address
                    # (vote liveness checks, per-partition handoffs)
                    self._drop_probe_transport(self._follower_of)
                    raise

            self._leader_prober = BrokerLivenessProber(
                self._follower_of, _ping, config=self._config,
                on_dead=self._on_leader_dead, flight=self.flight)
            self._leader_prober.start()
        elif prober.target != self._follower_of:
            prober.retarget(self._follower_of)

    def _on_leader_dead(self) -> None:
        """The liveness prober declared the leader dead: campaign for a
        cluster majority when quorum peers are configured (one prober's
        liveness view alone can no longer mint a leader), else the PR-4
        pairwise self-promotion."""
        if self.role == "leader" or self._dead or self._closed:
            return
        if self._quorum_peers:
            logger.error("leader %s declared dead by the liveness prober; "
                         "campaigning for a cluster majority",
                         self._follower_of)
            try:
                self._campaign_for_leadership()
            except Exception:  # noqa: BLE001 — stay follower, re-arm prober
                logger.exception("leadership campaign failed")
                if self._leader_prober is not None:
                    self._leader_prober.reset()
            return
        logger.error("leader %s declared dead by the liveness prober; "
                     "auto-promoting", self._follower_of)
        try:
            self.promote()
        except Exception:  # noqa: BLE001 — stay follower, prober keeps going
            logger.exception("auto-promotion failed")

    def _campaign_for_leadership(self) -> bool:
        """Majority-vote promotion rounds (the Raft-flavored layer over the
        KIP-101 epoch fence): mint a candidate epoch above every epoch this
        broker has seen OR campaigned, ask every quorum peer for its vote
        (each peer re-checks leader liveness from ITS vantage), and promote
        only on a strict cluster majority — self-vote included. Losing every
        round stands the candidacy down and re-arms the prober: the leader
        may yet return, or the true winner's first ship repoints us. Returns
        True when this broker promoted."""
        import json as _json

        me = self._my_target()
        backoff = 0.05
        for rnd in range(self._vote_rounds):
            if self._dead or self._closed or self.role == "leader":
                return self.role == "leader"
            # membership is DYNAMIC: re-read it every round, so an
            # AddBroker/RemoveBroker landing mid-campaign re-sizes the
            # majority this very election needs (no restart required) — and
            # a broker the cluster removed must stop campaigning entirely
            others = self._quorum_others()
            if self._quorum_peers and me and me not in self._quorum_peers:
                self.flight.record("quorum.stand-down", reason="removed")
                logger.error("this broker was removed from the membership; "
                             "standing down from the campaign")
                return False
            cluster = len(others) + 1
            needed = cluster // 2 + 1
            stand_down = self._stand_down_until - time.monotonic()
            if stand_down > 0:
                # we just granted a peer this round: give its promotion the
                # head start our vote promised it
                time.sleep(min(stand_down, 1.0))
                continue
            with self._role_lock:
                epoch = max(self.epoch, self._max_vote_epoch, 1) + 1
                self._max_vote_epoch = epoch
                self._voted[epoch] = me  # self-vote: our one vote this epoch
                self._persist_meta("vote", {"e": epoch, "c": me})
            grants, alive_hint = 1, None
            self.flight.record("quorum.campaign", epoch=epoch, round=rnd,
                               needed=needed, cluster=cluster)
            request = pb.TxnRequest(op="vote", txn_seq=epoch, records=[
                pb.RecordMsg(has_value=True, value=_json.dumps(
                    {"candidate": me,
                     "leader": self._follower_of or "",
                     # the up-to-date check's evidence (Raft §5.4.1 role):
                     # a voter holding MORE log than this denies — any
                     # majority then contains a holder of every
                     # quorum-acked commit, so the winner has them all
                     "ends": self._applied_ends()}).encode())])
            for peer in others:
                if grants >= needed:
                    continue
                try:
                    reply = self._probe_stub(
                        peer, "VoteLeader", pb.TxnRequest, pb.TxnReply)(
                        request, timeout=self._vote_timeout_s)
                except Exception:  # noqa: BLE001 — dead peer grants nothing
                    self._drop_probe_transport(peer)
                    continue
                if not reply.ok or not reply.records:
                    continue
                verdict = _json.loads(reply.records[0].value or b"{}")
                if verdict.get("granted"):
                    grants += 1
                    continue
                peer_epoch = int(verdict.get("epoch", 0))
                if peer_epoch > self._max_vote_epoch:
                    # a peer has seen further: campaign above it next round
                    self._max_vote_epoch = peer_epoch
                if verdict.get("leader_alive"):
                    alive_hint = verdict.get("leader_hint") or peer
            if alive_hint is not None:
                # a peer can still reach the leader (or IS a live leader):
                # our link is what died, not the leader — stand down and
                # keep probing instead of splitting the brain
                self.broker_metrics.quorum_stand_downs.record()
                self.flight.record("quorum.stand-down", epoch=epoch,
                                   reason="leader-alive", via=alive_hint)
                logger.warning(
                    "campaign for epoch %d stood down: a quorum peer still "
                    "reaches the leader (via %s)", epoch, alive_hint)
                if self._leader_prober is not None:
                    self._leader_prober.reset()
                return False
            if grants >= needed:
                if self._dead or self._closed:
                    # stop()/kill() landed mid-round: a majority collected
                    # for a broker that no longer serves must not promote
                    return False
                self.broker_metrics.quorum_elections_won.record()
                self.flight.record("quorum.win", epoch=epoch, grants=grants,
                                   needed=needed, cluster=cluster)
                logger.warning("campaign WON epoch %d with %d/%d votes; "
                               "promoting", epoch, grants, cluster)
                self.promote(at_epoch=epoch)
                return True
            self.flight.record("quorum.no-majority", epoch=epoch,
                               grants=grants, needed=needed)
            time.sleep(self._jittered_backoff(backoff))
            backoff = min(backoff * 2, 0.5)
        self.broker_metrics.quorum_stand_downs.record()
        self.flight.record("quorum.stand-down", reason="no-majority",
                           rounds=self._vote_rounds)
        logger.error("no majority after %d campaign rounds; standing down "
                     "(prober re-armed)", self._vote_rounds)
        if self._leader_prober is not None:
            self._leader_prober.reset()
        return False

    def _jittered_backoff(self, backoff: float) -> float:
        """Randomized sleep in [backoff/2, backoff): two candidates whose
        campaigns split the vote must not retry in lockstep forever."""
        import random

        return backoff * (0.5 + 0.5 * random.random())

    def stop(self, grace: float = 1.0) -> None:
        # a campaign already running on the prober thread checks this flag
        # every round (and before promoting): a STOPPED broker must not win
        # an election and repoint the cluster at its closed socket
        self._closed = True
        self._stop_metrics_server()
        if self._leader_prober is not None:
            self._leader_prober.stop()
            self._leader_prober = None
        if self._repl_thread is not None:
            with self._repl_cv:
                self._repl_stop = True
                self._repl_cv.notify_all()
            self._repl_thread.join(grace + 1.0)
            self._repl_thread = None
        if self._server is not None:
            self._server.stop(grace).wait()
            self._server = None

    # aliases kept for symmetry with the asyncio-hosted servers
    serve_background = start
    shutdown_background = stop
