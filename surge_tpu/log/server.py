"""Networked log broker: any LogTransport served over gRPC.

The shared durability substrate between engine processes — the role the external
Kafka broker plays for the reference (SURVEY.md §2.9 item 3; KafkaProducer.scala /
KafkaConsumer.scala are thin wrappers over a remote broker exactly like
:class:`surge_tpu.log.client.GrpcLogTransport` is over this server). Wraps any
in-process :class:`~surge_tpu.log.transport.LogTransport` — :class:`FileLog` for a
durable single-node broker, :class:`InMemoryLog` for tests (the EmbeddedKafka
analog, SURVEY.md §4.5).

Runs on the **synchronous** gRPC server (thread pool): the broker's inner logs are
already thread-safe, handlers never touch an event loop, and one process can host
the broker alongside grpc.aio clients/servers without the multi-loop hazards of
grpc.aio-on-a-thread.

Semantics preserved across the wire:

- **Atomic transactions**: the client buffers ``send()`` locally and ships the whole
  transaction in one ``Transact(op="commit")`` request; the server appends it through
  the wrapped log's transactional producer, so multi-topic atomicity and
  read_committed visibility are the inner log's.
- **Producer-epoch fencing**: ``OpenProducer`` opens a server-side producer, fencing
  any earlier holder of the transactional id (including one opened by another
  process); a fenced producer's operations return ``error_kind="fenced"`` which the
  client re-raises as :class:`ProducerFencedError`.
- **Consumer wakeups**: ``WaitForAppend`` long-polls ``end_offset`` with a bounded
  timeout (the client loops, so arbitrarily long waits stay cheap per request).
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict
from concurrent import futures
from typing import Dict, Optional

import grpc

from surge_tpu.common import logger
from surge_tpu.log import log_service_pb2 as pb
from surge_tpu.log.transport import (
    LogRecord,
    ProducerFencedError,
    TopicSpec,
    TransactionStateError,
)

class _ProducerState:
    """Server-side producer handle plus the idempotency dedup cache.

    One commit/send_immediate is in flight per producer at a time (the publisher
    is the partition's single writer), so caching only the most recent
    (seq, reply) per token is enough to answer any replay the client can send.
    """

    __slots__ = ("txn_id", "producer", "last_seq", "last_reply", "lock")

    def __init__(self, txn_id: str, producer) -> None:
        self.txn_id = txn_id
        self.producer = producer
        self.last_seq = 0
        self.last_reply: Optional[pb.TxnReply] = None
        self.lock = threading.Lock()


SERVICE = "surge_tpu.log.LogService"
METHODS = {
    "CreateTopic": (pb.CreateTopicRequest, pb.TopicReply),
    "GetTopic": (pb.TopicRequest, pb.TopicReply),
    "OpenProducer": (pb.OpenProducerRequest, pb.OpenProducerReply),
    "Transact": (pb.TxnRequest, pb.TxnReply),
    "Read": (pb.ReadRequest, pb.ReadReply),
    "EndOffset": (pb.OffsetRequest, pb.OffsetReply),
    "LatestByKey": (pb.OffsetRequest, pb.LatestByKeyReply),
    "WaitForAppend": (pb.WaitRequest, pb.WaitReply),
}


def record_to_msg(r: LogRecord) -> pb.RecordMsg:
    msg = pb.RecordMsg(topic=r.topic, partition=r.partition,
                       offset=r.offset, timestamp=r.timestamp)
    if r.key is not None:
        msg.has_key = True
        msg.key = r.key
    if r.value is not None:
        msg.has_value = True
        msg.value = r.value
    for k, v in r.headers.items():
        msg.headers[k] = v
    return msg


def msg_to_record(m: pb.RecordMsg) -> LogRecord:
    return LogRecord(topic=m.topic, key=m.key if m.has_key else None,
                     value=m.value if m.has_value else None,
                     partition=m.partition, headers=dict(m.headers),
                     offset=m.offset, timestamp=m.timestamp)


class LogServer:
    """gRPC facade over an in-process log. One instance per broker process."""

    def __init__(self, log, host: str = "127.0.0.1", port: int = 0,
                 config=None, max_workers: int = 32) -> None:
        self.log = log
        self._host = host
        self._port = port
        self._config = config
        self._max_workers = max_workers
        self._server: Optional[grpc.Server] = None
        self.bound_port: Optional[int] = None
        self._producers: Dict[int, "_ProducerState"] = {}  # by token
        self._fenced_tokens: "OrderedDict[int, None]" = OrderedDict()
        self._next_token = 1
        self._token_lock = threading.Lock()
        # long-poll waiters may not occupy more than half the handler pool, or
        # many tailing indexers would starve the Transact/Read command path
        self._wait_slots = threading.BoundedSemaphore(max(max_workers // 2, 1))

    # -- handlers (sync; called on the server thread pool) --------------------------------

    def CreateTopic(self, request: pb.CreateTopicRequest, context) -> pb.TopicReply:
        spec = TopicSpec(request.spec.name, request.spec.partitions or 1,
                         request.spec.compacted)
        self.log.create_topic(spec)
        return pb.TopicReply(found=True, spec=request.spec)

    def GetTopic(self, request: pb.TopicRequest, context) -> pb.TopicReply:
        try:
            spec = self.log.topic(request.name)
        except KeyError:
            return pb.TopicReply(found=False)
        return pb.TopicReply(found=True, spec=pb.TopicSpecMsg(
            name=spec.name, partitions=spec.partitions, compacted=spec.compacted))

    def OpenProducer(self, request: pb.OpenProducerRequest,
                     context) -> pb.OpenProducerReply:
        producer = self.log.transactional_producer(request.transactional_id)
        with self._token_lock:
            # prune tokens this open just fenced (the inner log fenced their
            # producers); remember them so a zombie client still gets the
            # protocol-correct "fenced" answer rather than "unknown token"
            for stale in [t for t, st in self._producers.items()
                          if st.txn_id == request.transactional_id]:
                del self._producers[stale]
                self._fenced_tokens[stale] = None
            while len(self._fenced_tokens) > 1024:
                self._fenced_tokens.popitem(last=False)
            token = self._next_token
            self._next_token += 1
            self._producers[token] = _ProducerState(
                request.transactional_id, producer)
        return pb.OpenProducerReply(producer_token=token)

    def Transact(self, request: pb.TxnRequest, context) -> pb.TxnReply:
        state = self._producers.get(request.producer_token)
        if state is None:
            if request.producer_token in self._fenced_tokens:
                return pb.TxnReply(ok=False, error="producer fenced",
                                   error_kind="fenced")
            return pb.TxnReply(ok=False, error="unknown producer token",
                               error_kind="state")
        records = [msg_to_record(m) for m in request.records]
        with state.lock:
            # idempotency window (txn_seq > 0): a replayed seq means the client
            # lost our reply and retried — answer from cache, never append twice
            if request.txn_seq:
                if request.txn_seq == state.last_seq:
                    if state.last_reply is not None:
                        return state.last_reply
                    return pb.TxnReply(ok=False, error="duplicate txn_seq with "
                                       "no cached reply", error_kind="state")
                if request.txn_seq < state.last_seq:
                    return pb.TxnReply(
                        ok=False, error_kind="state",
                        error=f"stale txn_seq {request.txn_seq} "
                              f"(last {state.last_seq})")
            try:
                if request.op == "commit":
                    state.producer.begin()
                    for r in records:
                        state.producer.send(r)
                    committed = state.producer.commit()
                elif request.op == "abort":
                    # transactions buffer client-side; nothing to discard here
                    committed = []
                elif request.op == "send_immediate":
                    committed = [state.producer.send_immediate(r)
                                 for r in records]
                else:
                    return pb.TxnReply(ok=False, error_kind="state",
                                       error=f"unknown op {request.op!r}")
            except ProducerFencedError as exc:
                return pb.TxnReply(ok=False, error=str(exc), error_kind="fenced")
            except TransactionStateError as exc:
                return pb.TxnReply(ok=False, error=str(exc), error_kind="state")
            except Exception as exc:  # noqa: BLE001 — surface inner-log failures
                logger.exception("log server transact failed")
                return pb.TxnReply(ok=False, error=repr(exc), error_kind="other")
            reply = pb.TxnReply(ok=True,
                                records=[record_to_msg(r) for r in committed])
            if request.txn_seq:
                state.last_seq = request.txn_seq
                state.last_reply = reply
            return reply

    def Read(self, request: pb.ReadRequest, context) -> pb.ReadReply:
        max_records = request.max_records if request.has_max else None
        recs = self.log.read(request.topic, request.partition,
                             from_offset=request.from_offset,
                             max_records=max_records)
        return pb.ReadReply(records=[record_to_msg(r) for r in recs])

    def EndOffset(self, request: pb.OffsetRequest, context) -> pb.OffsetReply:
        return pb.OffsetReply(
            end_offset=self.log.end_offset(request.topic, request.partition))

    def LatestByKey(self, request: pb.OffsetRequest,
                    context) -> pb.LatestByKeyReply:
        latest = self.log.latest_by_key(request.topic, request.partition)
        return pb.LatestByKeyReply(records=[record_to_msg(r)
                                            for r in latest.values()])

    def WaitForAppend(self, request: pb.WaitRequest, context) -> pb.WaitReply:
        def check() -> bool:
            return (self.log.end_offset(request.topic, request.partition)
                    > request.after_offset)

        if not self._wait_slots.acquire(blocking=False):
            # pool contended: answer immediately (the client paces its retry)
            return pb.WaitReply(appended=check())
        try:
            deadline = time.monotonic() + max(request.timeout_s, 0.01)
            while time.monotonic() < deadline:
                if check():
                    return pb.WaitReply(appended=True)
                time.sleep(0.02)
            return pb.WaitReply(appended=False)
        finally:
            self._wait_slots.release()

    # -- lifecycle ------------------------------------------------------------------------

    def start(self) -> int:
        from surge_tpu.remote.security import server_credentials, tls_enabled

        rpc = {}
        for name, (req_cls, reply_cls) in METHODS.items():
            rpc[name] = grpc.unary_unary_rpc_method_handler(
                getattr(self, name), request_deserializer=req_cls.FromString,
                response_serializer=reply_cls.SerializeToString)
        self._server = grpc.server(
            futures.ThreadPoolExecutor(max_workers=self._max_workers))
        self._server.add_generic_rpc_handlers(
            (grpc.method_handlers_generic_handler(SERVICE, rpc),))
        address = f"{self._host}:{self._port}"
        if tls_enabled(self._config):
            self.bound_port = self._server.add_secure_port(
                address, server_credentials(self._config))
        else:
            self.bound_port = self._server.add_insecure_port(address)
        self._server.start()
        return self.bound_port

    def stop(self, grace: float = 1.0) -> None:
        if self._server is not None:
            self._server.stop(grace).wait()
            self._server = None

    # aliases kept for symmetry with the asyncio-hosted servers
    serve_background = start
    shutdown_background = stop
