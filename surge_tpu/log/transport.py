"""Transport-neutral log contracts.

The engine only touches these protocols, mirroring how the reference's engine depends on
``KafkaProducerTrait``/``KafkaConsumerTrait`` rather than concrete clients
(modules/common/src/main/scala/surge/kafka/KafkaProducer.scala:18-66) — the seam its
entire test suite injects through (SURVEY.md §4). Semantics preserved from the Kafka
substrate:

- **Atomic multi-topic transactional append** (events topic + state topic in one commit;
  KafkaProducer.scala:106-117 begin/commit/abort).
- **Producer-epoch fencing**: opening a transactional producer with an id fences every
  earlier producer holding the same id; fenced producers fail with
  :class:`ProducerFencedError` (the zombie-writer exclusion the single-writer guarantee
  rests on — KafkaProducerActorImpl.scala:502-528).
- **read_committed isolation**: consumers at ``read_committed`` never observe records of
  open or aborted transactions (SurgeStateStoreConsumer.scala:38).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Mapping, Optional, Protocol, Sequence


class ProducerFencedError(Exception):
    """A newer producer with the same transactional id has been opened; this instance
    is a zombie and must never write again (KafkaProducerActorImpl.scala:502-510)."""


class NotLeaderError(Exception):
    """The addressed broker is a follower (or a fenced ex-leader); writes must
    go to the leader. ``leader_hint`` carries its address when known."""

    def __init__(self, message: str, leader_hint: str = "") -> None:
        super().__init__(message)
        self.leader_hint = leader_hint


class FaultInjector(Protocol):
    """The hook surface the log substrate consults when a fault plane is
    armed (:class:`surge_tpu.testing.faults.FaultPlane` is the one
    implementation; production code only depends on this seam, so the
    testing package never loads unless a plan is actually armed).

    Every hook is called at a named SITE; an unarmed plane answers None /
    returns without effect, so hot paths pay one attribute check."""

    def on_rpc(self, method: str): ...

    def on_ship(self, target: str) -> Optional[str]: ...

    def on_fsync(self, which: str) -> None: ...

    def torn(self, site: str, data: bytes) -> Optional[bytes]: ...

    def crash_point(self, name: str) -> None: ...

    def raise_point(self, site: str) -> None: ...


def load_fault_plane(config) -> Optional[FaultInjector]:
    """Build the configured fault plane (``surge.log.faults.plan``), lazily
    importing the testing package only when a plan is armed."""
    if config is None or not config.get_str("surge.log.faults.plan", ""):
        return None
    from surge_tpu.testing.faults import FaultPlane

    return FaultPlane.from_config(config)


class TransactionStateError(Exception):
    """Illegal transaction op for the current state (commit without begin, etc.)."""


@dataclass(frozen=True)
class TopicSpec:
    """Topic metadata. ``compacted`` marks state topics (latest-record-per-key retention,
    overview.md:8-63: the compacted state topic IS the durable aggregate store)."""

    name: str
    partitions: int = 1
    compacted: bool = False


@dataclass(frozen=True)
class LogRecord:
    """One record on a topic-partition. ``value=None`` is a tombstone (deletes the key
    from a compacted topic). ``offset``/``timestamp`` are assigned by the log."""

    topic: str
    key: Optional[str]
    value: Optional[bytes]
    partition: int = 0
    headers: Mapping[str, str] = field(default_factory=dict)
    offset: int = -1
    timestamp: float = 0.0


class TransactionalProducer(Protocol):
    """Handle for one transactional id (single-writer per id via epoch fencing)."""

    def begin(self) -> None: ...

    def send(self, record: LogRecord) -> None:
        """Buffer a record into the open transaction."""

    def commit(self) -> Sequence[LogRecord]:
        """Atomically append the buffered records; returns them with offsets assigned.
        All records become visible to read_committed consumers at once."""

    def abort(self) -> None:
        """Discard the open transaction's records."""

    def send_immediate(self, record: LogRecord) -> LogRecord:
        """Non-transactional single-record append (the opt-in fast path behind the
        reference's disable-single-record-transactions flag,
        KafkaProducerActorImpl.scala:455-468). Still epoch-fenced."""

    @property
    def fenced(self) -> bool: ...


class LogTransport(Protocol):
    """The log service: topics, producers, reads, offsets.

    Reads are pull-based with an async wait primitive instead of callback consumers —
    idiomatic for asyncio indexer tasks (the KafkaConsumerTrait poll-thread analog,
    KafkaConsumer.scala:17-132).
    """

    def create_topic(self, spec: TopicSpec) -> None: ...

    def topic(self, name: str) -> TopicSpec: ...

    def num_partitions(self, name: str) -> int: ...

    def transactional_producer(self, transactional_id: str) -> TransactionalProducer:
        """Open (and fence any prior holder of) ``transactional_id``."""

    def read(self, topic: str, partition: int, from_offset: int = 0,
             max_records: Optional[int] = None,
             isolation: str = "read_committed") -> Sequence[LogRecord]: ...

    def end_offset(self, topic: str, partition: int,
                   isolation: str = "read_committed") -> int:
        """Next offset to be assigned (read_committed: the last stable offset)."""

    def latest_by_key(self, topic: str, partition: int,
                      isolation: str = "read_committed") -> Mapping[str, LogRecord]:
        """Compacted view: latest non-tombstone record per key (what a compacted topic
        retains; the bulk-restore read path)."""

    async def wait_for_append(self, topic: str, partition: int,
                              after_offset: int) -> None:
        """Resolve once ``end_offset`` exceeds ``after_offset`` (consumer wakeup)."""


def page_keyed_records(log, topic: str, partition: int, *,
                       start: int = 0, upto: Optional[int] = None,
                       page: int = 10_000):
    """Offset-paged scan of one partition's keyed records (tombstones and
    keyless records skipped) — the shared bulk-scan loop of segment builds and
    bounded restores. ``upto`` clamps the scan to a pre-captured watermark so
    multi-pass consumers see ONE consistent snapshot of a live topic: records
    committed after the watermark are left for the tailing indexer instead of
    being half-seen across passes."""
    offset = start
    while True:
        if upto is not None and offset >= upto:
            return
        batch = log.read(topic, partition, from_offset=offset,
                         max_records=page)
        if not batch:
            return
        for r in batch:
            if upto is not None and r.offset >= upto:
                return
            if r.key is not None and r.value is not None:
                yield r
        offset = batch[-1].offset + 1
