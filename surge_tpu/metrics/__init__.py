"""Metrics registry — sensors fanning values into statistics providers.

Equivalent of modules/metrics/src/main/scala/surge/metrics/Metrics.scala:126-228 +
Sensor.scala:9-39: a named-sensor registry where each sensor updates one or more
:mod:`~surge_tpu.metrics.statistics` providers, with recording levels
(``surge.metrics.recording-level``: Info < Debug < Trace, MetricsConfig), the
high-level instrument types (counter / gauge / timer / rate), snapshot export
(``get_metrics`` / ``metric_descriptions`` / ``as_html`` — Metrics.scala:220-281), and
the ~20 predeclared engine metrics (Metrics.scala:20-115) via :func:`engine_metrics`.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from enum import IntEnum
from typing import Dict, List, Optional

from surge_tpu.metrics.statistics import (
    Count,
    ExponentialWeightedMovingAverage,
    FusedTimerStats,
    Max,
    MetricValueProvider,
    Min,
    MostRecentValue,
    RateHistogram,
    TimeBucketHistogram,
)

__all__ = [
    "MetricInfo",
    "Metrics",
    "RecordingLevel",
    "Sensor",
    "Timer",
    "engine_metrics",
]


class RecordingLevel(IntEnum):
    """Metrics.scala RecordingLevel: a sensor records iff its level <= configured."""

    INFO = 0
    DEBUG = 1
    TRACE = 2


@dataclass(frozen=True)
class MetricInfo:
    name: str
    description: str = ""
    tags: tuple = ()


@dataclass
class _Registered:
    info: MetricInfo
    provider: MetricValueProvider


class Sensor:
    """One named recording point fanning into N providers (Sensor.scala:9-39)."""

    def __init__(self, name: str, level: RecordingLevel, enabled: bool) -> None:
        self.name = name
        self.level = level
        self.enabled = enabled
        self._providers: List[MetricValueProvider] = []

    def add_metric(self, info: MetricInfo, provider: MetricValueProvider,
                   registry: "Metrics") -> None:
        self._providers.append(provider)
        registry._register(info, provider)

    def record(self, value: float = 1.0, timestamp: Optional[float] = None) -> None:
        if not self.enabled:
            return
        ts = timestamp if timestamp is not None else time.time()
        for p in self._providers:
            p.update(value, ts)


class _TimerContext:
    """Slots-based timing context: ``@contextmanager`` generators cost ~10us
    per use, and the engine opens several timer contexts per command."""

    __slots__ = ("_sensor", "_t0")

    def __init__(self, sensor: Sensor) -> None:
        self._sensor = sensor

    def __enter__(self) -> "_TimerContext":
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc) -> bool:
        self._sensor.record((time.perf_counter() - self._t0) * 1000.0)
        return False


class Timer:
    """EWMA + min/max/p99 over millisecond durations (the reference timer shape)."""

    def __init__(self, sensor: Sensor) -> None:
        self._sensor = sensor

    def record_ms(self, ms: float) -> None:
        self._sensor.record(ms)

    def time(self) -> _TimerContext:
        return _TimerContext(self._sensor)

    async def time_async(self, awaitable):
        t0 = time.perf_counter()
        try:
            return await awaitable
        finally:
            self.record_ms((time.perf_counter() - t0) * 1000.0)


class Metrics:
    """The registry (Metrics.scala:126-228).

    ``exemplars=True`` makes every timer's histogram capture the active trace
    id per recording (OpenMetrics exemplars — docs/observability.md); off by
    default so the engine hot path pays nothing."""

    def __init__(self, recording_level: RecordingLevel = RecordingLevel.INFO,
                 exemplars: bool = False) -> None:
        self.recording_level = recording_level
        self.exemplars = exemplars
        self._sensors: Dict[str, Sensor] = {}
        self._metrics: Dict[str, _Registered] = {}

    # -- core ---------------------------------------------------------------------------

    def sensor(self, name: str, level: RecordingLevel = RecordingLevel.INFO) -> Sensor:
        if name not in self._sensors:
            self._sensors[name] = Sensor(name, level,
                                         enabled=level <= self.recording_level)
        return self._sensors[name]

    def _register(self, info: MetricInfo, provider: MetricValueProvider) -> None:
        self._metrics[info.name] = _Registered(info, provider)

    # -- instruments --------------------------------------------------------------------

    def counter(self, info: MetricInfo, level: RecordingLevel = RecordingLevel.INFO) -> Sensor:
        s = self.sensor(info.name, level)
        if info.name not in self._metrics:
            s.add_metric(info, Count(), self)
        return s

    def gauge(self, info: MetricInfo, level: RecordingLevel = RecordingLevel.INFO) -> Sensor:
        s = self.sensor(info.name, level)
        if info.name not in self._metrics:
            s.add_metric(info, MostRecentValue(), self)
        return s

    def timer(self, info: MetricInfo, level: RecordingLevel = RecordingLevel.INFO) -> Timer:
        s = self.sensor(info.name, level)
        if info.name not in self._metrics:
            # ONE fused provider records all four statistics per observation
            # (the pre-fusion layout dispatched four provider updates per
            # recording — a real cost at per-command rates); the export names
            # are unchanged: the fused provider itself reports the EWMA under
            # the base name, min/max export through views, and .p99 registers
            # the embedded histogram so the OpenMetrics exposition still sees
            # a real TimeBucketHistogram
            fused = FusedTimerStats(TimeBucketHistogram(
                exemplars=self.exemplars))
            s.add_metric(info, fused, self)
            self._register(MetricInfo(f"{info.name}.min",
                                      f"min of {info.name}"),
                           fused.min_view())
            self._register(MetricInfo(f"{info.name}.max",
                                      f"max of {info.name}"),
                           fused.max_view())
            self._register(MetricInfo(f"{info.name}.p99",
                                      f"p99 of {info.name}"),
                           fused.histogram)
        return Timer(s)

    def rate(self, info: MetricInfo, level: RecordingLevel = RecordingLevel.INFO) -> Sensor:
        """1/5/15-minute event rates (Metrics.scala rate registration)."""
        s = self.sensor(info.name, level)
        if f"{info.name}.one-minute-rate" not in self._metrics:
            for label, secs in (("one-minute-rate", 60.0), ("five-minute-rate", 300.0),
                                ("fifteen-minute-rate", 900.0)):
                s.add_metric(MetricInfo(f"{info.name}.{label}", info.description),
                             RateHistogram(secs), self)
        return s

    # -- export (Metrics.scala:220-281) --------------------------------------------------

    def get_metrics(self) -> Dict[str, float]:
        return {name: r.provider.get_value() for name, r in sorted(self._metrics.items())}

    def metric_descriptions(self) -> Dict[str, str]:
        return {name: r.info.description for name, r in sorted(self._metrics.items())}

    def as_html(self) -> str:
        rows = "".join(
            f"<tr><td>{name}</td><td>{value:.4g}</td></tr>"
            for name, value in self.get_metrics().items())
        return f"<table><tr><th>metric</th><th>value</th></tr>{rows}</table>"


# -- predeclared engine metrics (Metrics.scala:20-115 + PersistentActor MetricsQuiver) --


@dataclass
class EngineMetrics:
    """The standard engine instrument set, created once per engine."""

    registry: Metrics
    state_fetch_timer: Timer = field(init=False)
    command_handling_timer: Timer = field(init=False)
    event_handling_timer: Timer = field(init=False)
    serialization_timer: Timer = field(init=False)
    deserialization_timer: Timer = field(init=False)
    publish_timer: Timer = field(init=False)
    flush_timer: Timer = field(init=False)
    replay_timer: Timer = field(init=False)
    command_rate: Sensor = field(init=False)
    rejection_rate: Sensor = field(init=False)
    error_rate: Sensor = field(init=False)
    publish_failure_counter: Sensor = field(init=False)
    fence_counter: Sensor = field(init=False)
    # group-commit publisher lane instruments (surge_tpu.engine.publisher):
    # batch formation, adaptive linger, and the pipelined in-flight window
    producer_batch_records: Sensor = field(init=False)
    producer_batch_commits: Sensor = field(init=False)
    producer_linger_timer: Timer = field(init=False)
    producer_in_flight: Sensor = field(init=False)
    producer_lane_pending: Sensor = field(init=False)
    replay_events_per_sec: Sensor = field(init=False)
    live_entities: Sensor = field(init=False)
    standby_lag: Sensor = field(init=False)
    # per-stage replay profile (DEBUG level: free at INFO, populated by
    # surge_tpu.replay.profiler when a profiler is attached to the engine)
    replay_encode_timer: Timer = field(init=False)
    replay_h2d_timer: Timer = field(init=False)
    replay_compile_timer: Timer = field(init=False)
    replay_dispatch_timer: Timer = field(init=False)
    replay_fetch_timer: Timer = field(init=False)
    replay_refresh_timer: Timer = field(init=False)
    replay_profile_windows: Sensor = field(init=False)
    # device-resident materialized state plane (surge_tpu.replay.resident_state):
    # the on-chip KTable's occupancy, incremental-fold cadence and read lane
    resident_occupancy: Sensor = field(init=False)
    resident_fold_round_timer: Timer = field(init=False)
    resident_feed_timer: Timer = field(init=False)
    resident_fold_lag: Sensor = field(init=False)
    resident_gather_batch: Sensor = field(init=False)
    resident_fallbacks: Sensor = field(init=False)
    resident_fallbacks_lag: Sensor = field(init=False)
    resident_fallbacks_lane_error: Sensor = field(init=False)
    resident_fallbacks_poison: Sensor = field(init=False)
    resident_fallbacks_untracked: Sensor = field(init=False)
    resident_evictions: Sensor = field(init=False)
    # device observatory (replay/ledger.py): per-round padding-waste and
    # dispatch-efficiency accounting off the refresh-round ledger
    resident_padding_waste_ratio: Sensor = field(init=False)
    resident_dispatch_occupancy: Sensor = field(init=False)
    resident_events_per_dispatch_us: Sensor = field(init=False)
    resident_round_events: Sensor = field(init=False)
    resident_shard_skew: Sensor = field(init=False)
    resident_bucket_dispatches: Sensor = field(init=False)
    resident_bucket_fill_ratio: Sensor = field(init=False)
    # TPU scan engine over columnar segments (surge_tpu.replay.query): the
    # analytics plane's scan cadence and coverage
    query_scan_timer: Timer = field(init=False)
    query_scanned_events: Sensor = field(init=False)
    query_result_rows: Sensor = field(init=False)
    query_scan_rows: Sensor = field(init=False)
    query_pushdown_selectivity: Sensor = field(init=False)
    # incremental materialized views + changefeeds (surge_tpu.replay.views):
    # per-round view folds off the resident plane's refresh feed
    views_fold_timer: Timer = field(init=False)
    views_delta_rows: Sensor = field(init=False)
    views_subscribers: Sensor = field(init=False)
    views_resume_gap_rounds: Sensor = field(init=False)
    # log compaction + state checkpoints (surge_tpu.log.compactor /
    # surge_tpu.store.checkpoint — the bounded-cold-start subsystem)
    compaction_runs: Sensor = field(init=False)
    compaction_bytes_reclaimed: Sensor = field(init=False)
    compaction_records_dropped: Sensor = field(init=False)
    compaction_timer: Timer = field(init=False)
    compaction_max_dirty_ratio: Sensor = field(init=False)
    checkpoint_writes: Sensor = field(init=False)
    checkpoint_events_folded: Sensor = field(init=False)
    checkpoint_timer: Timer = field(init=False)
    checkpoint_age: Sensor = field(init=False)
    checkpoint_lag_events: Sensor = field(init=False)
    # leader failover + fault-injection plane (surge_tpu.log.server /
    # surge_tpu.log.client / surge_tpu.testing.faults)
    failover_promotions: Sensor = field(init=False)
    failover_fencings: Sensor = field(init=False)
    failover_truncated_records: Sensor = field(init=False)
    failover_redirects: Sensor = field(init=False)
    failover_rolls: Sensor = field(init=False)
    # client-side failover latency histograms (surge_tpu.log.client): the
    # redirect/roll reconnect cost and the jittered backoff actually slept —
    # their buckets carry OpenMetrics exemplars when the registry has
    # exemplar capture on (the active-span contextvar is threaded through
    # the pipelined retry pool, so a failover bucket links to the command
    # trace that rode through the failover)
    failover_redirect_timer: Timer = field(init=False)
    failover_backoff_timer: Timer = field(init=False)
    faults_injected: Sensor = field(init=False)
    faults_armed: Sensor = field(init=False)
    # tail-based trace sampling (surge_tpu.tracing.tail): the engine-side
    # kept/dropped tallies and the in-flight span-buffer gauge — shared
    # names with the broker quiver, same pattern as the failover counters
    trace_kept: Sensor = field(init=False)
    trace_dropped: Sensor = field(init=False)
    trace_tail_buffer: Sensor = field(init=False)
    # saga / process-manager plane (surge_tpu.saga.manager): the driver
    # population and terminal-outcome tallies of this engine's SagaManager
    saga_active: Sensor = field(init=False)
    saga_completed: Sensor = field(init=False)
    saga_compensated: Sensor = field(init=False)
    saga_dead_letter: Sensor = field(init=False)
    saga_step_timer: Timer = field(init=False)
    # consistency observatory (surge_tpu.observability.audit): the shadow-
    # replay / digest-compare / dedup-probe findings and cadence
    audit_rounds: Sensor = field(init=False)
    audit_cohort_size: Sensor = field(init=False)
    audit_divergent_rows: Sensor = field(init=False)
    audit_digest_mismatches: Sensor = field(init=False)
    audit_dedup_holes: Sensor = field(init=False)
    audit_unresolved: Sensor = field(init=False)
    audit_round_timer: Timer = field(init=False)

    def __post_init__(self) -> None:
        m, MI = self.registry, MetricInfo
        self.state_fetch_timer = m.timer(MI(
            "surge.aggregate.state-fetch-timer", "ms to fetch state from the store"))
        self.command_handling_timer = m.timer(MI(
            "surge.aggregate.command-handling-timer", "ms in process_command"))
        self.event_handling_timer = m.timer(MI(
            "surge.aggregate.event-handling-timer", "ms folding events"))
        self.serialization_timer = m.timer(MI(
            "surge.aggregate.state-serialization-timer", "ms serializing outputs"))
        self.deserialization_timer = m.timer(MI(
            "surge.aggregate.state-deserialization-timer", "ms deserializing snapshots"))
        self.publish_timer = m.timer(MI(
            "surge.aggregate.event-publish-timer", "ms from publish to commit ack"))
        self.flush_timer = m.timer(MI(
            "surge.producer.flush-timer", "ms per flush transaction"))
        self.replay_timer = m.timer(MI(
            "surge.replay.rebuild-timer",
            "ms per bulk state rebuild (segment build if any + replay fold + "
            "snapshot overlay + indexer prime)"))
        self.command_rate = m.rate(MI(
            "surge.engine.command-rate", "commands processed"))
        self.rejection_rate = m.rate(MI(
            "surge.engine.rejection-rate", "commands rejected"))
        self.error_rate = m.rate(MI(
            "surge.engine.error-rate", "command failures"))
        self.publish_failure_counter = m.counter(MI(
            "surge.producer.publish-failures", "failed publish batches"))
        self.fence_counter = m.counter(MI(
            "surge.producer.fences", "producer fencing events"))
        self.producer_batch_records = m.gauge(MI(
            "surge.producer.batch-records",
            "records in the last committed publish batch (group-commit size)"))
        self.producer_batch_commits = m.counter(MI(
            "surge.producer.batch-commits",
            "committed publish batches (group commits)"))
        self.producer_linger_timer = m.timer(MI(
            "surge.producer.linger-timer",
            "ms a batch's FIRST publish waited from enqueue to commit "
            "dispatch (the adaptive linger actually paid)"))
        self.producer_in_flight = m.gauge(MI(
            "surge.producer.in-flight-txns",
            "pipelined publish transactions in flight on the last lane to "
            "record (bounded by surge.producer.max-in-flight)"))
        self.producer_lane_pending = m.gauge(MI(
            "surge.producer.lane-pending",
            "publishes still queued in the recording lane after a batch "
            "was drained (backpressure indicator)"))
        self.replay_events_per_sec = m.gauge(MI(
            "surge.replay.rebuild-events-per-sec",
            "events/s of the latest bulk rebuild, end to end (compare "
            "bench.py's cold_replay_events_per_sec for the fold alone)"))
        self.live_entities = m.gauge(MI(
            "surge.engine.live-entities", "currently resident aggregate entities"))
        self.standby_lag = m.gauge(MI(
            "surge.state-store.standby-lag",
            "records behind on partitions this node is warm standby for"))
        dbg = RecordingLevel.DEBUG
        self.replay_encode_timer = m.timer(MI(
            "surge.replay.profile.encode-timer",
            "ms host-side wire-packing/bucketing per replay window"), level=dbg)
        self.replay_h2d_timer = m.timer(MI(
            "surge.replay.profile.h2d-timer",
            "ms transferring a replay window/corpus host-to-device"), level=dbg)
        self.replay_compile_timer = m.timer(MI(
            "surge.replay.profile.compile-timer",
            "ms of fold dispatches that triggered an XLA compile"), level=dbg)
        self.replay_dispatch_timer = m.timer(MI(
            "surge.replay.profile.dispatch-timer",
            "ms of steady (pre-compiled) fold dispatches"), level=dbg)
        self.replay_fetch_timer = m.timer(MI(
            "surge.replay.profile.fetch-timer",
            "ms from dispatch to the fetch barrier closing device time "
            "(a real device-to-host fetch, never block_until_ready)"), level=dbg)
        self.replay_refresh_timer = m.timer(MI(
            "surge.replay.profile.refresh-timer",
            "ms per incremental resident-plane refresh round "
            "(encode + h2d + fold dispatch of one committed batch)"),
            level=dbg)
        self.replay_profile_windows = m.counter(MI(
            "surge.replay.profile.windows",
            "replay windows/tiles observed by the profiler"), level=dbg)
        self.resident_occupancy = m.gauge(MI(
            "surge.replay.resident.slab-occupancy",
            "aggregates resident in the on-device state slab"))
        self.resident_fold_round_timer = m.timer(MI(
            "surge.replay.resident.fold-round-timer",
            "ms per incremental fold round (committed batch -> slab)"))
        self.resident_feed_timer = m.timer(MI(
            "surge.replay.resident.feed-timer",
            "ms per refresh round's host feed leg: committed-tail read "
            "(native record-index views) + event deserialize (one batch "
            "decode on the native feed; surge.replay.resident.native-feed)"))
        self.resident_fold_lag = m.gauge(MI(
            "surge.replay.resident.fold-lag-records",
            "events committed past the plane's fold watermarks (reads fall "
            "back to the host store beyond "
            "surge.replay.resident.max-lag-records)"))
        self.resident_gather_batch = m.gauge(MI(
            "surge.replay.resident.gather-batch-size",
            "reads coalesced into the last device gather (the d2h "
            "amortization the batched read path exists for)"))
        self.resident_fallbacks = m.counter(MI(
            "surge.replay.resident.fallback-reads",
            "reads answered by the host KV store instead of the device "
            "slab (every cause; the .lag-exceeded/.lane-error/"
            ".unschema-poison/.untracked splits name why)"))
        self.resident_fallbacks_lag = m.counter(MI(
            "surge.replay.resident.fallback-reads.lag-exceeded",
            "fallback reads whose partition fold watermark lagged past "
            "surge.replay.resident.max-lag-records (or require_current "
            "demanded lag 0)"))
        self.resident_fallbacks_lane_error = m.counter(MI(
            "surge.replay.resident.fallback-reads.lane-error",
            "fallback reads failed over by a gather-lane device/decode "
            "error (the batch went to the host store)"))
        self.resident_fallbacks_poison = m.counter(MI(
            "surge.replay.resident.fallback-reads.unschema-poison",
            "fallback reads of aggregates poisoned off the tensor path "
            "(an event outside the replay schema)"))
        self.resident_fallbacks_untracked = m.counter(MI(
            "surge.replay.resident.fallback-reads.untracked",
            "fallback reads of aggregates the plane does not track "
            "(never admitted, revoked, or the plane is stopped/unseeded)"))
        self.resident_evictions = m.counter(MI(
            "surge.replay.resident.evictions",
            "aggregates evicted from the slab to the host spill "
            "(capacity pressure)"))
        self.resident_padding_waste_ratio = m.gauge(MI(
            "surge.replay.resident.padding-waste-ratio",
            "last refresh round's dispatched-to-occupied event-slot ratio "
            "(lane bucket x window width over events folded; the "
            "over-dispatch the fold-efficiency SLO bounds)"))
        self.resident_dispatch_occupancy = m.gauge(MI(
            "surge.replay.resident.dispatch-occupancy",
            "last refresh round's occupied fraction of dispatched event "
            "slots (1 / padding-waste-ratio)"))
        self.resident_events_per_dispatch_us = m.gauge(MI(
            "surge.replay.resident.events-per-dispatch-us",
            "events folded per microsecond of device fold dispatch in the "
            "last refresh round (the fold roofline's measured ev/us)"))
        self.resident_round_events = m.gauge(MI(
            "surge.replay.resident.round-events",
            "events folded by the last refresh round"))
        self.resident_shard_skew = m.gauge(MI(
            "surge.replay.resident.shard-skew",
            "last refresh round's max/mean lane-deal imbalance across mesh "
            "shards (1.0 = perfectly balanced; single-device rounds read 1)"))
        self.resident_bucket_dispatches = m.gauge(MI(
            "surge.replay.resident.bucket-dispatches",
            "bucket refresh programs dispatched by the last refresh round "
            "(one fused admission+fold+scatter per occupied length bucket; "
            "dense rounds read 1 per fold group)"))
        self.resident_bucket_fill_ratio = m.gauge(MI(
            "surge.replay.resident.bucket-fill-ratio",
            "occupied fraction of the last refresh round's dispatched lane "
            "slots (lanes dealt over pow2 lane-bucket capacity summed across "
            "bucket programs; 1.0 = every dispatched lane held an aggregate)"))
        self.query_scan_timer = m.timer(MI(
            "surge.query.scan-timer",
            "ms per segment scan / state query (device dispatch + the one "
            "result pull; mesh scans add one collective per output column)"))
        self.query_scanned_events = m.counter(MI(
            "surge.query.scanned-events",
            "events scanned by the query engine (projection pushdown means "
            "untouched columns were never decompressed)"))
        self.query_result_rows = m.gauge(MI(
            "surge.query.result-rows",
            "aggregates in the last query result (post-filter, pre-RPC "
            "surge.query.max-rows cap)"))
        self.query_scan_rows = m.counter(MI(
            "surge.query.scan-rows",
            "result rows emitted by the query engine across scans "
            "(cumulative twin of the per-scan result-rows gauge)"))
        self.query_pushdown_selectivity = m.gauge(MI(
            "surge.query.pushdown-selectivity",
            "matched/scanned event fraction of the last scan (how much the "
            "predicate pushdown narrowed before grouping)"))
        self.views_fold_timer = m.timer(MI(
            "surge.replay.views.fold-timer",
            "ms per materialized-view fold round (all registered views' "
            "incremental folds of one refresh round's committed tail)"))
        self.views_delta_rows = m.counter(MI(
            "surge.replay.views.delta-rows",
            "changed view rows emitted to changefeed deltas across fold "
            "rounds"))
        self.views_subscribers = m.gauge(MI(
            "surge.replay.views.subscribers",
            "live changefeed subscriptions across materialized views"))
        self.views_resume_gap_rounds = m.gauge(MI(
            "surge.replay.views.resume-gap-rounds",
            "fold rounds bridged by the last reconciling snapshot (a resume "
            "watermark older than the delta ring, or from the future)"))
        self.compaction_runs = m.counter(MI(
            "surge.log.compaction.runs", "partition compaction passes"))
        self.compaction_bytes_reclaimed = m.counter(MI(
            "surge.log.compaction.bytes-reclaimed",
            "segment bytes reclaimed by compaction"))
        self.compaction_records_dropped = m.counter(MI(
            "surge.log.compaction.records-dropped",
            "superseded records + GC'd tombstones dropped by compaction"))
        self.compaction_timer = m.timer(MI(
            "surge.log.compaction.duration-timer",
            "ms per partition compaction pass"))
        self.compaction_max_dirty_ratio = m.gauge(MI(
            "surge.log.compaction.max-dirty-ratio",
            "max dirty ratio across compacted partitions at the last "
            "scheduler wake"))
        self.checkpoint_writes = m.counter(MI(
            "surge.store.checkpoint.writes", "state checkpoints written"))
        self.checkpoint_events_folded = m.counter(MI(
            "surge.store.checkpoint.events-folded",
            "events folded by the incremental checkpoint materializer"))
        self.checkpoint_timer = m.timer(MI(
            "surge.store.checkpoint.duration-timer",
            "ms per checkpoint advance+write"))
        self.checkpoint_age = m.gauge(MI(
            "surge.store.checkpoint.age-seconds",
            "seconds since the newest durable checkpoint"))
        self.checkpoint_lag_events = m.gauge(MI(
            "surge.store.checkpoint.lag-events",
            "events committed past the newest checkpoint's watermarks "
            "(the cold-start tail a restore would fold)"))
        self.failover_promotions = m.counter(MI(
            "surge.log.failover.promotions",
            "follower-to-leader promotions performed by this process's "
            "broker (admin RPC or leader-death prober)"))
        self.failover_fencings = m.counter(MI(
            "surge.log.failover.fencings",
            "leader-epoch fences observed: this broker was deposed and "
            "demoted to follower"))
        self.failover_truncated_records = m.counter(MI(
            "surge.log.failover.truncated-records",
            "divergent unreplicated records truncated on demotion "
            "(KIP-101 tail rollback to the new leader's epoch-start)"))
        self.failover_redirects = m.counter(MI(
            "surge.log.failover.redirects",
            "NOT_LEADER redirects this client followed to the hinted leader"))
        self.failover_rolls = m.counter(MI(
            "surge.log.failover.client-rolls",
            "broker-endpoint-list failovers after UNAVAILABLE (the client "
            "rolled to the next broker)"))
        self.failover_redirect_timer = m.timer(MI(
            "surge.log.failover.redirect-timer",
            "ms per client reconnect onto a hinted/next broker (NOT_LEADER "
            "redirect follow or UNAVAILABLE endpoint roll) — the wiring "
            "half of client-visible failover latency"))
        self.failover_backoff_timer = m.timer(MI(
            "surge.log.failover.backoff-timer",
            "ms actually slept per jittered client retry backoff "
            "(mid-promotion waits; the patience half of client-visible "
            "failover latency)"))
        self.faults_injected = m.counter(MI(
            "surge.log.faults.injected",
            "faults fired by the armed fault-injection plane"))
        self.faults_armed = m.gauge(MI(
            "surge.log.faults.armed",
            "fault rules currently armed on this process's plane "
            "(0 outside chaos experiments)"))
        self.trace_kept = m.counter(MI(
            "surge.trace.kept",
            "traces the tail sampler kept into this process's trace ring "
            "(erred, breached surge.trace.tail.latency-ms, landed in an SLO "
            "breach window, or explicitly marked)"))
        self.trace_dropped = m.counter(MI(
            "surge.trace.dropped",
            "completed or evicted traces the tail sampler dropped "
            "(sampled-out, over the keep budget, or evicted by the span-"
            "buffer bound)"))
        self.trace_tail_buffer = m.gauge(MI(
            "surge.trace.tail-buffer-spans",
            "spans buffered for in-flight traces awaiting their tail "
            "keep/drop decision (bounded by "
            "surge.trace.tail.max-buffer-spans)"))
        self.saga_active = m.gauge(MI(
            "surge.saga.active",
            "in-flight sagas with a live driver task on this manager"))
        self.saga_completed = m.counter(MI(
            "surge.saga.completed",
            "sagas that reached COMPLETED (every step committed)"))
        self.saga_compensated = m.counter(MI(
            "surge.saga.compensated",
            "sagas that reached COMPENSATED (every committed step undone)"))
        self.saga_dead_letter = m.counter(MI(
            "surge.saga.dead-letter",
            "sagas parked in the dead letter (a compensation was rejected "
            "or exhausted its retry budget — operator intervention needed)"))
        self.saga_step_timer = m.timer(MI(
            "surge.saga.step-timer",
            "ms per saga step dispatch (forward or compensation), command "
            "send to participant ack"))
        self.audit_rounds = m.counter(MI(
            "surge.audit.rounds",
            "consistency-audit cycles completed (shadow replay + digest "
            "compare + dedup probe)"))
        self.audit_cohort_size = m.gauge(MI(
            "surge.audit.cohort-size",
            "resident aggregates shadow-replayed in the last audit cycle"))
        self.audit_divergent_rows = m.counter(MI(
            "surge.audit.divergent-rows",
            "live slab rows whose bytes diverged from their shadow refold "
            "(state corruption findings; fenced against evict/re-admit and "
            "rebalance races)"))
        self.audit_digest_mismatches = m.counter(MI(
            "surge.audit.digest-mismatches",
            "cross-replica chained-digest compares that disagreed at the "
            "same offset below the high-watermark (replica log divergence)"))
        self.audit_dedup_holes = m.counter(MI(
            "surge.audit.dedup-holes",
            "dedup probes where replaying a recently-acked txn_seq was "
            "ACCEPTED instead of answered from the dedup window"))
        self.audit_unresolved = m.gauge(MI(
            "surge.audit.unresolved-divergences",
            "divergences found and not yet re-verified clean (drives the "
            "state-divergence SLO; 0 on a healthy fleet)"))
        self.audit_round_timer = m.timer(MI(
            "surge.audit.round-timer",
            "ms per consistency-audit cycle, sample to verdict"))
        # Deprecation aliases for the r4 renames (ADVICE r4): dashboards keyed
        # to the old identifiers — including a timer's .min/.max/.p99
        # sub-metrics — keep working for a release window; the alias providers
        # join the same sensor, so every recording lands under both names.
        # Guarded like every base instrument so re-construction on a shared
        # registry cannot stack duplicate providers. Remove after the window.
        old_timer = "surge.replay.batch-timer"
        if old_timer not in m._metrics:
            alias = f"DEPRECATED alias of {self.replay_timer._sensor.name}"
            sensor = self.replay_timer._sensor
            sensor.add_metric(MI(old_timer, alias),
                              ExponentialWeightedMovingAverage(), m)
            sensor.add_metric(MI(f"{old_timer}.min", alias), Min(), m)
            sensor.add_metric(MI(f"{old_timer}.max", alias), Max(), m)
            sensor.add_metric(MI(f"{old_timer}.p99", alias),
                              TimeBucketHistogram(), m)
        old_gauge = "surge.replay.events-per-sec"
        if old_gauge not in m._metrics:
            self.replay_events_per_sec.add_metric(MI(
                old_gauge,
                "DEPRECATED alias of surge.replay.rebuild-events-per-sec"),
                MostRecentValue(), m)


def engine_metrics(registry: Optional[Metrics] = None) -> EngineMetrics:
    return EngineMetrics(registry if registry is not None else Metrics())
