"""Broker-side metrics quiver + scrape collector for the log broker.

PR 4 grew the broker a replication stream, epoch-fenced failover, a WAL
journal with group-commit fsync rounds, and a pipelined-transaction dedup
window — none of it observable at runtime (the engine-side ``EngineMetrics``
quiver only sees the client half). :class:`BrokerMetrics` is the broker's own
predeclared instrument set, one registry per :class:`~surge_tpu.log.server.
LogServer`:

- ``surge.log.replication.*`` — in-sync set size, ISR churn, epoch, ordered
  replication-queue depth, auto-resync/catch_up progress;
- ``surge.log.journal.*`` — fsync round duration (full histogram: the group
  commit's latency floor), round occupancy (commits acknowledged per fsync),
  journal rotations, WAL bytes;
- ``surge.log.txn.*`` — in-order gate wait, dedup/alias window occupancy,
  pipelined window depth;
- ``surge.log.quorum.*`` — the majority-vote promotion layer: VoteLeader
  requests answered/granted, elections won, campaigns stood down;
- ``surge.log.hwm.*`` — the per-partition high-watermark (quorum-acked
  frontier): applied-vs-hwm lag, follower reads clamped by the gate;
- ``surge.log.handoff.*`` — planned leadership transfer: fence duration,
  records shipped as checkpoint-codec slices;
- plus the ``surge.log.failover.*`` / ``surge.log.faults.*`` counters (same
  names as the engine quiver's) so a standalone broker's scrape carries its
  own promotion/fencing/truncation counts.

Per-follower gauges (lag in records and batches, in-sync flags) are labelled
families the registry cannot key — :func:`broker_collector` computes them
from live ``LogServer`` state at scrape time, the same contract as
``health_collector``. Timers capture OpenMetrics exemplars (the registry is
built with ``exemplars=True``): a broker-side histogram bucket links to the
trace that landed in it.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Iterable, List, Optional

from surge_tpu.metrics import MetricInfo, Metrics, Sensor, Timer
from surge_tpu.metrics.exposition import Family, Sample

__all__ = ["BrokerMetrics", "broker_collector", "broker_metrics"]


@dataclass
class BrokerMetrics:
    """The standard broker instrument set, created once per LogServer."""

    registry: Metrics
    # replication (leader side)
    repl_insync_replicas: Sensor = field(init=False)
    repl_isr_churn: Sensor = field(init=False)
    repl_queue_depth: Sensor = field(init=False)
    repl_epoch: Sensor = field(init=False)
    repl_catchup_records: Sensor = field(init=False)
    repl_ship_timer: Timer = field(init=False)
    # WAL journal (FileLog group-commit rounds)
    journal_fsync_round_timer: Timer = field(init=False)
    journal_round_occupancy: Sensor = field(init=False)
    journal_rotations: Sensor = field(init=False)
    journal_wal_bytes: Sensor = field(init=False)
    # pipelined transactions / idempotency window
    txn_inorder_wait_timer: Timer = field(init=False)
    txn_dedup_window: Sensor = field(init=False)
    txn_alias_window: Sensor = field(init=False)
    txn_pipelined_depth: Sensor = field(init=False)
    # native Transact hot path (csrc/txn.cc via log/native_gate)
    native_batch_decode_timer: Timer = field(init=False)
    native_gate_batches: Sensor = field(init=False)
    native_fallbacks: Sensor = field(init=False)
    native_reply_timer: Timer = field(init=False)
    native_ingest_batches: Sensor = field(init=False)
    native_active: Sensor = field(init=False)
    # majority-quorum promotion (vote layer)
    quorum_vote_requests: Sensor = field(init=False)
    quorum_votes_granted: Sensor = field(init=False)
    quorum_elections_won: Sensor = field(init=False)
    quorum_stand_downs: Sensor = field(init=False)
    # per-partition high-watermark (quorum-acked frontier)
    hwm_lag_records: Sensor = field(init=False)
    hwm_gated_reads: Sensor = field(init=False)
    # planned partition handoff
    handoff_fence_timer: Timer = field(init=False)
    handoff_shipped_records: Sensor = field(init=False)
    # dynamic membership & per-partition leadership spread (cluster plane)
    cluster_member_epoch: Sensor = field(init=False)
    cluster_members: Sensor = field(init=False)
    cluster_assign_epoch: Sensor = field(init=False)
    cluster_partitions_led: Sensor = field(init=False)
    cluster_reassignments: Sensor = field(init=False)
    # failover + fault-plane counters (shared names with EngineMetrics so a
    # broker without an engine-wired quiver still counts them — the LogServer
    # falls back to this quiver when metrics= is not given)
    failover_promotions: Sensor = field(init=False)
    failover_fencings: Sensor = field(init=False)
    failover_truncated_records: Sensor = field(init=False)
    faults_injected: Sensor = field(init=False)
    faults_armed: Sensor = field(init=False)
    # tail-based trace sampling (surge_tpu.tracing.tail) — shared names
    # with the engine quiver so a standalone broker's scrape carries its
    # own kept/dropped tallies, like the failover counters above
    trace_kept: Sensor = field(init=False)
    trace_dropped: Sensor = field(init=False)
    trace_tail_buffer: Sensor = field(init=False)

    def __post_init__(self) -> None:
        m, MI = self.registry, MetricInfo
        self.repl_insync_replicas = m.gauge(MI(
            "surge.log.replication.insync-replicas",
            "size of the in-sync replica set, this leader included "
            "(min.insync semantics; commits need this many acks)"))
        self.repl_isr_churn = m.counter(MI(
            "surge.log.replication.isr-churn",
            "in-sync-set membership changes (drops + rejoins) — sustained "
            "churn means a follower is flapping"))
        self.repl_queue_depth = m.gauge(MI(
            "surge.log.replication.queue-depth",
            "items in the ordered replication queue after the last finalize "
            "(commits awaiting the in-sync set)"))
        self.repl_epoch = m.gauge(MI(
            "surge.log.replication.epoch",
            "this broker's current leader epoch (KIP-101 fence view)"))
        self.repl_catchup_records = m.counter(MI(
            "surge.log.replication.catchup-records",
            "records pushed to rejoining followers by leader auto-resync "
            "(the replica fetch-loop role)"))
        self.repl_ship_timer = m.timer(MI(
            "surge.log.replication.ship-timer",
            "ms per successful leader->follower Replicate ship of the "
            "ordered queue's head item"))
        self.journal_fsync_round_timer = m.timer(MI(
            "surge.log.journal.fsync-round-timer",
            "ms per WAL group-commit fsync round (the shared journal fsync "
            "every concurrent committer rides)"))
        self.journal_round_occupancy = m.gauge(MI(
            "surge.log.journal.round-occupancy",
            "commit waiters acknowledged by the last fsync round (how much "
            "of the group-commit amortization one fsync bought)"))
        self.journal_rotations = m.counter(MI(
            "surge.log.journal.rotations",
            "WAL journal rotations (segments fsynced, frontier line written, "
            "old generation GC'd)"))
        self.journal_wal_bytes = m.gauge(MI(
            "surge.log.journal.wal-bytes",
            "bytes in the live commits.log journal after the last fsync "
            "round / rotation (embedded WAL payloads included)"))
        self.txn_inorder_wait_timer = m.timer(MI(
            "surge.log.txn.inorder-wait-timer",
            "ms a pipelined txn_seq waited at the in-order apply gate for "
            "its predecessor to apply"))
        self.txn_dedup_window = m.gauge(MI(
            "surge.log.txn.dedup-window",
            "cached replies in the acking producer's dedup window at the "
            "last ack (replays anywhere in it answer from cache)"))
        self.txn_alias_window = m.gauge(MI(
            "surge.log.txn.alias-window",
            "in-limbo seqs armed for reopen-alias absorption at the last "
            "OpenProducer (applied-but-unacked batches the reopened "
            "producer may verbatim-retry under new seqs)"))
        self.txn_pipelined_depth = m.gauge(MI(
            "surge.log.txn.pipelined-depth",
            "how far past the acked frontier the last arriving txn_seq ran "
            "(the live pipelined window depth, bounded by "
            "surge.producer.max-in-flight)"))
        self.native_batch_decode_timer = m.timer(MI(
            "surge.log.native.batch-decode-timer",
            "ms per native Transact batch: C++ payload decode + gate + "
            "pipelined apply incl. WAL-entry formatting (csrc/txn.cc; "
            "compare txn.inorder-wait-timer for gate stalls)"))
        self.native_gate_batches = m.counter(MI(
            "surge.log.native.gate-batches",
            "Transact batches committed through the native decode/gate/"
            "format path (0 = library unbuilt or "
            "surge.log.native.enabled=false)"))
        self.native_fallbacks = m.counter(MI(
            "surge.log.native.fallbacks",
            "Transact batches that fell back to the pure-Python path on a "
            "native-enabled broker (unparseable request bytes — the "
            "bit-identical fallback contract, not an error)"))
        self.native_reply_timer = m.timer(MI(
            "surge.log.native.reply-timer",
            "ms per native reply-leg format (csrc/txn.cc "
            "surge_reply_format: Read/LatestByKey reply bytes emitted in "
            "one call, no per-record RecordMsg materialization)"))
        self.native_ingest_batches = m.counter(MI(
            "surge.log.native.ingest-batches",
            "replica Replicate batches verbatim-ingested through the "
            "native path (csrc/txn.cc parse_packed_v + format_verbatim — "
            "follower apply off the GIL; 0 = Python-path follower)"))
        self.native_active = m.gauge(MI(
            "surge.log.native.active",
            "1 when this broker's native hot path is live (library built "
            "AND surge.log.native.enabled); 0 = silently-degraded Python "
            "fallback — the surgetop 'native' column"))
        self.quorum_vote_requests = m.counter(MI(
            "surge.log.quorum.vote-requests",
            "VoteLeader RPCs answered by this broker (each candidate's "
            "campaign asks every peer once per epoch)"))
        self.quorum_votes_granted = m.counter(MI(
            "surge.log.quorum.votes-granted",
            "VoteLeader requests this broker granted (one vote per epoch, "
            "persisted — a bounced voter cannot double-vote)"))
        self.quorum_elections_won = m.counter(MI(
            "surge.log.quorum.elections-won",
            "campaigns this broker won with a strict cluster majority "
            "(each win is followed by a promotion)"))
        self.quorum_stand_downs = m.counter(MI(
            "surge.log.quorum.stand-downs",
            "campaigns abandoned without a majority (voters unreachable, "
            "leader proven alive from a peer's vantage, or a higher epoch "
            "seen) — the split-brain window the vote layer closes"))
        self.hwm_lag_records = m.gauge(MI(
            "surge.log.hwm.lag-records",
            "applied-frontier minus high-watermark across the partitions "
            "the last finalized batch touched (records applied on the "
            "leader but not yet quorum-acked)"))
        self.hwm_gated_reads = m.counter(MI(
            "surge.log.hwm.gated-reads",
            "follower-served reads clamped by the shipped high-watermark "
            "(records applied locally but not provably quorum-held stayed "
            "invisible)"))
        self.handoff_fence_timer = m.timer(MI(
            "surge.log.handoff.fence-timer",
            "ms the handoff fence was up per planned leadership transfer "
            "(drain + journal-tail ship + dedup push + promote — the "
            "client-visible unavailability bound)"))
        self.handoff_shipped_records = m.counter(MI(
            "surge.log.handoff.shipped-records",
            "records shipped to handoff destinations as checkpoint-codec "
            "partition slices (bulk phase + fenced tail)"))
        self.cluster_member_epoch = m.gauge(MI(
            "surge.cluster.member-epoch",
            "version of the replicated membership record this broker last "
            "applied (AddBroker/RemoveBroker bump it; stale views are "
            "epoch-fenced)"))
        self.cluster_members = m.gauge(MI(
            "surge.cluster.members",
            "brokers in the membership record this broker last applied "
            "(the dynamic quorum_peers list, self included)"))
        self.cluster_assign_epoch = m.gauge(MI(
            "surge.cluster.assign-epoch",
            "version of the partition->leader assignment map this broker "
            "last applied (handoffs and failed-member reassignments bump "
            "it)"))
        self.cluster_partitions_led = m.gauge(MI(
            "surge.cluster.partitions-led",
            "partition indices this broker currently leads under the "
            "spread assignment map (0 on legacy whole-broker clusters)"))
        self.cluster_reassignments = m.counter(MI(
            "surge.cluster.reassignments",
            "partition leaderships the coordinator moved off failed or "
            "removed members (the per-partition failover leg of "
            "self-healing)"))
        self.failover_promotions = m.counter(MI(
            "surge.log.failover.promotions",
            "follower-to-leader promotions performed by this broker"))
        self.failover_fencings = m.counter(MI(
            "surge.log.failover.fencings",
            "leader-epoch fences observed: this broker was deposed and "
            "demoted to follower"))
        self.failover_truncated_records = m.counter(MI(
            "surge.log.failover.truncated-records",
            "divergent unreplicated records truncated on demotion "
            "(KIP-101 tail rollback to the new leader's epoch-start)"))
        self.faults_injected = m.counter(MI(
            "surge.log.faults.injected",
            "faults fired by the armed fault-injection plane"))
        self.faults_armed = m.gauge(MI(
            "surge.log.faults.armed",
            "fault rules currently armed on this broker's plane "
            "(0 outside chaos experiments)"))
        self.trace_kept = m.counter(MI(
            "surge.trace.kept",
            "traces the tail sampler kept into this broker's trace ring "
            "(erred, breached surge.trace.tail.latency-ms, landed in an SLO "
            "breach window, or explicitly marked)"))
        self.trace_dropped = m.counter(MI(
            "surge.trace.dropped",
            "completed or evicted traces the tail sampler dropped "
            "(sampled-out, over the keep budget, or evicted by the span-"
            "buffer bound)"))
        self.trace_tail_buffer = m.gauge(MI(
            "surge.trace.tail-buffer-spans",
            "spans buffered for in-flight traces awaiting their tail "
            "keep/drop decision (bounded by "
            "surge.trace.tail.max-buffer-spans)"))


def broker_metrics(registry: Optional[Metrics] = None) -> BrokerMetrics:
    """A broker quiver on its own registry (exemplar capture on: broker-side
    histograms record inside the Transact span, so buckets link to traces)."""
    return BrokerMetrics(registry if registry is not None
                         else Metrics(exemplars=True))


def broker_collector(server):
    """Per-follower replication families computed from live LogServer state
    at scrape time (the registry cannot key one gauge per follower):

    - ``surge_log_replication_lag_records{follower}`` — records enqueued for
      replication that this follower has not acked yet;
    - ``surge_log_replication_lag_batches{follower}`` — same, in ordered
      queue items;
    - ``surge_log_replication_in_sync{follower}`` — 1 in the in-sync set;
    - ``surge_log_broker_is_leader`` — 1 on the leader, 0 on a follower.
    """

    def collect() -> Iterable[Family]:
        out: List[Family] = []
        targets = list(server._repl_targets)
        if targets:
            lag_r = Family(name="surge_log_replication_lag_records",
                           mtype="gauge",
                           help="records enqueued for replication but not "
                                "yet acked by this follower")
            lag_b = Family(name="surge_log_replication_lag_batches",
                           mtype="gauge",
                           help="replication-queue items not yet acked by "
                                "this follower")
            insync = Family(name="surge_log_replication_in_sync",
                            mtype="gauge",
                            help="1 while this follower is in the in-sync "
                                 "set (commits wait on it)")
            for target in targets:
                st = server._repl_target_state.get(target)
                if st is None:
                    continue
                items, records = server._repl_progress(target)
                label = (("follower", target),)
                lag_b.samples.append(Sample("", label, float(items)))
                lag_r.samples.append(Sample("", label, float(records)))
                insync.samples.append(Sample("", label,
                                             1.0 if st.in_sync else 0.0))
            out.extend([lag_r, lag_b, insync])
        role = Family(name="surge_log_broker_is_leader", mtype="gauge",
                      help="1 while this broker serves as the leader")
        role.samples.append(Sample("", (),
                                   1.0 if server.role == "leader" else 0.0))
        out.append(role)
        return out

    return collect
