"""OpenMetrics/Prometheus text exposition of the metrics registry.

The registry's reference only ever rendered HTML (``Metrics.as_html``,
Metrics.scala:270-281 — a JMX-era operator view); a production scrape surface
needs the OpenMetrics text format instead. This module renders every registered
provider as a correctly-typed family:

- :class:`~surge_tpu.metrics.statistics.Count` → ``counter`` (``_total`` sample);
- :class:`~surge_tpu.metrics.statistics.TimeBucketHistogram` → a full
  ``histogram`` family with cumulative ``_bucket``/``_sum``/``_count`` series
  (the lone p99 point the snapshot export reports is a lossy projection — the
  scrape carries the whole distribution, ``+Inf`` bucket included);
- everything else (gauge / EWMA / min / max / rate) → ``gauge``.

Dotted registry names sanitize to Prometheus names (``surge.engine.command-rate``
→ ``surge_engine_command_rate``); a timer's ``<name>.p99`` histogram provider is
exported as the ``<name>_ms`` histogram family so it cannot collide with the
timer's EWMA gauge. Extra collectors (health-bus signal counts, supervisor
restart counts — :func:`health_collector`) contribute labelled families to the
same payload.

Serving: :class:`MetricsHTTPServer` is a stdlib ``http.server`` scrape endpoint
(no third-party dependency); the AdminServer exposes the same text over gRPC as
``GetMetricsText`` (admin/server.py).
"""

from __future__ import annotations

import math
import re
import threading
from dataclasses import dataclass, field
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Dict, Iterable, List, Optional, Sequence, Tuple

from surge_tpu.metrics import Metrics
from surge_tpu.metrics.statistics import Count, TimeBucketHistogram

__all__ = [
    "CONTENT_TYPE",
    "Family",
    "MetricsHTTPServer",
    "Sample",
    "health_collector",
    "render_openmetrics",
]

#: the OpenMetrics 1.0 content type (Prometheus also accepts it)
CONTENT_TYPE = "application/openmetrics-text; version=1.0.0; charset=utf-8"

_NAME_OK = re.compile(r"[a-zA-Z_:][a-zA-Z0-9_:]*$")
_NAME_BAD_CHARS = re.compile(r"[^a-zA-Z0-9_:]")


def sanitize_name(name: str) -> str:
    """Dotted/dashed registry name → valid Prometheus metric name."""
    out = _NAME_BAD_CHARS.sub("_", name)
    if not _NAME_OK.match(out):
        out = "_" + out
    return out


def escape_help(text: str) -> str:
    return text.replace("\\", "\\\\").replace("\n", "\\n")


def escape_label_value(text: str) -> str:
    return (text.replace("\\", "\\\\").replace("\n", "\\n")
            .replace('"', '\\"'))


def format_value(v: float) -> str:
    """Shortest exact rendering; +Inf/-Inf/NaN per the OpenMetrics grammar."""
    if math.isinf(v):
        return "+Inf" if v > 0 else "-Inf"
    if math.isnan(v):
        return "NaN"
    if float(v).is_integer() and abs(v) < 1e15:
        return str(int(v))
    return repr(float(v))


@dataclass(frozen=True)
class Sample:
    """One sample line: ``name+suffix{labels} value`` plus an optional
    OpenMetrics exemplar (``# {trace_id="..."} value timestamp``) linking a
    histogram bucket to the trace that produced one of its observations."""

    suffix: str  # "", "_total", "_bucket", "_sum", "_count"
    labels: Tuple[Tuple[str, str], ...]
    value: float
    exemplar: Optional[Tuple[str, float, float]] = None  # (trace_id, value, ts)


@dataclass
class Family:
    """One metric family (the unit a collector contributes)."""

    name: str  # already-sanitized Prometheus name
    mtype: str  # "gauge" | "counter" | "histogram"
    help: str
    samples: List[Sample] = field(default_factory=list)


def _render_family(lines: List[str], fam: Family) -> None:
    if fam.help:
        lines.append(f"# HELP {fam.name} {escape_help(fam.help)}")
    lines.append(f"# TYPE {fam.name} {fam.mtype}")
    for s in fam.samples:
        if s.labels:
            body = ",".join(f'{k}="{escape_label_value(v)}"'
                            for k, v in s.labels)
            line = (f"{fam.name}{s.suffix}{{{body}}} "
                    f"{format_value(s.value)}")
        else:
            line = f"{fam.name}{s.suffix} {format_value(s.value)}"
        if s.exemplar is not None:
            trace_id, obs, ts = s.exemplar
            line += (f' # {{trace_id="{escape_label_value(trace_id)}"}} '
                     f"{format_value(obs)} {ts:.3f}")
        lines.append(line)


def _histogram_family(name: str, help_text: str,
                      h: TimeBucketHistogram) -> Family:
    fam = Family(name=name, mtype="histogram", help=help_text)
    exemplars = h.exemplars()
    for i, (bound, cum) in enumerate(h.bucket_counts()):
        fam.samples.append(Sample("_bucket", (("le", format_value(bound)),),
                                  float(cum), exemplar=exemplars.get(i)))
    fam.samples.append(Sample("_sum", (), h.sum_value))
    fam.samples.append(Sample("_count", (), float(h.total_count)))
    return fam


def _label_tuple(tags) -> Tuple[Tuple[str, str], ...]:
    """MetricInfo.tags as label pairs; non-pair tags are ignored."""
    out = []
    for t in tags or ():
        if isinstance(t, (tuple, list)) and len(t) == 2:
            out.append((sanitize_name(str(t[0])), str(t[1])))
    return tuple(out)


def registry_families(registry: Metrics) -> List[Family]:
    """Every registered metric as a typed family, registry order (sorted)."""
    families: List[Family] = []
    for name, reg in sorted(registry._metrics.items()):
        provider = reg.provider
        labels = _label_tuple(reg.info.tags)
        if isinstance(provider, TimeBucketHistogram):
            # a timer registers its distribution under "<timer>.p99"; the
            # histogram family drops that projection suffix and marks the unit
            base = name[:-len(".p99")] if name.endswith(".p99") else name
            fam = _histogram_family(sanitize_name(base) + "_ms",
                                    reg.info.description, provider)
            if labels:
                fam.samples = [Sample(s.suffix, labels + s.labels, s.value,
                                      exemplar=s.exemplar)
                               for s in fam.samples]
            families.append(fam)
        elif isinstance(provider, Count):
            fam = Family(name=sanitize_name(name), mtype="counter",
                         help=reg.info.description)
            fam.samples.append(Sample("_total", labels, provider.get_value()))
            families.append(fam)
        else:
            fam = Family(name=sanitize_name(name), mtype="gauge",
                         help=reg.info.description)
            fam.samples.append(Sample("", labels, provider.get_value()))
            families.append(fam)
    return families


#: a collector contributes extra families to one exposition pass
Collector = Callable[[], Iterable[Family]]


def health_collector(bus=None, supervisor=None) -> Collector:
    """Families for the health plane: signal counts by severity level from the
    :class:`~surge_tpu.health.HealthSignalBus` and per-component restart counts
    from the :class:`~surge_tpu.health.HealthSupervisor` (the JMX health-MBean
    numbers, now scrapeable)."""

    def collect() -> Iterable[Family]:
        out: List[Family] = []
        if bus is not None:
            fam = Family(name="surge_health_signals", mtype="counter",
                         help="health signals emitted onto the bus, by level")
            # snapshot: emit() mutates on the event-loop thread while this
            # runs on the HTTP scrape thread — iterating live would 500 a
            # scrape on a concurrent first-seen-level insert
            counts = dict(bus.signal_counts)
            for level in sorted(counts):
                fam.samples.append(Sample(
                    "_total", (("level", level),), float(counts[level])))
            out.append(fam)
        if supervisor is not None:
            fam = Family(name="surge_health_component_restarts",
                         mtype="counter",
                         help="supervisor-driven restarts per registered "
                              "component")
            for comp, n in sorted(supervisor.restart_counts().items()):
                fam.samples.append(Sample(
                    "_total", (("component", comp),), float(n)))
            out.append(fam)
        return out

    return collect


def render_openmetrics(registry: Metrics,
                       collectors: Sequence[Collector] = ()) -> str:
    """The full OpenMetrics payload, ``# EOF``-terminated."""
    lines: List[str] = []
    for fam in registry_families(registry):
        _render_family(lines, fam)
    for collect in collectors:
        for fam in collect():
            _render_family(lines, fam)
    lines.append("# EOF")
    return "\n".join(lines) + "\n"


class MetricsHTTPServer:
    """Stdlib scrape endpoint: ``GET /metrics`` (or ``/``) renders the registry.

    No third-party server dependency — a ``ThreadingHTTPServer`` on a daemon
    thread, same zero-footprint philosophy as the hand-written gRPC glue. Bind
    with ``port=0`` to take an ephemeral port (returned by :meth:`start`).

    ``render`` overrides the payload source entirely (the federated scraper
    serves its MERGED exposition — a fresh federation pass per GET — through
    this hook instead of a registry); ``registry`` may then be ``None``.
    """

    def __init__(self, registry: Optional[Metrics], host: str = "127.0.0.1",
                 port: int = 0,
                 collectors: Sequence[Collector] = (),
                 render: Optional[Callable[[], str]] = None) -> None:
        if registry is None and render is None:
            raise ValueError("need a registry or a render callable")
        self.registry = registry
        self.collectors = list(collectors)
        self.render = render
        self._host = host
        self._port = port
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None
        self.bound_port: Optional[int] = None

    def start(self) -> int:
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self) -> None:  # noqa: N802 — http.server API
                if self.path.split("?")[0] not in ("/", "/metrics"):
                    self.send_error(404)
                    return
                try:
                    body = (outer.render() if outer.render is not None
                            else render_openmetrics(
                                outer.registry, outer.collectors)).encode()
                except Exception as exc:  # noqa: BLE001 — scrape must answer
                    self.send_error(500, repr(exc))
                    return
                self.send_response(200)
                self.send_header("Content-Type", CONTENT_TYPE)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *args) -> None:  # silence per-scrape noise
                pass

        self._httpd = ThreadingHTTPServer((self._host, self._port), Handler)
        self._httpd.daemon_threads = True
        self.bound_port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            name=f"metrics-scrape-{self.bound_port}", daemon=True)
        self._thread.start()
        return self.bound_port

    def stop(self) -> None:
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
