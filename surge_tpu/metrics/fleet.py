"""Fleet-level metrics quiver: the federated scraper's own instruments.

The engine and broker quivers describe ONE process; the fleet quiver
describes the act of watching all of them — how the federation pass went
(``surge.fleet.*``: targets up, scrape latency, staleness of the oldest
cached payload) and what the SLO burn-rate engine concluded from the merged
payload (``surge.slo.*``: objectives evaluated, breaches fired, the worst
burn rate observed). One registry per
:class:`~surge_tpu.observability.federation.FederatedScraper`; its families
join the federated exposition itself, so the fleet scrape is self-describing
(a dashboard can alert on ``surge_fleet_up_targets`` falling below the fleet
size from the same payload it graphs the fleet with).

Golden/catalog coupled like the engine and broker quivers: every instrument
here must appear in ``tests/golden/metrics_fleet.om`` AND the
docs/observability.md catalog (``tools/regen_golden_metrics.py`` regenerates
the golden; surgelint's ``metric-catalog`` rule and the runtime
catalog-completeness test both enforce the coupling).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from surge_tpu.metrics import MetricInfo, Metrics, Sensor, Timer

__all__ = ["FleetMetrics", "fleet_metrics"]


@dataclass
class FleetMetrics:
    """The standard fleet instrument set, created once per federated scraper."""

    registry: Metrics
    # federation pass health
    fleet_targets: Sensor = field(init=False)
    fleet_up_targets: Sensor = field(init=False)
    fleet_scrape_timer: Timer = field(init=False)
    fleet_scrape_errors: Sensor = field(init=False)
    fleet_merged_families: Sensor = field(init=False)
    fleet_max_staleness: Sensor = field(init=False)
    # SLO burn-rate engine
    slo_objectives: Sensor = field(init=False)
    slo_evaluations: Sensor = field(init=False)
    slo_breaches: Sensor = field(init=False)
    slo_active_breaches: Sensor = field(init=False)
    slo_max_burn_rate: Sensor = field(init=False)
    # command-anatomy plane (surge_tpu/observability/anatomy.py)
    trace_assembly_timer: Timer = field(init=False)
    # cluster autobalancer (surge_tpu/cluster/autobalancer.py)
    balancer_cycles: Sensor = field(init=False)
    balancer_moves: Sensor = field(init=False)
    balancer_skipped: Sensor = field(init=False)
    balancer_lead_skew: Sensor = field(init=False)

    def __post_init__(self) -> None:
        m, MI = self.registry, MetricInfo
        self.fleet_targets = m.gauge(MI(
            "surge.fleet.targets",
            "scrape targets registered with the federated scraper"))
        self.fleet_up_targets = m.gauge(MI(
            "surge.fleet.up-targets",
            "targets that answered the last federation pass (the merged "
            "payload's up{instance} gauges, summed)"))
        self.fleet_scrape_timer = m.timer(MI(
            "surge.fleet.scrape-timer",
            "ms per full federation pass (every target scraped "
            "concurrently, slowest answer bounds the round)"))
        self.fleet_scrape_errors = m.counter(MI(
            "surge.fleet.scrape-errors",
            "per-target scrape failures (timeout, refused, bad payload) "
            "across all federation passes"))
        self.fleet_merged_families = m.gauge(MI(
            "surge.fleet.merged-families",
            "metric families in the last merged exposition (fleet self-"
            "instruments included)"))
        self.fleet_max_staleness = m.gauge(MI(
            "surge.fleet.max-staleness-seconds",
            "age of the OLDEST per-target payload served in the last merged "
            "exposition (a down target's cached families keep serving with "
            "this staleness stamp until it answers again)"))
        self.slo_objectives = m.gauge(MI(
            "surge.slo.objectives",
            "SLO definitions the burn-rate engine evaluates per pass"))
        self.slo_evaluations = m.counter(MI(
            "surge.slo.evaluations",
            "SLO evaluation passes run over the federated payload"))
        self.slo_breaches = m.counter(MI(
            "surge.slo.breaches",
            "burn-rate breaches fired (fast AND slow window over the "
            "threshold — the Google-SRE multiwindow page condition)"))
        self.slo_active_breaches = m.gauge(MI(
            "surge.slo.active-breaches",
            "objectives currently in breach (degraded-not-down: the health "
            "bus carries an `slo` component while this is nonzero)"))
        self.slo_max_burn_rate = m.gauge(MI(
            "surge.slo.max-burn-rate",
            "worst fast-window burn rate across objectives at the last "
            "evaluation (1.0 = spending error budget exactly at the "
            "objective's sustainable rate)"))
        self.trace_assembly_timer = m.timer(MI(
            "surge.trace.assembly-timer",
            "ms per cross-process trace assembly + critical-path "
            "attribution pass over DumpTraces envelopes "
            "(observability/anatomy.py; tools/trace_anatomy.py)"))
        self.balancer_cycles = m.counter(MI(
            "surge.cluster.balancer.cycles",
            "autobalancer decision passes (one federated scrape + SLO "
            "evaluation + ClusterMeta fetch each)"))
        self.balancer_moves = m.counter(MI(
            "surge.cluster.balancer.moves",
            "planned per-partition HandoffPartition moves the autobalancer "
            "executed (dry-run decisions are recorded, not counted here)"))
        self.balancer_skipped = m.counter(MI(
            "surge.cluster.balancer.skipped",
            "moves the autobalancer decided but did not execute (dry-run, "
            "hysteresis, move budget, or the handoff RPC failing)"))
        self.balancer_lead_skew = m.gauge(MI(
            "surge.cluster.balancer.lead-skew",
            "partition lead-count spread (max - min) across up members at "
            "the last cycle — the imbalance the balancer steers toward "
            "surge.cluster.balancer.max-lead-skew"))


def fleet_metrics(registry: Optional[Metrics] = None) -> FleetMetrics:
    return FleetMetrics(registry if registry is not None else Metrics())
