"""Metric value providers — the statistics behind every sensor.

Equivalents of modules/metrics/src/main/scala/surge/metrics/statistics/*: Count, Min,
Max, MostRecentValue, ExponentialWeightedMovingAverage (timers use EWMA(0.95),
Metrics.scala:134-172), RateHistogram over 1/5/15-minute windows, and a fixed-bucket
time histogram. Providers are updated by :class:`~surge_tpu.metrics.Sensor` and read by
the registry snapshot."""

from __future__ import annotations

import bisect
import time
from collections import deque
from typing import Callable, Deque, List, Protocol, Sequence, Tuple

from surge_tpu.tracing import active_trace_id


class MetricValueProvider(Protocol):
    def update(self, value: float, timestamp: float) -> None: ...

    def get_value(self) -> float: ...


class Count:
    """Running total of recorded values (statistics/Count.scala)."""

    def __init__(self) -> None:
        self._total = 0.0

    def update(self, value: float, timestamp: float) -> None:
        self._total += value

    def get_value(self) -> float:
        return self._total


class MostRecentValue:
    def __init__(self) -> None:
        self._value = 0.0

    def update(self, value: float, timestamp: float) -> None:
        self._value = value

    def get_value(self) -> float:
        return self._value


class Min:
    def __init__(self) -> None:
        self._value: float | None = None

    def update(self, value: float, timestamp: float) -> None:
        self._value = value if self._value is None else min(self._value, value)

    def get_value(self) -> float:
        return 0.0 if self._value is None else self._value


class Max:
    def __init__(self) -> None:
        self._value: float | None = None

    def update(self, value: float, timestamp: float) -> None:
        self._value = value if self._value is None else max(self._value, value)

    def get_value(self) -> float:
        return 0.0 if self._value is None else self._value


class ExponentialWeightedMovingAverage:
    """EWMA with the reference's timer smoothing (alpha weight on history, 0.95
    default — Metrics.scala:141-147)."""

    def __init__(self, alpha: float = 0.95) -> None:
        self.alpha = alpha
        self._value = 0.0
        self._initialized = False

    def update(self, value: float, timestamp: float) -> None:
        if not self._initialized:
            self._value = value
            self._initialized = True
        else:
            self._value = self.alpha * self._value + (1.0 - self.alpha) * value

    def get_value(self) -> float:
        return self._value


class _StatView:
    """Read-only registry adapter over one statistic of a fused provider —
    registered under its export name (``<timer>.min`` etc.) while the ONE
    fused provider does the per-record work."""

    __slots__ = ("_fn",)

    def __init__(self, fn: Callable[[], float]) -> None:
        self._fn = fn

    def update(self, value: float, timestamp: float) -> None:
        """No-op: the owning FusedTimerStats records; views only export."""

    def get_value(self) -> float:
        return self._fn()


class FusedTimerStats:
    """All four timer statistics — EWMA, min, max and the bucket histogram —
    in ONE provider update. A timer recording used to dispatch four provider
    ``update`` calls per observation; at command-path rates (several timers
    per command, one per broker Transact) the call overhead alone was
    measurable, so the sensor now fans into this single provider and the
    registry exports the individual statistics through views
    (:class:`_StatView`) and the embedded :class:`TimeBucketHistogram`.
    ``get_value`` reports the EWMA — the fused provider itself registers
    under the timer's base name, exactly like the EWMA it replaces."""

    __slots__ = ("histogram", "alpha", "_ewma", "_ewma_init", "_min", "_max")

    def __init__(self, histogram: "TimeBucketHistogram",
                 alpha: float = 0.95) -> None:
        self.histogram = histogram
        self.alpha = alpha
        self._ewma = 0.0
        self._ewma_init = False
        self._min: float | None = None
        self._max: float | None = None

    def update(self, value: float, timestamp: float) -> None:
        if self._ewma_init:
            self._ewma = self.alpha * self._ewma + (1.0 - self.alpha) * value
        else:
            self._ewma = value
            self._ewma_init = True
        mn = self._min
        if mn is None or value < mn:
            self._min = value
        mx = self._max
        if mx is None or value > mx:
            self._max = value
        self.histogram.update(value, timestamp)

    def get_value(self) -> float:
        return self._ewma

    def min_view(self) -> _StatView:
        return _StatView(lambda: 0.0 if self._min is None else self._min)

    def max_view(self) -> _StatView:
        return _StatView(lambda: 0.0 if self._max is None else self._max)


class RateHistogram:
    """Events/second over a sliding window (statistics/RateHistogram.scala; the
    registry exposes 1/5/15-minute variants). ``clock`` is injectable so rate
    assertions can run against a frozen time source instead of ``time.time``."""

    def __init__(self, window_s: float,
                 clock: Callable[[], float] = time.time) -> None:
        self.window_s = window_s
        self._clock = clock
        self._events: Deque[Tuple[float, float]] = deque()  # (timestamp, weight)
        self._sum = 0.0

    def update(self, value: float, timestamp: float) -> None:
        self._events.append((timestamp, value))
        self._sum += value
        self._evict(timestamp)

    def _evict(self, now: float) -> None:
        cutoff = now - self.window_s
        while self._events and self._events[0][0] < cutoff:
            _, w = self._events.popleft()
            self._sum -= w

    def get_value(self) -> float:
        self._evict(self._clock())
        return self._sum / self.window_s


class TimeBucketHistogram:
    """Counts of recorded durations falling into fixed latency buckets
    (statistics/TimeBucketHistogram.scala analog). ``get_value`` reports the p-th
    percentile estimate (upper bucket bound). The full distribution —
    ``bucket_counts()`` (cumulative), ``total_count``, ``sum_value`` — backs the
    OpenMetrics ``_bucket``/``_sum``/``_count`` series
    (:mod:`surge_tpu.metrics.exposition`).

    With ``exemplars=True`` each recording also captures the ACTIVE trace id
    (:func:`surge_tpu.tracing.active_trace_id` — the span the recording thread
    or task is inside of), keeping the newest exemplar per bucket; the
    exposition renders them in OpenMetrics ``# {trace_id="..."}`` syntax so a
    p99 latency bucket links straight to one JSONL trace that landed in it."""

    def __init__(self, buckets_ms: Sequence[float] = (1, 5, 10, 25, 50, 100, 250, 500,
                                                      1000, 2500, 5000, 10000),
                 percentile: float = 0.99, exemplars: bool = False) -> None:
        self.bounds: List[float] = sorted(buckets_ms)
        self.counts: List[int] = [0] * (len(self.bounds) + 1)
        self.percentile = percentile
        self._total = 0
        self._sum = 0.0
        #: bucket index -> (trace_id, recorded value, unix timestamp); None
        #: when exemplar capture is off (the default — no per-update overhead)
        self._exemplars: "dict[int, Tuple[str, float, float]] | None" = (
            {} if exemplars else None)

    def update(self, value: float, timestamp: float) -> None:
        idx = bisect.bisect_left(self.bounds, value)
        self.counts[idx] += 1
        self._total += 1
        self._sum += value
        if self._exemplars is not None:
            trace_id = active_trace_id()
            if trace_id is not None:
                self._exemplars[idx] = (trace_id, value, timestamp)

    def exemplars(self) -> "dict[int, Tuple[str, float, float]]":
        """Newest captured exemplar per bucket index (empty when disabled)."""
        return dict(self._exemplars) if self._exemplars else {}

    def get_value(self) -> float:
        """Percentile estimate. An overflow-bucket hit reports the largest
        FINITE bound: a ``float("inf")`` here broke every numeric export (JSON
        has no Infinity; the text format would emit a non-plottable point) —
        the true unbounded tail is visible in the exposition's ``+Inf`` bucket
        instead."""
        if self._total == 0:
            return 0.0
        target = self.percentile * self._total
        seen = 0
        for i, c in enumerate(self.counts):
            seen += c
            if seen >= target:
                return self.bounds[i] if i < len(self.bounds) else self.bounds[-1]
        return self.bounds[-1]

    @property
    def total_count(self) -> int:
        return self._total

    @property
    def sum_value(self) -> float:
        return self._sum

    def bucket_counts(self) -> List[Tuple[float, int]]:
        """Cumulative ``(upper_bound, count)`` pairs ending with ``(+Inf, total)``
        — exactly the Prometheus/OpenMetrics histogram contract."""
        out: List[Tuple[float, int]] = []
        running = 0
        for bound, c in zip(self.bounds, self.counts):
            running += c
            out.append((bound, running))
        out.append((float("inf"), self._total))
        return out
