"""Conformance fixture model families, mirroring the reference's test/doc aggregates:

- :mod:`surge_tpu.models.counter` — the Counter bounded context
  (command-engine/core/src/test/scala/surge/core/TestBoundedContext.scala:17-82),
  including the poison commands/events its tests rely on.
- :mod:`surge_tpu.models.bank_account` — the BankAccount docs sample
  (surge-docs/src/test/scala/docs/command/BankAccountCommandModel.scala:53-88).
- :mod:`surge_tpu.models.shopping_cart` — variable-length-log aggregate for
  ragged/segmented replay (BASELINE.json config "ShoppingCart aggregate").

Each family ships the scalar model (engine steady state) AND the JAX ReplaySpec
(TPU batched replay) over the same event schema — golden tests assert the two folds agree.
"""

from surge_tpu.models import counter, bank_account, shopping_cart  # noqa: F401
