"""BankAccount docs-sample parity fixture — BankAccountCommandModel.scala:53-88.

Semantics preserved exactly:
- CreateAccount on an existing account emits no events (idempotent create).
- Credit/Debit on a missing account reject (AccountDoesNotExistException analog).
- Debit with insufficient funds rejects (InsufficientFundsException analog).
- BankAccountCreated replaces the state; BankAccountUpdated sets the balance only when
  the account exists (``aggregate.map(_.copy(...))``).

On the tensor path, strings (owner, security code) are dictionary-encoded via Vocab and
the "exists" optionality becomes an explicit ``created`` flag column. Balances are float32
on the tensor path (see tests for the exactness/tolerance discipline).
"""

from __future__ import annotations

import functools as _functools
from dataclasses import dataclass
from typing import Optional, Sequence

import numpy as np

from surge_tpu.codec.schema import SchemaRegistry, Vocab
from surge_tpu.engine.model import RejectedCommand, ReplayHandlers, ReplaySpec
from surge_tpu.serialization import JsonEventFormatting, JsonFormatting


@dataclass(frozen=True)
class BankAccount:
    account_number: str
    account_owner: str
    security_code: str
    balance: float


@dataclass(frozen=True)
class CreateAccount:
    account_number: str
    account_owner: str
    security_code: str
    initial_balance: float


@dataclass(frozen=True)
class CreditAccount:
    account_number: str
    amount: float


@dataclass(frozen=True)
class DebitAccount:
    account_number: str
    amount: float


@dataclass(frozen=True)
class BankAccountCreated:
    account_number: str
    account_owner: str
    security_code: str
    balance: float


@dataclass(frozen=True)
class BankAccountUpdated:
    account_number: str
    new_balance: float


class AccountDoesNotExist(RejectedCommand):
    pass


class InsufficientFunds(RejectedCommand):
    pass


class BankAccountModel:
    """Scalar model — processCommand/handleEvent parity with BankAccountCommandModel.scala:54-88."""

    def initial_state(self, aggregate_id: str) -> Optional[BankAccount]:
        return None

    def process_command(self, state: Optional[BankAccount], command) -> Sequence[object]:
        if isinstance(command, CreateAccount):
            if state is not None:
                return []
            return [BankAccountCreated(command.account_number, command.account_owner,
                                       command.security_code, command.initial_balance)]
        if isinstance(command, CreditAccount):
            if state is None:
                raise AccountDoesNotExist(command.account_number)
            return [BankAccountUpdated(state.account_number, state.balance + command.amount)]
        if isinstance(command, DebitAccount):
            if state is None:
                raise AccountDoesNotExist(command.account_number)
            if state.balance < command.amount:
                raise InsufficientFunds(state.account_number)
            return [BankAccountUpdated(state.account_number, state.balance - command.amount)]
        raise RejectedCommand(f"unknown command {command!r}")

    def handle_event(self, state: Optional[BankAccount], event) -> Optional[BankAccount]:
        if isinstance(event, BankAccountCreated):
            return BankAccount(event.account_number, event.account_owner,
                               event.security_code, event.balance)
        if isinstance(event, BankAccountUpdated):
            if state is None:
                return None
            return BankAccount(state.account_number, state.account_owner,
                               state.security_code, event.new_balance)
        return state

    def replay_spec(self) -> ReplaySpec:
        return make_replay_spec()


# --- tensor path -----------------------------------------------------------------------

CREATED, UPDATED = 0, 1


@dataclass(frozen=True)
class EncodedAccountState:
    """Tensor-side state record (the scalar BankAccount with strings vocab-encoded)."""

    created: bool
    owner_code: int
    security_code_code: int
    balance: float


@dataclass(frozen=True)
class EncodedCreated:
    owner_code: int
    security_code_code: int
    balance: float


@dataclass(frozen=True)
class EncodedUpdated:
    new_balance: float


def make_registry() -> SchemaRegistry:
    reg = SchemaRegistry()
    reg.register_event(EncodedCreated, type_id=CREATED)
    reg.register_event(EncodedUpdated, type_id=UPDATED)
    reg.register_state(EncodedAccountState)
    return reg


def encode_event(vocab: Vocab, event):
    """Host-side vocab encoding of the domain events into their tensor forms."""
    if isinstance(event, BankAccountCreated):
        return EncodedCreated(owner_code=vocab.encode(event.account_owner),
                              security_code_code=vocab.encode(event.security_code),
                              balance=np.float32(event.balance))
    if isinstance(event, BankAccountUpdated):
        return EncodedUpdated(new_balance=np.float32(event.new_balance))
    raise TypeError(f"not a bank account event: {event!r}")


def decode_state(vocab: Vocab, account_number: str, rec: EncodedAccountState) -> Optional[BankAccount]:
    if not rec.created:
        return None
    return BankAccount(account_number=account_number,
                       account_owner=vocab.decode(rec.owner_code),
                       security_code=vocab.decode(rec.security_code_code),
                       balance=float(rec.balance))


def make_replay_spec() -> ReplaySpec:
    import jax.numpy as jnp

    def created(s, f):
        return {"created": jnp.asarray(True),
                "owner_code": f["owner_code"],
                "security_code_code": f["security_code_code"],
                "balance": f["balance"]}

    def updated(s, f):
        # aggregate.map(_.copy(balance = newBalance)): no-op when account absent
        return {"created": s["created"],
                "owner_code": s["owner_code"],
                "security_code_code": s["security_code_code"],
                "balance": jnp.where(s["created"], f["new_balance"], s["balance"])}

    return ReplaySpec(
        registry=make_registry(),
        handlers=ReplayHandlers({CREATED: created, UPDATED: updated}),
        init_record={"created": False, "owner_code": 0, "security_code_code": 0, "balance": 0.0},
        associative=make_associative_fold(),
    )


@_functools.cache
def make_associative_fold():
    """The bank fold as a last-writer-with-reset monoid for sequence-parallel
    replay (surge_tpu.replay.seqpar): a Created RESETS the account (its values
    win over everything earlier), an Updated sets the balance only if an
    account exists at that point, orphan Updateds are no-ops. Summary =
    (has_create, create vals, last-update-after-last-create); ``combine`` is
    the standard reset-aware last-writer composition. Repeated factory calls
    are structurally equal, sharing seqpar's compiled programs and one-time
    conformance check."""
    import jax.numpy as jnp

    from surge_tpu.replay.seqpar import AssociativeFold

    def lift(ev):
        tid = ev["type_id"]
        cr = tid == CREATED
        up = tid == UPDATED
        f32 = jnp.float32
        return {
            "hc": cr,
            "cr_owner": jnp.where(cr, ev["owner_code"], 0).astype(jnp.int32),
            "cr_sec": jnp.where(cr, ev["security_code_code"],
                                0).astype(jnp.int32),
            "cr_bal": jnp.where(cr, ev["balance"], 0.0).astype(f32),
            "upd_has": up,
            "upd_bal": jnp.where(up, ev["new_balance"], 0.0).astype(f32),
        }

    def combine(a, b):
        # updates after the combined slice's LAST create: b's own if b has a
        # create (reset) or any update; otherwise a's carry through
        upd_has = jnp.where(b["hc"], b["upd_has"],
                            b["upd_has"] | a["upd_has"])
        upd_bal = jnp.where(b["upd_has"], b["upd_bal"], a["upd_bal"])
        upd_bal = jnp.where(b["hc"] & ~b["upd_has"],
                            jnp.float32(0.0), upd_bal)
        return {
            "hc": a["hc"] | b["hc"],
            "cr_owner": jnp.where(b["hc"], b["cr_owner"], a["cr_owner"]),
            "cr_sec": jnp.where(b["hc"], b["cr_sec"], a["cr_sec"]),
            "cr_bal": jnp.where(b["hc"], b["cr_bal"], a["cr_bal"]),
            "upd_has": upd_has,
            "upd_bal": upd_bal,
        }

    def apply(state, s):
        created = state["created"] | s["hc"]
        # with a create in the slice: its values, overridden by any later
        # update; without one: updates apply only if the account existed
        bal_with_create = jnp.where(s["upd_has"], s["upd_bal"], s["cr_bal"])
        bal_no_create = jnp.where(state["created"] & s["upd_has"],
                                  s["upd_bal"], state["balance"])
        return {
            "created": created,
            "owner_code": jnp.where(s["hc"], s["cr_owner"],
                                    state["owner_code"]).astype(jnp.int32),
            "security_code_code": jnp.where(
                s["hc"], s["cr_sec"],
                state["security_code_code"]).astype(jnp.int32),
            "balance": jnp.where(s["hc"], bal_with_create,
                                 bal_no_create).astype(jnp.float32),
        }

    return AssociativeFold(
        lift=lift, combine=combine, apply=apply,
        identity={"hc": np.bool_(False), "cr_owner": np.int32(0),
                  "cr_sec": np.int32(0), "cr_bal": np.float32(0.0),
                  "upd_has": np.bool_(False), "upd_bal": np.float32(0.0)})


# --- byte formats ---

_EVENTS = {c.__name__: c for c in (BankAccountCreated, BankAccountUpdated)}


def state_formatting() -> JsonFormatting:
    return JsonFormatting(
        to_dict=lambda s: {"account_number": s.account_number, "account_owner": s.account_owner,
                           "security_code": s.security_code, "balance": s.balance},
        from_dict=lambda d: BankAccount(**d))


def event_formatting() -> JsonEventFormatting:
    def to_dict(e):
        d = {k: getattr(e, k) for k in e.__dataclass_fields__}
        d["_type"] = type(e).__name__
        return d

    def from_dict(d):
        d = dict(d)
        return _EVENTS[d.pop("_type")](**d)

    return JsonEventFormatting(to_dict=to_dict, from_dict=from_dict,
                               key_of=lambda e: e.account_number)
