"""Counter bounded context — parity fixture for TestBoundedContext.scala:17-175.

State(aggregate_id, count, version); Increment/Decrement/DoNothing commands; poison
commands (FailCommandProcessing, CreateExceptionThrowingEvent, CreateUnserializableEvent)
used by engine failure-path tests, exactly as the reference's specs use them
(TestBoundedContext.scala:39-43).
"""

from __future__ import annotations

import functools as _functools
from dataclasses import dataclass
from typing import Optional, Sequence

from surge_tpu.codec.schema import SchemaRegistry
from surge_tpu.engine.model import RejectedCommand, ReplayHandlers, ReplaySpec
from surge_tpu.serialization import (JsonCommandFormatting, JsonEventFormatting,
                                     JsonFormatting)


# --- domain types (TestBoundedContext.scala:18-66) ---


@dataclass(frozen=True)
class State:
    aggregate_id: str
    count: int
    version: int


@dataclass(frozen=True)
class Increment:
    aggregate_id: str


@dataclass(frozen=True)
class Decrement:
    aggregate_id: str


@dataclass(frozen=True)
class DoNothing:
    aggregate_id: str


@dataclass(frozen=True)
class CreateNoOpEvent:
    aggregate_id: str


@dataclass(frozen=True)
class FailCommandProcessing:
    aggregate_id: str
    error_msg: str


@dataclass(frozen=True)
class CreateExceptionThrowingEvent:
    aggregate_id: str
    error_msg: str


@dataclass(frozen=True)
class CreateUnserializableEvent:
    aggregate_id: str
    error_msg: str


@dataclass(frozen=True)
class CountIncremented:
    aggregate_id: str
    increment_by: int
    sequence_number: int


@dataclass(frozen=True)
class CountDecremented:
    aggregate_id: str
    decrement_by: int
    sequence_number: int


@dataclass(frozen=True)
class NoOpEvent:
    aggregate_id: str
    sequence_number: int


class ExceptionThrowingError(RuntimeError):
    """Raised when an ExceptionThrowingEvent is folded (fault-injection fixture)."""


@dataclass(frozen=True)
class ExceptionThrowingEvent:
    aggregate_id: str
    sequence_number: int
    error_msg: str


@dataclass(frozen=True)
class UnserializableEvent:
    aggregate_id: str
    sequence_number: int
    error_msg: str


# --- scalar model (TestBoundedContext BusinessLogicTrait handleEvent/processCommand) ---


class CounterModel:
    def initial_state(self, aggregate_id: str) -> Optional[State]:
        return None

    def process_command(self, state: Optional[State], command) -> Sequence[object]:
        agg_id = command.aggregate_id
        seq = (state.version if state else 0) + 1
        if isinstance(command, Increment):
            return [CountIncremented(agg_id, 1, seq)]
        if isinstance(command, Decrement):
            return [CountDecremented(agg_id, 1, seq)]
        if isinstance(command, DoNothing):
            return []
        if isinstance(command, CreateNoOpEvent):
            return [NoOpEvent(agg_id, seq)]
        if isinstance(command, FailCommandProcessing):
            raise RejectedCommand(command.error_msg)
        if isinstance(command, CreateExceptionThrowingEvent):
            return [ExceptionThrowingEvent(agg_id, seq, command.error_msg)]
        if isinstance(command, CreateUnserializableEvent):
            return [UnserializableEvent(agg_id, seq, command.error_msg)]
        raise RejectedCommand(f"unknown command {command!r}")

    def handle_event(self, state: Optional[State], event) -> Optional[State]:
        current = state if state is not None else State(event.aggregate_id, 0, 0)
        if isinstance(event, CountIncremented):
            return State(current.aggregate_id, current.count + event.increment_by, event.sequence_number)
        if isinstance(event, CountDecremented):
            return State(current.aggregate_id, current.count - event.decrement_by, event.sequence_number)
        if isinstance(event, NoOpEvent):
            return current
        if isinstance(event, UnserializableEvent):
            return State(current.aggregate_id, current.count, event.sequence_number)
        if isinstance(event, ExceptionThrowingEvent):
            raise ExceptionThrowingError(event.error_msg)
        return current

    # -- TPU replay contract --------------------------------------------------------
    def replay_spec(self) -> ReplaySpec:
        return make_replay_spec()


# --- tensor schemas + JAX fold ---

INCREMENTED, DECREMENTED, NOOP, UNSERIALIZABLE = 0, 1, 2, 3


def make_registry() -> SchemaRegistry:
    """Tensor-path event subset: every event the reference fold handles without
    raising (TestBoundedContext.scala handleEvent). ExceptionThrowingEvent is
    deliberately unregistered — its fold semantics are "throw", which the batched
    path surfaces as an encode-time KeyError instead."""
    reg = SchemaRegistry()
    # narrow wire widths: increment/decrement deltas are 0..3 (the reference
    # commands always emit 1, TestBoundedContext.scala:27-31) — with the 3-bit type
    # discriminant the whole event packs into ONE wire byte when sequence_number is
    # producer-derived (codec/wire.py)
    reg.register_event(CountIncremented, type_id=INCREMENTED, exclude=("aggregate_id",),
                       bits={"increment_by": 2})
    reg.register_event(CountDecremented, type_id=DECREMENTED, exclude=("aggregate_id",),
                       bits={"decrement_by": 2})
    reg.register_event(NoOpEvent, type_id=NOOP, exclude=("aggregate_id",))
    reg.register_event(UnserializableEvent, type_id=UNSERIALIZABLE,
                       exclude=("aggregate_id", "error_msg"))
    reg.register_state(State, exclude=("aggregate_id",))
    return reg


def make_replay_spec() -> ReplaySpec:
    def incremented(s, f):
        return {"count": s["count"] + f["increment_by"], "version": f["sequence_number"]}

    def decremented(s, f):
        return {"count": s["count"] - f["decrement_by"], "version": f["sequence_number"]}

    def unserializable(s, f):
        # reference: version bumps to sequenceNumber, count unchanged
        return {"version": f["sequence_number"]}

    return ReplaySpec(
        registry=make_registry(),
        handlers=ReplayHandlers({INCREMENTED: incremented, DECREMENTED: decremented,
                                 UNSERIALIZABLE: unserializable}),
        init_record={"count": 0, "version": 0},
        associative=make_associative_fold(),
    )


@_functools.cache
def make_associative_fold():
    """The counter fold as an associative transform monoid, for
    sequence-parallel replay of very long logs (surge_tpu.replay.seqpar).

    Summary = (d_count, has_version_event, last_sequence_number): count is
    additive; version is the sequence number of the LAST version-setting event
    (inc/dec/unserializable — NoOpEvent leaves it, mirroring handle_event).
    ``combine`` is associative but not commutative (right-biased version).

    Repeated factory calls produce structurally-equal folds: seqpar's program
    cache keys on fold STRUCTURE, so each call shares the compiled programs
    (and the one-time conformance check) with its predecessors."""
    import jax.numpy as jnp

    from surge_tpu.replay.seqpar import AssociativeFold

    import numpy as np

    def lift(ev):
        tid = ev["type_id"]
        inc = (tid == INCREMENTED)
        dec = (tid == DECREMENTED)
        sets_version = inc | dec | (tid == UNSERIALIZABLE)
        d = (jnp.where(inc, ev["increment_by"], 0)
             - jnp.where(dec, ev["decrement_by"], 0))
        return {
            "d_count": d.astype(jnp.int32),
            "has": sets_version,
            "last_seq": jnp.where(sets_version, ev["sequence_number"],
                                  0).astype(jnp.int32),
        }

    def combine(a, b):
        return {
            "d_count": a["d_count"] + b["d_count"],
            "has": a["has"] | b["has"],
            "last_seq": jnp.where(b["has"], b["last_seq"], a["last_seq"]),
        }

    def apply(state, s):
        return {
            "count": (state["count"] + s["d_count"]).astype(jnp.int32),
            "version": jnp.where(s["has"], s["last_seq"],
                                 state["version"]).astype(jnp.int32),
        }

    return AssociativeFold(
        lift=lift, combine=combine, apply=apply,
        identity={"d_count": np.int32(0), "has": np.bool_(False),
                  "last_seq": np.int32(0)})


# --- byte formats (play-json Format equivalents, TestBoundedContext.scala:84-110) ---

_EVENT_TYPES = {c.__name__: c for c in (CountIncremented, CountDecremented, NoOpEvent,
                                        ExceptionThrowingEvent, UnserializableEvent)}


def _event_to_dict(e) -> dict:
    if isinstance(e, UnserializableEvent):
        # parity: the reference's play-json format for this event throws — that is the
        # point of the CreateUnserializableEvent poison command (TestBoundedContext
        # serialization-failure path). The tensor path still folds it.
        raise ValueError(f"deliberately unserializable event: {e.error_msg}")
    return _to_tagged_dict(e)


def _event_from_dict(d: dict):
    return _from_tagged_dict(_EVENT_TYPES, d)


_COMMAND_TYPES = {c.__name__: c for c in (Increment, Decrement, DoNothing,
                                          CreateNoOpEvent, FailCommandProcessing,
                                          CreateExceptionThrowingEvent,
                                          CreateUnserializableEvent)}


def _to_tagged_dict(obj) -> dict:
    d = {k: getattr(obj, k) for k in obj.__dataclass_fields__}
    d["_type"] = type(obj).__name__
    return d


def _from_tagged_dict(type_map: dict, d: dict):
    d = dict(d)
    return type_map[d.pop("_type")](**d)


def command_formatting() -> JsonCommandFormatting:
    """Command codec for cross-node delivery (remote transport tests)."""
    return JsonCommandFormatting(
        to_dict=_to_tagged_dict,
        from_dict=lambda d: _from_tagged_dict(_COMMAND_TYPES, d))


def state_formatting() -> JsonFormatting:
    return JsonFormatting(
        to_dict=lambda s: {"aggregate_id": s.aggregate_id, "count": s.count, "version": s.version},
        from_dict=lambda d: State(**d))


def event_formatting() -> JsonEventFormatting:
    return JsonEventFormatting(to_dict=_event_to_dict, from_dict=_event_from_dict,
                               key_of=lambda e: e.aggregate_id)
