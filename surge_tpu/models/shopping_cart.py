"""ShoppingCart — variable-length-log fixture for ragged/segmented replay
(BASELINE.json config: "ShoppingCart aggregate, variable-length event logs").

The reference has no shopping-cart sample; this family exists to exercise the ragged
batching path (length buckets + masked scan) and a richer heterogeneous event set.
Prices are integer cents so scalar↔tensor golden comparisons are exact.
"""

from __future__ import annotations

import functools as _functools
from dataclasses import dataclass
from typing import Optional, Sequence

from surge_tpu.codec.schema import SchemaRegistry
from surge_tpu.engine.model import RejectedCommand, ReplayHandlers, ReplaySpec
from surge_tpu.serialization import JsonEventFormatting, JsonFormatting


@dataclass(frozen=True)
class Cart:
    cart_id: str
    item_count: int
    total_cents: int
    checked_out: bool
    version: int


# commands
@dataclass(frozen=True)
class AddItem:
    cart_id: str
    item_code: int
    quantity: int
    unit_price_cents: int


@dataclass(frozen=True)
class RemoveItem:
    cart_id: str
    item_code: int
    quantity: int
    unit_price_cents: int


@dataclass(frozen=True)
class Checkout:
    cart_id: str


# events
@dataclass(frozen=True)
class ItemAdded:
    cart_id: str
    item_code: int
    quantity: int
    unit_price_cents: int
    sequence_number: int


@dataclass(frozen=True)
class ItemRemoved:
    cart_id: str
    item_code: int
    quantity: int
    unit_price_cents: int
    sequence_number: int


@dataclass(frozen=True)
class CheckedOut:
    cart_id: str
    sequence_number: int


class CartAlreadyCheckedOut(RejectedCommand):
    pass


class CartModel:
    def initial_state(self, aggregate_id: str) -> Optional[Cart]:
        return None

    def process_command(self, state: Optional[Cart], command) -> Sequence[object]:
        if state is not None and state.checked_out:
            raise CartAlreadyCheckedOut(command.cart_id)
        seq = (state.version if state else 0) + 1
        if isinstance(command, AddItem):
            return [ItemAdded(command.cart_id, command.item_code, command.quantity,
                              command.unit_price_cents, seq)]
        if isinstance(command, RemoveItem):
            have = state.item_count if state else 0
            qty = min(command.quantity, have)
            if qty <= 0:
                return []
            return [ItemRemoved(command.cart_id, command.item_code, qty,
                                command.unit_price_cents, seq)]
        if isinstance(command, Checkout):
            return [CheckedOut(command.cart_id, seq)]
        raise RejectedCommand(f"unknown command {command!r}")

    def handle_event(self, state: Optional[Cart], event) -> Optional[Cart]:
        cur = state if state is not None else Cart(event.cart_id, 0, 0, False, 0)
        if isinstance(event, ItemAdded):
            return Cart(cur.cart_id, cur.item_count + event.quantity,
                        cur.total_cents + event.quantity * event.unit_price_cents,
                        cur.checked_out, event.sequence_number)
        if isinstance(event, ItemRemoved):
            return Cart(cur.cart_id, cur.item_count - event.quantity,
                        cur.total_cents - event.quantity * event.unit_price_cents,
                        cur.checked_out, event.sequence_number)
        if isinstance(event, CheckedOut):
            return Cart(cur.cart_id, cur.item_count, cur.total_cents, True, event.sequence_number)
        return cur

    def replay_spec(self) -> ReplaySpec:
        return make_replay_spec()


ADDED, REMOVED, CHECKED_OUT = 0, 1, 2


def make_registry() -> SchemaRegistry:
    reg = SchemaRegistry()
    reg.register_event(ItemAdded, type_id=ADDED, exclude=("cart_id",))
    reg.register_event(ItemRemoved, type_id=REMOVED, exclude=("cart_id",))
    reg.register_event(CheckedOut, type_id=CHECKED_OUT, exclude=("cart_id",))
    reg.register_state(Cart, exclude=("cart_id",))
    return reg


def make_replay_spec() -> ReplaySpec:
    def added(s, f):
        return {"item_count": s["item_count"] + f["quantity"],
                "total_cents": s["total_cents"] + f["quantity"] * f["unit_price_cents"],
                "checked_out": s["checked_out"],
                "version": f["sequence_number"]}

    def removed(s, f):
        return {"item_count": s["item_count"] - f["quantity"],
                "total_cents": s["total_cents"] - f["quantity"] * f["unit_price_cents"],
                "checked_out": s["checked_out"],
                "version": f["sequence_number"]}

    def checked_out(s, f):
        import jax.numpy as jnp
        return {"item_count": s["item_count"], "total_cents": s["total_cents"],
                "checked_out": jnp.asarray(True), "version": f["sequence_number"]}

    return ReplaySpec(
        registry=make_registry(),
        handlers=ReplayHandlers({ADDED: added, REMOVED: removed, CHECKED_OUT: checked_out}),
        init_record={"item_count": 0, "total_cents": 0, "checked_out": False, "version": 0},
        associative=make_associative_fold(),
    )


@_functools.cache
def make_associative_fold():
    """The cart fold as an associative transform monoid for sequence-parallel
    replay (surge_tpu.replay.seqpar): item/total deltas are additive,
    checked_out is OR-monotone, version is right-biased on any real event.
    Repeated factory calls are structurally equal, sharing seqpar's compiled
    programs and one-time conformance check."""
    import jax.numpy as jnp
    import numpy as np

    from surge_tpu.replay.seqpar import AssociativeFold

    def lift(ev):
        tid = ev["type_id"]
        add = tid == ADDED
        rem = tid == REMOVED
        real = add | rem | (tid == CHECKED_OUT)
        signed_qty = (jnp.where(add, ev["quantity"], 0)
                      - jnp.where(rem, ev["quantity"], 0))
        return {
            "d_items": signed_qty.astype(jnp.int32),
            "d_cents": (signed_qty * ev["unit_price_cents"]).astype(jnp.int32),
            "checked": tid == CHECKED_OUT,
            "has": real,
            "last_seq": jnp.where(real, ev["sequence_number"],
                                  0).astype(jnp.int32),
        }

    def combine(a, b):
        return {
            "d_items": a["d_items"] + b["d_items"],
            "d_cents": a["d_cents"] + b["d_cents"],
            "checked": a["checked"] | b["checked"],
            "has": a["has"] | b["has"],
            "last_seq": jnp.where(b["has"], b["last_seq"], a["last_seq"]),
        }

    def apply(state, s):
        return {
            "item_count": (state["item_count"] + s["d_items"]).astype(jnp.int32),
            "total_cents": (state["total_cents"] + s["d_cents"]).astype(jnp.int32),
            "checked_out": state["checked_out"] | s["checked"],
            "version": jnp.where(s["has"], s["last_seq"],
                                 state["version"]).astype(jnp.int32),
        }

    return AssociativeFold(
        lift=lift, combine=combine, apply=apply,
        identity={"d_items": np.int32(0), "d_cents": np.int32(0),
                  "checked": np.bool_(False), "has": np.bool_(False),
                  "last_seq": np.int32(0)})


_EVENTS = {c.__name__: c for c in (ItemAdded, ItemRemoved, CheckedOut)}


def state_formatting() -> JsonFormatting:
    return JsonFormatting(
        to_dict=lambda s: {k: getattr(s, k) for k in s.__dataclass_fields__},
        from_dict=lambda d: Cart(**d))


def event_formatting() -> JsonEventFormatting:
    def to_dict(e):
        d = {k: getattr(e, k) for k in e.__dataclass_fields__}
        d["_type"] = type(e).__name__
        return d

    def from_dict(d):
        d = dict(d)
        return _EVENTS[d.pop("_type")](**d)

    return JsonEventFormatting(to_dict=to_dict, from_dict=from_dict, key_of=lambda e: e.cart_id)
