"""Multilanguage gRPC bridge — polyglot business apps over a sidecar engine.

Capability parity with the reference's multilanguage modules (SURVEY.md §2.11):
the protocol IDL lives in ``proto/multilanguage.proto`` (regenerate bindings with
``proto/gen.sh``); :mod:`gateway` is the engine side (gateway service + the generic
gRPC-backed processing model); :mod:`sdk` is the app side (CQRSModel + SerDeser +
BusinessLogicServer + SurgeClient).
"""

from surge_tpu.multilanguage.gateway import (
    BytesCommand,
    BytesEvent,
    GrpcBusinessModel,
    MultilanguageGatewayServer,
    generic_business_logic,
)
from surge_tpu.multilanguage.sdk import (
    BusinessLogicServer,
    CommandRejectedByApp,
    CQRSModel,
    SerDeser,
    SurgeClient,
)

__all__ = [
    "BusinessLogicServer",
    "BytesCommand",
    "BytesEvent",
    "CQRSModel",
    "CommandRejectedByApp",
    "GrpcBusinessModel",
    "MultilanguageGatewayServer",
    "SerDeser",
    "SurgeClient",
    "generic_business_logic",
]
