"""The multilanguage gateway: a Surge engine whose business logic lives in another
process, reached over gRPC.

Reference roles reproduced (SURVEY.md §2.11):

- :class:`GrpcBusinessModel` — ``GenericAsyncAggregateCommandModel``
  (modules/multilanguage/.../GenericAsyncAggregateCommandModel.scala:14-104): the
  engine-side processing model whose ``process_command``/``handle_events`` are gRPC
  calls to the business app's ``BusinessLogic`` service, timed with the
  ``SURGE_GRPC_*``-equivalent metrics.
- byte-payload formats — ``GenericSurgeCommandBusinessLogic`` (protobuf-bytes
  read/write formatting, GenericSurgeCommandBusinessLogic.scala:14-43): the state
  topic stores the app's opaque payload verbatim.
- :class:`MultilanguageGatewayServer` — ``MultilanguageGatewayServer`` +
  ``MultilanguageGatewayServiceImpl`` (MultilanguageGatewayServiceImpl.scala:29-82):
  hosts ``MultilanguageGateway`` (ForwardCommand → ``aggregate_for(id).send_command``,
  GetState → ``.get_state``, HealthCheck → the engine health tree).

State on the wire is ``AggregateState(exists=False)`` for "no aggregate"; inside the
engine, state is ``None`` or raw payload bytes.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional, Sequence

import grpc

from surge_tpu.engine.business_logic import SurgeCommandBusinessLogic
from surge_tpu.engine.entity import CommandRejected, CommandSuccess
from surge_tpu.engine.model import RejectedCommand
from surge_tpu.metrics import MetricInfo, Metrics
from surge_tpu.multilanguage import multilanguage_pb2 as pb
from surge_tpu.multilanguage.service import (
    BUSINESS_METHODS,
    BUSINESS_SERVICE,
    GATEWAY_METHODS,
    GATEWAY_SERVICE,
    GATEWAY_STREAM_METHODS,
    generic_handler,
    unary_callables,
)
from surge_tpu.serialization import SerializedAggregate, SerializedMessage


@dataclass(frozen=True)
class BytesCommand:
    """An opaque app command routed through the engine."""

    aggregate_id: str
    payload: bytes


@dataclass(frozen=True)
class BytesEvent:
    """An opaque app event (the envelope keeps the aggregate id for the events
    topic key and the HandleEvents callback)."""

    aggregate_id: str
    payload: bytes


class GrpcBusinessModel:
    """Async processing model delegating to the app's BusinessLogic service.

    State is ``Optional[bytes]`` (the app's serialized state), events are raw
    payload bytes — the engine never interprets them.
    """

    def __init__(self, channel: grpc.aio.Channel,
                 metrics: Optional[Metrics] = None) -> None:
        self._calls = unary_callables(channel, BUSINESS_SERVICE, BUSINESS_METHODS)
        m = metrics or Metrics()
        # the SURGE_GRPC_* call timers of GenericAsyncAggregateCommandModel.scala:24-38
        self._process_timer = m.timer(MetricInfo(
            "surge.grpc.process-command-timer",
            "Round-trip latency of BusinessLogic.ProcessCommand"))
        self._handle_timer = m.timer(MetricInfo(
            "surge.grpc.handle-events-timer",
            "Round-trip latency of BusinessLogic.HandleEvents"))

    def initial_state(self, aggregate_id: str) -> Optional[bytes]:
        return None

    @staticmethod
    def _wire_state(aggregate_id: str, state: Optional[bytes]) -> pb.AggregateState:
        return pb.AggregateState(aggregate_id=aggregate_id, payload=state or b"",
                                 exists=state is not None)

    async def process_command(self, state: Optional[bytes],
                              command: BytesCommand) -> Sequence[BytesEvent]:
        req = pb.ProcessCommandRequest(
            state=self._wire_state(command.aggregate_id, state),
            command=pb.DomainCommand(aggregate_id=command.aggregate_id,
                                     payload=command.payload))
        with self._process_timer.time():
            reply = await self._calls["ProcessCommand"](req)
        if not reply.success:
            raise RejectedCommand(reply.rejection or "rejected by business app")
        return [BytesEvent(e.aggregate_id or command.aggregate_id, e.payload)
                for e in reply.events]

    async def handle_events(self, state: Optional[bytes],
                            events: Sequence[BytesEvent]) -> Optional[bytes]:
        if not events:
            return state
        agg_id = events[0].aggregate_id
        req = pb.HandleEventsRequest(
            state=self._wire_state(agg_id, state),
            events=[pb.DomainEvent(aggregate_id=e.aggregate_id, payload=e.payload)
                    for e in events])
        with self._handle_timer.time():
            reply = await self._calls["HandleEvents"](req)
        return reply.state.payload if reply.state.exists else None


class _PassthroughStateFormat:
    """State bytes on the topic == the app's payload (protobuf-bytes formatting).

    ``None`` state writes a tombstone (``value=None`` deletes the key from the
    compacted topic), so an app state that legitimately serializes to zero bytes —
    any all-default proto message — round-trips as ``exists=True, payload=b""``
    instead of collapsing to "does not exist"."""

    def write_state(self, state: Optional[bytes]) -> SerializedAggregate:
        return SerializedAggregate(value=state)

    def read_state(self, data: bytes) -> Optional[bytes]:
        return bytes(data)


class _PassthroughEventFormat:
    def write_event(self, event: BytesEvent) -> SerializedMessage:
        return SerializedMessage(key=event.aggregate_id, value=event.payload)

    def read_event(self, msg: SerializedMessage) -> BytesEvent:
        return BytesEvent(msg.key, msg.value)


def generic_business_logic(aggregate_name: str, channel: grpc.aio.Channel,
                           metrics: Optional[Metrics] = None
                           ) -> SurgeCommandBusinessLogic:
    """The GenericSurgeCommandBusinessLogic analog: byte payloads end to end."""
    return SurgeCommandBusinessLogic(
        aggregate_name=aggregate_name,
        model=GrpcBusinessModel(channel, metrics),
        state_format=_PassthroughStateFormat(),
        event_format=_PassthroughEventFormat())


class MultilanguageGatewayServer:
    """gRPC server exposing an engine to polyglot apps (SidecarMain analog)."""

    def __init__(self, engine, host: str = "127.0.0.1", port: int = 0) -> None:
        self.engine = engine
        self._host = host
        self._port = port
        self._server: Optional[grpc.aio.Server] = None
        self.bound_port: Optional[int] = None

    # -- service implementation ----------------------------------------------------------

    async def ForwardCommand(self, request: pb.ForwardCommandRequest,
                             context) -> pb.ForwardCommandReply:
        cmd = request.command
        result = await self.engine.aggregate_for(cmd.aggregate_id).send_command(
            BytesCommand(cmd.aggregate_id, cmd.payload))
        if isinstance(result, CommandSuccess):
            return pb.ForwardCommandReply(
                success=True,
                state=GrpcBusinessModel._wire_state(cmd.aggregate_id, result.state))
        if isinstance(result, CommandRejected):
            return pb.ForwardCommandReply(success=False, rejection=str(result.reason))
        await context.abort(grpc.StatusCode.INTERNAL, str(result.error))

    async def GetState(self, request: pb.GetStateRequest, context) -> pb.GetStateReply:
        state = await self.engine.aggregate_for(request.aggregate_id).get_state()
        return pb.GetStateReply(
            state=GrpcBusinessModel._wire_state(request.aggregate_id, state))

    async def HealthCheck(self, request: pb.HealthRequest, context) -> pb.HealthReply:
        health = self.engine.health_check()
        return pb.HealthReply(status="up" if health.is_healthy() else "down")

    # -- read-side analytics (message reuse; docs/replay.md) ----------------------------

    @staticmethod
    def _json_reply(name: str, payload: dict) -> pb.GetStateReply:
        import json

        return pb.GetStateReply(state=pb.AggregateState(
            aggregate_id=name, payload=json.dumps(payload).encode(),
            exists=True))

    async def QueryStates(self, request: pb.GetStateRequest,
                          context) -> pb.GetStateReply:
        """Fold-then-filter state query through the sidecar: the polyglot
        app's "every matching aggregate's current state" read.
        ``aggregate_id`` carries the StateQuery JSON; the reply payload is
        the same capped rows JSON the admin ``QueryStates`` RPC serves."""
        import json

        try:
            q = json.loads(request.aggregate_id or "{}")
            result = await self.engine.query_states(q)
            cap = self.engine.config.get_int("surge.query.max-rows", 10_000)
            return self._json_reply("query", {
                "rows": result.rows(limit=cap),
                "num_aggregates": result.num_aggregates,
                "scanned_events": result.scanned_events,
                "matched_events": result.matched_events,
                "truncated": result.num_aggregates > cap,
            })
        except Exception as exc:  # noqa: BLE001 — app gets the failure back
            return self._json_reply("query", {"error": repr(exc)})

    async def QueryView(self, request: pb.GetStateRequest,
                        context) -> pb.GetStateReply:
        """Materialized-view snapshot through the sidecar. ``aggregate_id``
        carries the view name ("" = the per-view operator summary)."""
        try:
            name = (request.aggregate_id or "").strip()
            if not name or name == "{}":
                return self._json_reply("views", {
                    "views": await self.engine.view_summary()})
            snap = await self.engine.query_view(name)
            return self._json_reply(name, {
                k: v for k, v in snap.items() if k != "columns"})
        except Exception as exc:  # noqa: BLE001 — app gets the failure back
            return self._json_reply("views", {"error": repr(exc)})

    async def SubscribeView(self, request: pb.GetStateRequest, context):
        """Server-streaming changefeed through the sidecar (the admin
        ``SubscribeView`` twin): ``aggregate_id`` carries ``{"view",
        "from_version"}`` JSON, each frame's payload one changefeed entry."""
        import json

        try:
            req = json.loads(request.aggregate_id or "{}")
            sub = await self.engine.subscribe_view(
                req["view"], req.get("from_version"))
        except Exception as exc:  # noqa: BLE001 — app gets the failure back
            yield self._json_reply("views", {"error": repr(exc)})
            return
        try:
            async for entry in sub:
                yield self._json_reply(entry.get("view", "views"), entry)
                if entry.get("closed"):
                    return
        finally:
            self.engine.views.unsubscribe(sub)

    # -- lifecycle -----------------------------------------------------------------------

    async def start(self) -> int:
        from surge_tpu.remote.security import add_secure_port

        self._server = grpc.aio.server()
        self._server.add_generic_rpc_handlers(
            (generic_handler(GATEWAY_SERVICE, GATEWAY_METHODS, self,
                             stream_methods=GATEWAY_STREAM_METHODS),))
        self.bound_port = add_secure_port(
            self._server, f"{self._host}:{self._port}",
            getattr(self.engine, "config", None))
        await self._server.start()
        return self.bound_port

    async def stop(self, grace: float = 1.0) -> None:
        if self._server is not None:
            await self._server.stop(grace)
            self._server = None
