"""App-side SDK: write pure CQRS handlers in any process, serve them to the engine.

The Python counterpart of the reference's language SDKs
(multilanguage-scala-sdk/.../ScalaSurge.scala:16-77 — ``CQRSModel`` of two pure
functions + ``SerDeser`` + a server binding the BusinessLogicService; the C# SDK has
the same shape, SurgeEngine.cs:12-80):

- :class:`CQRSModel` — ``process_command(state, command) -> [events]`` (raise
  :class:`CommandRejectedByApp` to reject) and ``handle_events(state, events) -> state``
  over the app's own domain objects.
- :class:`SerDeser` — app-object ⇄ bytes codecs for state/command/event.
- :class:`BusinessLogicServer` — hosts the ``BusinessLogic`` gRPC service over the
  model (the engine's :class:`~surge_tpu.multilanguage.gateway.GrpcBusinessModel`
  calls it).
- :class:`SurgeClient` — the app's typed handle on the gateway
  (forward_command/get_state/health over ``MultilanguageGateway``).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable, Optional, Sequence, Tuple

import grpc

from surge_tpu.multilanguage import multilanguage_pb2 as pb
from surge_tpu.multilanguage.service import (
    BUSINESS_METHODS,
    BUSINESS_SERVICE,
    GATEWAY_METHODS,
    GATEWAY_SERVICE,
    GATEWAY_STREAM_METHODS,
    generic_handler,
    stream_callables,
    unary_callables,
)


class CommandRejectedByApp(Exception):
    """Raised by app command handlers to reject a command (maps to a rejection
    reply, not an error)."""


@dataclass
class CQRSModel:
    """Two pure functions over app domain objects (scala-sdk Model.scala analog)."""

    process_command: Callable[[Optional[Any], Any], Sequence[Any]]
    handle_events: Callable[[Optional[Any], Sequence[Any]], Optional[Any]]


@dataclass
class SerDeser:
    """App-object ⇄ bytes codecs (scala-sdk SerDeser analog)."""

    serialize_state: Callable[[Any], bytes]
    deserialize_state: Callable[[bytes], Any]
    serialize_event: Callable[[Any], bytes]
    deserialize_event: Callable[[bytes], Any]
    serialize_command: Callable[[Any], bytes]
    deserialize_command: Callable[[bytes], Any]


class BusinessLogicServer:
    """Hosts the app's CQRSModel as the BusinessLogic gRPC service."""

    def __init__(self, model: CQRSModel, serdes: SerDeser,
                 host: str = "127.0.0.1", port: int = 0) -> None:
        self.model = model
        self.serdes = serdes
        self._host = host
        self._port = port
        self._server: Optional[grpc.aio.Server] = None
        self.bound_port: Optional[int] = None

    def _state_in(self, wire: pb.AggregateState) -> Optional[Any]:
        return self.serdes.deserialize_state(wire.payload) if wire.exists else None

    def _state_out(self, aggregate_id: str, state: Optional[Any]) -> pb.AggregateState:
        if state is None:
            return pb.AggregateState(aggregate_id=aggregate_id, exists=False)
        return pb.AggregateState(aggregate_id=aggregate_id,
                                 payload=self.serdes.serialize_state(state),
                                 exists=True)

    # -- service implementation ----------------------------------------------------------

    async def ProcessCommand(self, request: pb.ProcessCommandRequest,
                             context) -> pb.ProcessCommandReply:
        state = self._state_in(request.state)
        command = self.serdes.deserialize_command(request.command.payload)
        try:
            events = self.model.process_command(state, command)
        except CommandRejectedByApp as rej:
            return pb.ProcessCommandReply(success=False, rejection=str(rej))
        agg = request.command.aggregate_id
        return pb.ProcessCommandReply(success=True, events=[
            pb.DomainEvent(aggregate_id=agg,
                           payload=self.serdes.serialize_event(e))
            for e in events])

    async def HandleEvents(self, request: pb.HandleEventsRequest,
                           context) -> pb.HandleEventsReply:
        state = self._state_in(request.state)
        events = [self.serdes.deserialize_event(e.payload) for e in request.events]
        new_state = self.model.handle_events(state, events)
        return pb.HandleEventsReply(
            state=self._state_out(request.state.aggregate_id, new_state))

    async def HealthCheck(self, request: pb.HealthRequest, context) -> pb.HealthReply:
        return pb.HealthReply(status="up")

    # -- lifecycle -----------------------------------------------------------------------

    async def start(self) -> int:
        self._server = grpc.aio.server()
        self._server.add_generic_rpc_handlers(
            (generic_handler(BUSINESS_SERVICE, BUSINESS_METHODS, self),))
        self.bound_port = self._server.add_insecure_port(f"{self._host}:{self._port}")
        await self._server.start()
        return self.bound_port

    async def stop(self, grace: float = 1.0) -> None:
        if self._server is not None:
            await self._server.stop(grace)
            self._server = None


class SurgeClient:
    """Typed app handle on the gateway (ScalaSurgeServer's client analog)."""

    def __init__(self, channel: grpc.aio.Channel, serdes: SerDeser) -> None:
        self._calls = unary_callables(channel, GATEWAY_SERVICE, GATEWAY_METHODS)
        self._streams = stream_callables(channel, GATEWAY_SERVICE,
                                         GATEWAY_STREAM_METHODS)
        self.serdes = serdes

    async def forward_command(self, aggregate_id: str, command: Any
                              ) -> Tuple[bool, Optional[Any], str]:
        """Returns (success, state, rejection_reason)."""
        reply = await self._calls["ForwardCommand"](pb.ForwardCommandRequest(
            command=pb.DomainCommand(
                aggregate_id=aggregate_id,
                payload=self.serdes.serialize_command(command))))
        if not reply.success:
            return False, None, reply.rejection
        state = (self.serdes.deserialize_state(reply.state.payload)
                 if reply.state.exists else None)
        return True, state, ""

    async def get_state(self, aggregate_id: str) -> Optional[Any]:
        reply = await self._calls["GetState"](
            pb.GetStateRequest(aggregate_id=aggregate_id))
        return (self.serdes.deserialize_state(reply.state.payload)
                if reply.state.exists else None)

    async def health(self) -> str:
        return (await self._calls["HealthCheck"](pb.HealthRequest())).status

    # -- read-side analytics (message reuse; docs/replay.md) ----------------------------

    async def query_states(self, query: dict) -> dict:
        """Fold-then-filter state query (StateQuery json form) through the
        gateway; returns the capped rows payload. Raises RuntimeError on a
        refused/failed query."""
        import json

        reply = await self._calls["QueryStates"](
            pb.GetStateRequest(aggregate_id=json.dumps(query)))
        payload = json.loads(reply.state.payload)
        if "error" in payload and "rows" not in payload:
            raise RuntimeError(payload["error"])
        return payload

    async def query_view(self, name: str = "") -> dict:
        """Materialized-view snapshot (or, with no name, the per-view
        operator summary) through the gateway. Raises RuntimeError when the
        query is refused; a degraded view's payload is returned as-is."""
        import json

        reply = await self._calls["QueryView"](
            pb.GetStateRequest(aggregate_id=name))
        payload = json.loads(reply.state.payload)
        if "error" in payload and "view" not in payload \
                and "views" not in payload:
            raise RuntimeError(payload["error"])
        return payload

    def subscribe_view(self, view: str, from_version: Optional[int] = None):
        """Changefeed subscription through the gateway: an async iterator of
        entry dicts (reconciling snapshot or exactly-missed deltas first,
        then live per-round deltas). End it early by breaking out; raises
        RuntimeError when the subscription is refused."""
        import json

        call = self._streams["SubscribeView"](pb.GetStateRequest(
            aggregate_id=json.dumps({"view": view,
                                     "from_version": from_version})))

        async def entries():
            try:
                async for reply in call:
                    payload = json.loads(reply.state.payload)
                    if "error" in payload and "view" not in payload:
                        raise RuntimeError(payload["error"])
                    yield payload
                    if payload.get("closed"):
                        return
            finally:
                call.cancel()

        return entries()
