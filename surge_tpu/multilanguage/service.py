"""Hand-written gRPC service glue (grpcio-tools is not available to codegen stubs).

Method tables for the two services in proto/multilanguage.proto; servers register
them via :func:`generic_handler`, clients build typed callables via
:func:`unary_callables`. Equivalent surface to the generated ``*_pb2_grpc`` modules.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Mapping

import grpc

from surge_tpu.multilanguage import multilanguage_pb2 as pb

_PACKAGE = "surge_tpu.multilanguage"

GATEWAY_SERVICE = f"{_PACKAGE}.MultilanguageGateway"
BUSINESS_SERVICE = f"{_PACKAGE}.BusinessLogic"

#: method name -> (request message class, reply message class)
GATEWAY_METHODS: Dict[str, tuple] = {
    "ForwardCommand": (pb.ForwardCommandRequest, pb.ForwardCommandReply),
    "GetState": (pb.GetStateRequest, pb.GetStateReply),
    "HealthCheck": (pb.HealthRequest, pb.HealthReply),
    # read-side analytics through the sidecar (message reuse — routed by this
    # table, not the frozen descriptor): GetStateRequest.aggregate_id carries
    # the request JSON, GetStateReply.state.payload carries the result JSON
    "QueryStates": (pb.GetStateRequest, pb.GetStateReply),
    "QueryView": (pb.GetStateRequest, pb.GetStateReply),
}

#: server-streaming gateway methods (same message-reuse discipline):
#: SubscribeView's aggregate_id carries {"view", "from_version"} JSON and
#: each reply frame's state.payload is one changefeed entry
GATEWAY_STREAM_METHODS: Dict[str, tuple] = {
    "SubscribeView": (pb.GetStateRequest, pb.GetStateReply),
}

BUSINESS_METHODS: Dict[str, tuple] = {
    "ProcessCommand": (pb.ProcessCommandRequest, pb.ProcessCommandReply),
    "HandleEvents": (pb.HandleEventsRequest, pb.HandleEventsReply),
    "HealthCheck": (pb.HealthRequest, pb.HealthReply),
}


def generic_handler(service_name: str, methods: Mapping[str, tuple],
                    implementation: Any,
                    stream_methods: Mapping[str, tuple] | None = None
                    ) -> grpc.GenericRpcHandler:
    """Build a server handler mapping each method to ``implementation.<Method>``
    (an async callable ``(request, context) -> reply``). ``stream_methods``
    entries are server-streaming: the implementation method is an async
    GENERATOR yielding replies (the changefeed shape — SubscribeView)."""
    rpc_handlers = {}
    for name, (req_cls, reply_cls) in methods.items():
        fn = getattr(implementation, name)
        rpc_handlers[name] = grpc.unary_unary_rpc_method_handler(
            fn, request_deserializer=req_cls.FromString,
            response_serializer=reply_cls.SerializeToString)
    for name, (req_cls, reply_cls) in (stream_methods or {}).items():
        fn = getattr(implementation, name)
        rpc_handlers[name] = grpc.unary_stream_rpc_method_handler(
            fn, request_deserializer=req_cls.FromString,
            response_serializer=reply_cls.SerializeToString)
    return grpc.method_handlers_generic_handler(service_name, rpc_handlers)


def unary_callables(channel: grpc.aio.Channel, service_name: str,
                    methods: Mapping[str, tuple]) -> Dict[str, Callable]:
    """Typed client callables ``{method: async fn(request) -> reply}``."""
    out = {}
    for name, (req_cls, reply_cls) in methods.items():
        out[name] = channel.unary_unary(
            f"/{service_name}/{name}",
            request_serializer=req_cls.SerializeToString,
            response_deserializer=reply_cls.FromString)
    return out


def stream_callables(channel: grpc.aio.Channel, service_name: str,
                     methods: Mapping[str, tuple]) -> Dict[str, Callable]:
    """Server-streaming client callables ``{method: fn(request) -> call}``
    where the call is async-iterable over replies (and ``.cancel()``-able)."""
    out = {}
    for name, (req_cls, reply_cls) in methods.items():
        out[name] = channel.unary_stream(
            f"/{service_name}/{name}",
            request_serializer=req_cls.SerializeToString,
            response_deserializer=reply_cls.FromString)
    return out
