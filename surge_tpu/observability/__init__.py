"""Fleet observability plane: flight recorders (broker AND engine), the
failover-timeline reconstruction they feed, the federated scraper that merges
every fleet member's OpenMetrics payload into one exposition, and the SLO
burn-rate engine evaluated on top of it (docs/observability.md,
docs/operations.md).

The metrics/tracing half of the telemetry plane lives in
:mod:`surge_tpu.metrics` / :mod:`surge_tpu.tracing`; this package holds the
black-box and fleet-level pieces — bounded in-memory event recording at the
sites a post-incident analysis needs, the merge/reconstruction tooling that
turns per-process dumps into one ordered story, cross-fleet scrape
federation, and multiwindow burn-rate alerting over the merged payload.
"""

from surge_tpu.observability.anatomy import (
    assemble_traces,
    attribute_trace,
    attribution_table,
    dominant_leg,
)
from surge_tpu.observability.federation import (
    FederatedScraper,
    ScrapeTarget,
    parse_openmetrics,
    target_from_spec,
)
from surge_tpu.observability.flight import (
    FlightRecorder,
    host_wall_offset,
    merge_dumps,
    reconstruct_failover,
    same_clock_domain,
)
from surge_tpu.observability.roofline import (
    RooflineRecorder,
    against_reference,
    roofline_row,
)
from surge_tpu.observability.slo import DEFAULT_SLOS, SLO, SLOEngine

__all__ = ["DEFAULT_SLOS", "FederatedScraper", "FlightRecorder",
           "RooflineRecorder", "SLO", "SLOEngine", "ScrapeTarget",
           "against_reference", "assemble_traces", "attribute_trace",
           "attribution_table", "dominant_leg", "host_wall_offset",
           "merge_dumps", "parse_openmetrics", "reconstruct_failover",
           "roofline_row", "same_clock_domain", "target_from_spec"]
