"""Cluster observability plane: the broker flight recorder and the failover
timeline reconstruction it feeds (docs/observability.md, docs/operations.md).

The metrics/tracing half of the telemetry plane lives in
:mod:`surge_tpu.metrics` / :mod:`surge_tpu.tracing`; this package holds the
black-box pieces — bounded in-memory event recording at the sites a
post-incident analysis needs, and the merge/reconstruction tooling that turns
per-broker dumps into one ordered story.
"""

from surge_tpu.observability.flight import (
    FlightRecorder,
    host_wall_offset,
    merge_dumps,
    reconstruct_failover,
    same_clock_domain,
)

__all__ = ["FlightRecorder", "merge_dumps", "reconstruct_failover",
           "same_clock_domain", "host_wall_offset"]
