"""Command anatomy: cross-process trace assembly + the critical-path latency
attributor (ISSUE 14 — the analysis half of the command-anatomy plane).

**Assembly.** Brokers and engines each retain their tail-kept spans in a
bounded :class:`~surge_tpu.tracing.tail.TraceRing`; :func:`assemble_traces`
merges several rings' ``DumpTraces`` envelopes into whole traces. Spans are
placed on one timeline by the SAME mono↔wall offset estimation the flight
merge uses (:func:`~surge_tpu.observability.flight.host_wall_offset`): each
dump's header pairs the host's two clocks at one instant, so every span of
that dump is positioned at ``offset + start_mono`` — an NTP step or a
deliberately skewed wall clock during the incident cannot scramble the order
of a trace's legs (tests/test_anatomy.py proves a 3-host dump set whose raw
wall order inverts the fsync leg still assembles correctly). Dumps without
the header pair (hand-built) fall back to raw wall stamps.

**Attribution.** For each assembled COMMAND trace (one that reaches a broker
``log.server.transact`` span), :func:`attribute_trace` decomposes the root
span's wall time into named legs along the ack critical path:

- ``mailbox-wait`` — ask boundary → entity receive (routing + mailbox);
- ``command-handling`` — entity receive → publish enqueue (handler + fold +
  serialize);
- ``publisher-linger`` — publish enqueue → flush dispatch (the group-commit
  linger actually paid);
- ``lane-dispatch`` — flush dispatch → the broker call leaving the client;
- ``router-resolve`` — PartitionRouter resolve/redirect/retry time around
  the broker calls (router span self-time);
- ``gate-wait`` — the broker's in-order/dedup apply gate hold
  (``leg.gate-wait-ms`` span attribute);
- ``journal-fsync`` — local apply + the WAL group-commit fsync round
  (``leg.fsync-ms``);
- ``replication-ack`` — the quorum/in-sync replication ack wait
  (``leg.repl-ms``);
- ``reply-decode`` — client-observed broker-call time not accounted on the
  broker (wire + reply decode);
- ``gather-coalesce`` / ``device-dispatch`` / ``fetch-barrier`` /
  ``decode`` — the DEVICE legs (the fold anatomy, ISSUE 16): resident-plane
  ``resident.gather`` and engine ``query.scan`` spans carry measured
  ``leg.{coalesce,dispatch,fetch,decode}-ms`` attributes, and the replay
  profiler's ``replay.dispatch``/``replay.compile``/``replay.fetch`` stage
  spans map by name — so a stalled refresh dispatch names
  ``device-dispatch`` dominant the same way a slow WAL names
  ``journal-fsync``;
- ``other`` — root residue none of the above claims (reply fan-out, event
  loop scheduling).

Legs are *self-times on the critical path*: they sum to (at most) the root
duration, so a leg's share IS its share of the command's wall time.
:func:`attribution_table` aggregates kept traces into per-leg
p50/p99/total/share rows and names the dominant leg — the evidence the next
perf PR starts from, instead of paired-ladder medians that can only say THAT
time was lost, not where.
"""

from __future__ import annotations

import time
from typing import Dict, Iterable, List, Optional, Sequence

from surge_tpu.observability.flight import host_wall_offset

__all__ = ["LEGS", "assemble_traces", "attribute_trace", "attribution_table",
           "dominant_leg"]

#: attribution legs in critical-path order (the table's row order)
LEGS = ("mailbox-wait", "command-handling", "publisher-linger",
        "lane-dispatch", "router-resolve", "gate-wait", "journal-fsync",
        "replication-ack", "reply-decode", "gather-coalesce",
        "device-dispatch", "fetch-barrier", "decode", "other")

#: broker span attributes carrying measured waits (surge_tpu/log/server.py
#: stamps them on the active ``log.server.transact`` span)
_BROKER_ATTR_LEGS = (("leg.gate-wait-ms", "gate-wait"),
                     ("leg.fsync-ms", "journal-fsync"),
                     ("leg.repl-ms", "replication-ack"))

#: span names marking a COMMAND-shaped trace: the attribution table skips
#: traces with none of these (an indexer's kept read-poll trace is one bare
#: ``log.Read`` span — aggregating it would dilute every command leg)
_COMMAND_MARKERS = ("aggregate-ref.", "entity.", "publisher.",
                    "router.commit", "log.server.transact", "log.Transact")

#: span-name prefixes of the device planes (resident gather lane, query
#: engine, replay profiler stages) — accepted alongside the command markers
#: so a kept device trace attributes instead of being skipped as noise
_DEVICE_MARKERS = ("resident.", "query.", "replay.")

#: device span attributes carrying measured leg times (resident_state's
#: gather spans, pipeline's query spans — measured, not inferred)
_DEVICE_ATTR_LEGS = (("leg.coalesce-ms", "gather-coalesce"),
                     ("leg.dispatch-ms", "device-dispatch"),
                     ("leg.fetch-ms", "fetch-barrier"),
                     ("leg.decode-ms", "decode"))

#: replay-profiler stage spans carry no leg attributes — their whole
#: duration IS the leg, mapped by name (host stages encode/h2d stay in
#: ``other``: they are not device legs)
_DEVICE_NAME_LEGS = (("replay.dispatch", "device-dispatch"),
                     ("replay.compile", "device-dispatch"),
                     ("replay.fetch", "fetch-barrier"))


def _place(span: dict, offset: Optional[float]) -> dict:
    """Copy a span with estimated-wall ``start``/``end`` stamps."""
    s = dict(span)
    if offset is not None and s.get("start_mono") is not None:
        s["start"] = offset + s["start_mono"]
        end_mono = s.get("end_mono")
        s["end"] = (offset + end_mono) if end_mono is not None \
            else s["start"]
    else:
        s["start"] = s.get("start_wall", 0.0)
        s["end"] = s.get("end_wall") or s["start"]
    return s


def assemble_traces(dumps: Sequence[dict]) -> Dict[str, List[dict]]:
    """Merge several ``DumpTraces`` envelopes into whole traces.

    Returns ``{trace_id: [span, ...]}`` with spans ordered by estimated wall
    start time; each span gains ``recorder``/``lane`` (who recorded it) and
    ``start``/``end`` (estimated-wall placement, module doc). ``keep_reason``
    carries the recorder's tail-keep verdict."""
    traces: Dict[str, List[dict]] = {}
    for d in dumps:
        who = d.get("recorder") or d.get("node") or "?"
        lane = d.get("role") or "broker"
        offset = host_wall_offset(d)
        for entry in d.get("traces", ()):
            tid = entry.get("trace_id", "")
            for span in entry.get("spans", ()):
                s = _place(span, offset)
                s["recorder"] = who
                s["lane"] = lane
                s["keep_reason"] = entry.get("reason", "")
                traces.setdefault(tid, []).append(s)
    for spans in traces.values():
        spans.sort(key=lambda s: (s["start"], s.get("span_id", "")))
    return traces


def _first_named(spans: Sequence[dict], *prefixes: str) -> Optional[dict]:
    for s in spans:
        name = s.get("name", "")
        if any(name.startswith(p) for p in prefixes):
            return s
    return None


def _dur(span: Optional[dict]) -> float:
    if span is None:
        return 0.0
    return max((span["end"] - span["start"]) * 1000.0, 0.0)


def attribute_trace(spans: Sequence[dict]) -> Optional[dict]:
    """Decompose one assembled trace into the critical-path legs.

    Returns ``{"trace_id", "duration_ms", "legs": {leg: ms}, "dominant"}``,
    or None for a trace with no recognizable command shape (no root span).
    Partial traces attribute the legs their spans cover; the residue stays
    in ``other`` rather than being guessed."""
    spans = list(spans)
    if not spans:
        return None
    root = next((s for s in spans if not s.get("parent_id")), None)
    if root is None:
        # every span is a child of something remote/unkept: use the earliest
        # as the envelope — partial anatomy beats none mid-incident
        root = spans[0]
    total_ms = _dur(root)
    legs = {leg: 0.0 for leg in LEGS}

    entity = _first_named(spans, "entity.")
    publish = _first_named(spans, "publisher.publish")
    flush = _first_named(spans, "publisher.flush")
    client_calls = [s for s in spans if s.get("name", "").startswith("log.")
                    and not s.get("name", "").startswith("log.server.")]
    broker_spans = [s for s in spans
                    if s.get("name", "") == "log.server.transact"]
    router_spans = [s for s in spans
                    if s.get("name", "").startswith("router.")]
    first_call = (router_spans[0] if router_spans
                  else (client_calls[0] if client_calls else None))

    if entity is not None:
        legs["mailbox-wait"] = max(
            (entity["start"] - root["start"]) * 1000.0, 0.0)
    if publish is not None and entity is not None:
        legs["command-handling"] = max(
            (publish["start"] - entity["start"]) * 1000.0, 0.0)
    if flush is not None and publish is not None:
        legs["publisher-linger"] = max(
            (flush["start"] - publish["start"]) * 1000.0, 0.0)
    if flush is not None and first_call is not None:
        legs["lane-dispatch"] = max(
            (first_call["start"] - flush["start"]) * 1000.0, 0.0)
    # router self-time: resolve/redirect/backoff around the broker calls.
    # Subtract only children NESTED UNDER a router span (router.resolve is a
    # child of router.commit, client calls are children of either) — summing
    # all router durations minus all client calls would double-count the
    # overlapped commit/resolve interval on redirect-heavy traces
    if router_spans:
        router_ids = {r.get("span_id") for r in router_spans}
        nested_client = sum(_dur(c) for c in client_calls
                            if c.get("parent_id") in router_ids)
        nested_router = sum(_dur(r) for r in router_spans
                            if r.get("parent_id") in router_ids)
        legs["router-resolve"] = max(
            sum(_dur(r) for r in router_spans)
            - nested_client - nested_router, 0.0)
    # broker-measured waits ride span attributes (measured, not inferred)
    for attr, leg in _BROKER_ATTR_LEGS:
        for b in broker_spans:
            try:
                legs[leg] += float((b.get("attributes") or {}).get(attr, 0.0))
            except (TypeError, ValueError):
                pass
    # device legs (the fold anatomy): gather/query spans claim their
    # measured leg attributes; attribute-less profiler stage spans map by
    # name — a span claims via attributes OR name, never both (the
    # attributes already decompose the span's own duration)
    for s in spans:
        name = s.get("name", "")
        if not name.startswith(_DEVICE_MARKERS):
            continue
        attrs = s.get("attributes") or {}
        claimed = False
        for attr, leg in _DEVICE_ATTR_LEGS:
            if attr in attrs:
                try:
                    legs[leg] += float(attrs[attr])
                    claimed = True
                except (TypeError, ValueError):
                    pass
        if not claimed:
            for prefix, leg in _DEVICE_NAME_LEGS:
                if name.startswith(prefix):
                    legs[leg] += _dur(s)
                    break
    # client-observed broker time the broker itself does not account for:
    # wire + request encode + reply decode
    if client_calls and broker_spans:
        client_ms = sum(_dur(c) for c in client_calls)
        broker_ms = sum(_dur(b) for b in broker_spans)
        legs["reply-decode"] = max(client_ms - broker_ms, 0.0)
    elif client_calls and flush is not None:
        # no broker dump for this trace: the whole call is unattributed wire
        legs["reply-decode"] = sum(_dur(c) for c in client_calls)

    accounted = sum(v for k, v in legs.items() if k != "other")
    if total_ms > 0.0:
        legs["other"] = max(total_ms - accounted, 0.0)
    dominant = max(legs, key=lambda leg: legs[leg]) if any(
        v > 0.0 for v in legs.values()) else None
    return {"trace_id": spans[0].get("trace_id", ""),
            "duration_ms": round(total_ms, 3),
            "legs": {k: round(v, 3) for k, v in legs.items()},
            "dominant": dominant}


def _percentile(values: List[float], q: float) -> float:
    if not values:
        return 0.0
    ordered = sorted(values)
    idx = min(int(q * (len(ordered) - 1) + 0.5), len(ordered) - 1)
    return ordered[idx]


def attribution_table(traces: Dict[str, List[dict]], metrics=None,
                      command_only: bool = True) -> dict:
    """Aggregate assembled traces into the per-leg attribution table.

    Returns ``{"traces": N, "legs": {leg: {"p50", "p99", "total_ms",
    "share"}}, "dominant", "dominant_share", "slowest": [...]}`` — shares
    are of the summed critical-path time across all attributed traces.
    ``command_only`` (default) restricts to command-shaped traces
    (``_COMMAND_MARKERS``) so kept read-poll traces cannot dilute the legs.
    ``metrics`` (a FleetMetrics quiver) records the assembly+attribution
    duration into ``surge.trace.assembly-timer``."""
    t0 = time.perf_counter()
    rows: List[dict] = []
    for tid, spans in traces.items():
        if command_only and not any(
                s.get("name", "").startswith(_COMMAND_MARKERS)
                or s.get("name", "").startswith(_DEVICE_MARKERS)
                for s in spans):
            continue
        row = attribute_trace(spans)
        if row is not None:
            row["trace_id"] = tid
            rows.append(row)
    per_leg: Dict[str, List[float]] = {leg: [] for leg in LEGS}
    for row in rows:
        for leg in LEGS:
            per_leg[leg].append(row["legs"].get(leg, 0.0))
    totals = {leg: sum(vals) for leg, vals in per_leg.items()}
    grand = sum(totals.values())
    legs = {leg: {"p50": round(_percentile(per_leg[leg], 0.50), 3),
                  "p99": round(_percentile(per_leg[leg], 0.99), 3),
                  "total_ms": round(totals[leg], 3),
                  "share": round(totals[leg] / grand, 4) if grand else 0.0}
            for leg in LEGS}
    dominant = max(totals, key=lambda leg: totals[leg]) if grand else None
    slowest = sorted(rows, key=lambda r: r["duration_ms"], reverse=True)[:5]
    out = {"traces": len(rows), "legs": legs, "dominant": dominant,
           "dominant_share": (round(totals[dominant] / grand, 4)
                              if dominant else 0.0),
           "slowest": [{"trace_id": r["trace_id"],
                        "duration_ms": r["duration_ms"],
                        "dominant": r["dominant"]} for r in slowest]}
    if metrics is not None:
        metrics.trace_assembly_timer.record_ms(
            (time.perf_counter() - t0) * 1000.0)
    return out


def dominant_leg(dumps: Iterable[dict], metrics=None) -> Optional[dict]:
    """One-call convenience for the SLO wiring: assemble + attribute and
    return ``{"dominant", "dominant_share", "traces"}`` (None when the dumps
    hold no attributable trace)."""
    table = attribution_table(assemble_traces(list(dumps)), metrics=metrics)
    if not table["traces"] or table["dominant"] is None:
        return None
    return {"dominant": table["dominant"],
            "dominant_share": table["dominant_share"],
            "traces": table["traces"]}
