"""The consistency observatory — online audits that page when state rots.

Every consistency proof in this repo lives in tests; a production fleet has
rich *performance* observability but no runtime evidence that the resident
slab still byte-matches the log, that leader and follower logs agree inside
the high-watermark, or that the dedup window would still absorb a replay.
:class:`ConsistencyAuditor` is that missing correctness half: a supervised
Controllable (the autobalancer's lifecycle shape) whose every cycle runs
three independent probes —

1. **Shadow replay.** A rotating cohort of resident aggregates is pulled
   from the live slab in ONE gather (``ResidentStatePlane.audit_pull`` — the
   (row, ordinal) pairs are atomic w.r.t. fold commits), then re-folded from
   the log from scratch through the SAME device fold that built them
   (``shadow_replay_rows``), and byte-compared field by field. Fencing (the
   views-fold discipline) keeps churn from false-positivizing: findings are
   discarded at verdict time when the aggregate left the slab, its
   partition's anchor generation moved (rebalance / re-grant), or the
   watermark went backwards (failover truncation) while the refold flew;
   an aggregate whose log prefix no longer covers its ordinal (compaction)
   is *unverifiable*, never divergent.
2. **Cross-replica digest compare.** For each audited (topic, partition)
   the auditor asks every registered peer broker for its chained digest
   (``PartitionDigest`` RPC → :mod:`surge_tpu.log.digest`) at one common
   offset — the minimum high-watermark across peers — and flags any
   disagreement. Unequal chain bases (compaction skew between replicas) are
   incomparable and skipped; the replication compaction barrier reconverges
   them. No records ship: two CRCs cross the wire per partition.
3. **Dedup probe.** The auditor commits one tiny record to its own probe
   topic through a real transactional producer, then re-ships the SAME
   txn_seq via ``replay_commit`` — a healthy broker answers from its dedup
   window with the original offsets (REPLAY); fresh offsets mean the
   exactly-once gate has a hole. Transports without a wire seq gate
   (in-memory) are *unsupported* and skipped, never counted as holes.

Findings land everywhere an operator looks: ``surge.audit.*`` instruments,
an ``audit.divergence`` flight event (merge-ready — the incident timeline
names the divergent aggregate/partition next to the fault that caused it),
the ``state-divergence`` DEFAULT_SLOS objective (driven by the
``surge.audit.unresolved-divergences`` gauge — a finding burns the budget
until the same check re-verifies clean), a degraded-not-down health
component, ``chaos.py audit`` and the ``surgetop`` audit column.
"""

from __future__ import annotations

import asyncio
import time
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from surge_tpu.common import Ack, BackgroundTask, Controllable, logger
from surge_tpu.config import Config, default_config
from surge_tpu.health import HealthCheck
from surge_tpu.log.transport import LogRecord, page_keyed_records

__all__ = ["ConsistencyAuditor", "PROBE_TOPIC"]

#: the dedup probe's private topic — one tiny record per probing cycle,
#: committed through the real gate (never an aggregate topic: the probe
#: must not perturb state it audits)
PROBE_TOPIC = "__audit_probe"


class ConsistencyAuditor(Controllable):
    """Supervised consistency-audit loop (module doc). Construct with the
    engine's resident plane + log; digest peers join via
    :meth:`add_digest_peer`; ``cycle()`` is directly awaitable for
    deterministic tests."""

    def __init__(self, plane=None, log=None, config: Config | None = None,
                 metrics=None, flight=None, on_signal=None) -> None:
        self.plane = plane
        self.log = log if log is not None else getattr(plane, "log", None)
        cfg = config or default_config()
        self._interval = max(cfg.get_seconds("surge.audit.interval-ms",
                                             2_000), 0.01)
        self._cohort = max(cfg.get_int("surge.audit.cohort-size", 8), 1)
        self._digest_enabled = cfg.get_bool("surge.audit.digest-enabled",
                                            True)
        self._dedup_probe = cfg.get_bool("surge.audit.dedup-probe", True)
        self.metrics = metrics  # EngineMetrics (surge.audit.*) or None
        self.flight = flight  # FlightRecorder: findings join the timeline
        self.on_signal = on_signal or (lambda name, level: None)
        #: [(name, client)] — clients exposing partition_digest(+ either
        #: high_watermark or end_offset); ≥2 make the compare meaningful
        self._digest_peers: List[Tuple[str, object]] = []
        #: [(topic, partition)] compared each cycle (engine wiring defaults
        #: this to the events topic's partitions)
        self._digest_targets: List[Tuple[str, int]] = []
        #: open findings keyed ("state", agg) / ("digest", topic, part) /
        #: ("dedup", "probe") — an entry resolves when its check re-verifies
        #: clean; len() drives the state-divergence SLO gauge
        self.unresolved: Dict[tuple, dict] = {}
        self.stats = {"cycles": 0, "cohort_rows": 0, "divergent_rows": 0,
                      "unverifiable_rows": 0, "digest_compares": 0,
                      "digest_mismatches": 0, "dedup_probes": 0,
                      "dedup_holes": 0, "skipped_cycles": 0}
        self.last_round: dict = {}
        self._cursor = 0  # cohort rotation position
        self._probe_producer = None
        self._probe_n = 0
        self._task: Optional[BackgroundTask] = None
        self._running = False

    # -- lifecycle (Controllable) -------------------------------------------------------

    @property
    def running(self) -> bool:
        return self._running

    async def start(self) -> Ack:
        if self._running:
            return Ack()
        self._task = BackgroundTask(self._audit_loop, "consistency-audit")
        self._task.start()
        self._running = True
        return Ack()

    async def stop(self) -> Ack:
        self._running = False
        if self._task is not None:
            await self._task.stop()
            self._task = None
        if self._probe_producer is not None:
            self._probe_producer = None
        return Ack()

    async def shutdown(self) -> Ack:
        return await self.stop()

    async def _audit_loop(self) -> None:
        while True:
            try:
                await self.cycle()
            except asyncio.CancelledError:
                raise
            except Exception:  # noqa: BLE001 — the auditor must outlive a bad cycle
                logger.exception("consistency-audit cycle failed")
                try:
                    self.on_signal("consistency-auditor.cycle-error", "error")
                except Exception:  # noqa: BLE001
                    logger.exception("on_signal failed")
            await asyncio.sleep(self._interval)

    # -- peers / targets ----------------------------------------------------------------

    def add_digest_peer(self, name: str, client) -> None:
        """Register one broker's client for the digest compare. The client
        needs ``partition_digest(topic, partition, upto)`` plus
        ``high_watermark`` (or ``end_offset``) — both
        :class:`~surge_tpu.log.client.GrpcLogTransport` and the in-process
        log backends qualify."""
        self._digest_peers.append((name, client))

    def set_digest_targets(self, targets: Sequence[Tuple[str, int]]) -> None:
        self._digest_targets = [(t, int(p)) for t, p in targets]

    # -- one audit cycle ----------------------------------------------------------------

    async def cycle(self) -> dict:
        """One full audit round: shadow replay + digest compare + dedup
        probe. Returns the round verdict (also kept as ``last_round``)."""
        t0 = time.perf_counter()
        out: dict = {"cohort": 0, "divergent": [], "unverifiable": 0,
                     "digest_compared": 0, "digest_mismatches": [],
                     "dedup": "skipped", "skipped": None}
        loop = asyncio.get_running_loop()
        await self._shadow_audit(out, loop)
        if self._digest_enabled and len(self._digest_peers) >= 2 \
                and self._digest_targets:
            mismatches, compared = await loop.run_in_executor(
                None, self._digest_audit_sync)
            out["digest_compared"] = compared
            self.stats["digest_compares"] += compared
            for m in mismatches:
                key = ("digest", m["topic"], m["partition"])
                out["digest_mismatches"].append(m)
                self.stats["digest_mismatches"] += 1
                if self.metrics is not None:
                    self.metrics.audit_digest_mismatches.record()
                self._find(key, kind="digest", **m)
            found = {(m["topic"], m["partition"]) for m in mismatches}
            for t, p in self._digest_targets:
                if (t, p) not in found:
                    self._resolve(("digest", t, p))
        if self._dedup_probe and self.log is not None:
            verdict = await loop.run_in_executor(None, self._probe_sync)
            out["dedup"] = verdict
            if verdict in ("replayed", "hole"):
                self.stats["dedup_probes"] += 1
            if verdict == "hole":
                self.stats["dedup_holes"] += 1
                if self.metrics is not None:
                    self.metrics.audit_dedup_holes.record()
                self._find(("dedup", "probe"), kind="dedup",
                           detail="replayed acked seq was ACCEPTED "
                                  "(dedup-window hole)")
            elif verdict == "replayed":
                self._resolve(("dedup", "probe"))
        self.stats["cycles"] += 1
        elapsed_ms = (time.perf_counter() - t0) * 1000.0
        if self.metrics is not None:
            self.metrics.audit_rounds.record()
            self.metrics.audit_round_timer.record_ms(elapsed_ms)
            self.metrics.audit_unresolved.record(len(self.unresolved))
        out["unresolved"] = len(self.unresolved)
        out["elapsed_ms"] = round(elapsed_ms, 3)
        self.last_round = out
        return out

    # -- probe 1: shadow replay ---------------------------------------------------------

    async def _shadow_audit(self, out: dict, loop) -> None:
        plane = self.plane
        if plane is None or not getattr(plane, "_seeded", False):
            return
        ids = sorted(plane._dir)
        if not ids:
            return
        n = min(self._cohort, len(ids))
        start = self._cursor % len(ids)
        cohort = [ids[(start + i) % len(ids)] for i in range(n)]
        self._cursor = (start + n) % len(ids)
        # ONE on-loop, await-free block: generations + watermarks + the live
        # (row, ordinal) pairs all describe the same fold state — the pull is
        # a single device gather against the pinned slab
        gens = dict(plane._anchor_gen)
        wms = dict(plane._watermarks)
        part_of = {a: plane._agg_part.get(a) for a in cohort}
        try:
            pulled = plane.audit_pull(cohort)
        except Exception as exc:  # noqa: BLE001
            if "delet" in str(exc).lower():
                # a donated refresh dispatch consumed the gathered buffers
                # mid-pull: a liveness race, not a finding — skip the cycle
                out["skipped"] = "slab-donation-race"
                self.stats["skipped_cycles"] += 1
                return
            raise
        out["cohort"] = len(pulled)
        self.stats["cohort_rows"] += len(pulled)
        if self.metrics is not None:
            self.metrics.audit_cohort_size.record(len(pulled))
        if not pulled:
            return
        try:
            verdicts, unverifiable = await loop.run_in_executor(
                None, self._shadow_verify, pulled, part_of, wms)
        except Exception:  # noqa: BLE001 — a failover mid-scan is liveness
            logger.exception("shadow verify failed (transient log read?) — "
                             "cycle skipped")
            out["skipped"] = "verify-error"
            self.stats["skipped_cycles"] += 1
            return
        out["unverifiable"] = unverifiable
        self.stats["unverifiable_rows"] += unverifiable
        # verdict-time fence (on-loop again): discard anything whose ground
        # truth moved while the refold flew — evict/re-admit, rebalance
        # re-anchor, failover truncation are all liveness, not corruption
        for agg, diff in verdicts:
            p = part_of.get(agg)
            if (p is None
                    or plane._anchor_gen.get(p, 0) != gens.get(p, 0)
                    or plane._watermarks.get(p, 0) < wms.get(p, 0)
                    or agg not in plane._dir):
                continue
            if diff:
                finding = {"aggregate": agg, "partition": p, "fields": diff}
                out["divergent"].append(finding)
                self.stats["divergent_rows"] += 1
                if self.metrics is not None:
                    self.metrics.audit_divergent_rows.record()
                self._find(("state", agg), kind="state", **finding)
            else:
                self._resolve(("state", agg))

    def _shadow_verify(self, pulled: dict, part_of: dict, wms: dict):
        """Executor half: collect each audited aggregate's first-``ordinal``
        events with ONE paged scan per partition, refold them through the
        plane's device fold, byte-compare. Returns
        ``([(agg, diff_fields)], n_unverifiable)``."""
        plane = self.plane
        want = {a: ordn for a, (_row, ordn) in pulled.items() if ordn > 0}
        events: Dict[str, list] = {a: [] for a in want}
        by_part: Dict[int, set] = {}
        for a in want:
            p = part_of.get(a)
            if p is not None:
                by_part.setdefault(p, set()).add(a)
        for p, aggs in by_part.items():
            remaining = set(aggs)
            for rec in page_keyed_records(plane.log, plane.events_topic, p,
                                          upto=wms.get(p, 0)):
                a = rec.key
                if a not in remaining:
                    continue
                try:
                    events[a].append(plane._encode_event(rec.value))
                except Exception:  # noqa: BLE001 — poison race: unverifiable
                    events.pop(a, None)
                    remaining.discard(a)
                    continue
                if len(events[a]) >= want[a]:
                    remaining.discard(a)
                    if not remaining:
                        break
        verify = [a for a in pulled
                  if a in events and len(events[a]) >= want.get(a, 1 << 62)]
        results: List[Tuple[str, list]] = []
        if verify:
            shadow = plane.shadow_replay_rows(
                [events[a][: want[a]] for a in verify])
            for j, a in enumerate(verify):
                row = pulled[a][0]
                diff = [k for k in sorted(shadow)
                        if np.asarray(row[k]).tobytes()
                        != np.asarray(shadow[k][j]).tobytes()]
                results.append((a, diff))
        return results, len(pulled) - len(verify)

    # -- probe 2: cross-replica digest compare ------------------------------------------

    @staticmethod
    def _peer_hwm(client, topic: str, partition: int) -> int:
        hw = getattr(client, "high_watermark", None)
        if hw is not None:
            return int(hw(topic, partition))
        return int(client.end_offset(topic, partition))

    def _digest_audit_sync(self):
        """Blocking half of the digest compare (peer RPCs) — run in the
        executor. Returns ``(mismatches, n_compared)``."""
        mismatches: List[dict] = []
        compared = 0
        for topic, part in self._digest_targets:
            try:
                upto = min(self._peer_hwm(c, topic, part)
                           for _n, c in self._digest_peers)
                if upto <= 0:
                    continue
                digests = [(n, c.partition_digest(topic, part, upto))
                           for n, c in self._digest_peers]
            except Exception:  # noqa: BLE001 — an unreachable peer is not divergence
                logger.exception("digest compare of %s[%d] failed "
                                 "(peer unreachable?)", topic, part)
                continue
            if len({d["base"] for _n, d in digests}) != 1:
                continue  # compaction skew between replicas: incomparable
            if any(d["digest"] is None for _n, d in digests):
                continue
            compared += 1
            if len({d["digest"] for _n, d in digests}) > 1:
                mismatches.append({
                    "topic": topic, "partition": part, "upto": upto,
                    "digests": {n: d["digest"] for n, d in digests}})
        return mismatches, compared

    # -- probe 3: dedup probe -----------------------------------------------------------

    def _probe_sync(self) -> str:
        """Blocking half of the exactly-once probe — run in the executor.
        Commits one record through the real gate, re-ships the SAME seq, and
        expects the dedup window's cached reply (original offsets)."""
        prod = self._probe_producer
        if prod is None:
            try:
                self.log.topic(PROBE_TOPIC)  # auto-create
                prod = self.log.transactional_producer("__audit-probe__")
            except Exception:  # noqa: BLE001 — no producer plane here
                return "unavailable"
            self._probe_producer = prod
        if not hasattr(prod, "replay_commit"):
            return "unsupported"  # no wire seq gate to probe (in-memory)
        self._probe_n += 1
        rec = LogRecord(topic=PROBE_TOPIC, key="probe",
                        value=b"%d" % self._probe_n)
        try:
            prod.begin()
            prod.send(rec)
            acked = prod.commit()
            replay = prod.replay_commit([rec])
        except Exception:  # noqa: BLE001 — a failover mid-probe is not a hole
            self._probe_producer = None
            return "unavailable"
        orig = [(r.topic, r.partition, r.offset) for r in acked]
        seen = [(r.topic, r.partition, r.offset) for r in replay]
        return "replayed" if orig == seen else "hole"

    # -- findings ledger ----------------------------------------------------------------

    def _find(self, key: tuple, **info) -> None:
        fresh = key not in self.unresolved
        self.unresolved[key] = {**info, "cycle": self.stats["cycles"]}
        if fresh:
            try:
                self.on_signal(f"audit.divergence.{info.get('kind')}",
                               "warning")
            except Exception:  # noqa: BLE001
                logger.exception("on_signal failed")
            if self.flight is not None:
                self.flight.record("audit.divergence", **info)
            logger.warning("consistency divergence: %s", info)

    def _resolve(self, key: tuple) -> None:
        if self.unresolved.pop(key, None) is not None and \
                self.flight is not None:
            self.flight.record("audit.resolved", key=list(map(str, key)))

    # -- operator surface ---------------------------------------------------------------

    def summary(self) -> dict:
        """The ``chaos.py audit`` / AuditStatus verdict: ``ok`` is False
        while any divergence is unresolved."""
        return {"ok": not self.unresolved,
                "running": self._running,
                "stats": dict(self.stats),
                "unresolved": [
                    {"key": list(map(str, k)), **v}
                    for k, v in sorted(self.unresolved.items(),
                                       key=lambda kv: str(kv[0]))],
                "last_round": self.last_round,
                "digest_peers": [n for n, _c in self._digest_peers],
                "digest_targets": [[t, p] for t, p in self._digest_targets]}

    def health_component(self) -> HealthCheck:
        """Degraded while a divergence is unresolved, never down — a
        corruption page means "go look at the flight timeline", not
        "restart the engine over it"."""
        return HealthCheck(name="consistency-audit",
                           status="degraded" if self.unresolved else "up")
