"""Federated scrape: every engine/broker payload merged into ONE exposition.

A quorum cluster (PR 7) plus a fleet of engines leaves an operator scraping N
brokers and M engines by hand — `tools/chaos.py metrics` per broker, an HTTP
port per engine — and eyeballing raw families with no way to tell whose
`surge_log_replication_epoch` is whose. :class:`FederatedScraper` is the
Prometheus-federation answer, self-hosted (no Prometheus dependency, same
zero-footprint philosophy as the stdlib scrape server):

- every registered :class:`ScrapeTarget` is pulled CONCURRENTLY per pass,
  each with its own timeout — one hung broker cannot stall the fleet view;
- payloads merge into one grammar-valid OpenMetrics exposition where every
  sample gains ``instance``/``role`` labels (the Prometheus federation
  labelling convention), duplicate family names across engine and broker
  registries collapse into one ``TYPE`` block, and a cross-registry TYPE
  conflict re-homes the later family under ``<name>_<type>`` instead of
  emitting a grammar-violating duplicate declaration;
- a down target keeps serving its LAST payload with a staleness stamp
  (``surge_fleet_scrape_staleness_seconds{instance=...}``) and an
  ``up{instance=...} 0`` gauge — the fleet view degrades, it never lies by
  omission;
- the scraper's own :class:`~surge_tpu.metrics.fleet.FleetMetrics` quiver
  (``surge.fleet.*`` / ``surge.slo.*``) joins the same payload, and an
  attached :class:`~surge_tpu.observability.slo.SLOEngine` is evaluated
  after every pass;
- :meth:`serve` exposes the merged payload from the scraper's own scrape
  port (one federation pass per GET), and ``tools/chaos.py fleet`` /
  ``tools/surgetop.py`` drive the same object from the CLI.

Target addressing: ``role@address`` strings — ``broker@host:port`` scrapes
over the log-service ``GetMetricsText`` RPC, ``engine@host:port`` over the
admin-service ``GetMetricsText`` RPC, and ``role@http://host:port/metrics``
over plain HTTP (any exposition endpoint, including another federated
scraper).
"""

from __future__ import annotations

import re
import threading
import time
import urllib.request
from concurrent.futures import ThreadPoolExecutor
from concurrent.futures import wait as _futures_wait
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from surge_tpu.common import logger
from surge_tpu.config import Config, default_config
from surge_tpu.metrics.exposition import (
    Family,
    MetricsHTTPServer,
    Sample,
    _render_family,
    registry_families,
    sanitize_name,
)
from surge_tpu.metrics.fleet import FleetMetrics, fleet_metrics
from surge_tpu.metrics.statistics import Count as _Count
from surge_tpu.metrics.statistics import TimeBucketHistogram as _TBHist


def _registry_shapes(registry):
    """(family name, type) for every registered metric — the exposition's
    naming rules without touching provider values."""
    for dotted, reg in registry._metrics.items():
        if isinstance(reg.provider, _TBHist):
            base = dotted[:-len(".p99")] if dotted.endswith(".p99") else dotted
            yield sanitize_name(base) + "_ms", "histogram"
        elif isinstance(reg.provider, _Count):
            yield sanitize_name(dotted), "counter"
        else:
            yield sanitize_name(dotted), "gauge"

__all__ = ["FederatedScraper", "ScrapeTarget", "parse_openmetrics",
           "target_from_spec"]

#: labels the federation layer owns; same-named labels in a target payload
#: are renamed ``exported_<label>`` (the Prometheus honor_labels=false rule)
RESERVED_LABELS = ("instance", "role")

_HELP_RE = re.compile(r"^# HELP (\S+) ?(.*)$")
_TYPE_RE = re.compile(r"^# TYPE (\S+) (\S+)$")
_SAMPLE_RE = re.compile(
    r"^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)"
    r"(?:\{(?P<labels>[^}]*)\})?"
    r" (?P<value>[^ ]+)"
    r"(?: # \{trace_id=\"(?P<trace>[0-9a-f]+)\"\}"
    r" (?P<exval>[^ ]+) (?P<exts>[0-9.]+))?$")
_LABEL_RE = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')
_SUFFIXES = ("_total", "_bucket", "_sum", "_count", "")


def _unescape(value: str) -> str:
    out: List[str] = []
    i = 0
    while i < len(value):
        c = value[i]
        if c == "\\" and i + 1 < len(value):
            out.append({"n": "\n", '"': '"', "\\": "\\"}.get(
                value[i + 1], value[i + 1]))
            i += 2
        else:
            out.append(c)
            i += 1
    return "".join(out)


def parse_openmetrics(text: str) -> List[Family]:
    """Parse an exposition back into :class:`Family` objects (the inverse of
    ``render_openmetrics``, for re-labelling and re-emission). Lenient where
    a federating scraper must be: untyped samples become implicit gauge
    families (a target payload must not take the whole merge down), unknown
    comment lines are skipped, parsing stops at ``# EOF``."""
    helps: Dict[str, str] = {}
    families: Dict[str, Family] = {}
    order: List[str] = []

    def family_of(sample_name: str) -> Tuple[Family, str]:
        for suffix in _SUFFIXES:
            if suffix and not sample_name.endswith(suffix):
                continue
            cand = sample_name[: len(sample_name) - len(suffix)] \
                if suffix else sample_name
            if cand in families:
                return families[cand], suffix
        fam = Family(name=sample_name, mtype="gauge",
                     help=helps.get(sample_name, ""))
        families[sample_name] = fam
        order.append(sample_name)
        return fam, ""

    for line in text.splitlines():
        if line == "# EOF":
            break
        if not line:
            continue
        if line.startswith("# HELP "):
            m = _HELP_RE.match(line)
            if m:
                helps[m.group(1)] = m.group(2)
            continue
        if line.startswith("# TYPE "):
            m = _TYPE_RE.match(line)
            if m and m.group(1) not in families:
                families[m.group(1)] = Family(
                    name=m.group(1), mtype=m.group(2),
                    help=helps.get(m.group(1), ""))
                order.append(m.group(1))
            continue
        if line.startswith("#"):
            continue
        m = _SAMPLE_RE.match(line)
        if not m:
            continue
        fam, suffix = family_of(m.group("name"))
        labels = tuple((k, _unescape(v))
                       for k, v in _LABEL_RE.findall(m.group("labels") or ""))
        exemplar = None
        if m.group("trace"):
            exemplar = (m.group("trace"), float(m.group("exval")),
                        float(m.group("exts")))
        fam.samples.append(Sample(suffix, labels, float(m.group("value")),
                                  exemplar=exemplar))
    return [families[name] for name in order]


@dataclass
class ScrapeTarget:
    """One fleet member's scrape surface. ``fetch`` (tests, in-process
    registries) overrides the address-derived fetcher entirely."""

    instance: str
    role: str = "broker"
    address: str = ""
    fetch: Optional[Callable[[], str]] = None


def target_from_spec(spec: str) -> ScrapeTarget:
    """``role@address`` → target (bare ``host:port`` defaults to broker)."""
    role, sep, addr = spec.partition("@")
    if not sep:
        role, addr = "broker", spec
    instance = re.sub(r"^https?://", "", addr).split("/")[0]
    return ScrapeTarget(instance=instance, role=role.strip(),
                        address=addr.strip())


class FederatedScraper:
    """Pulls every registered target concurrently and serves one merged,
    instance-labelled OpenMetrics exposition (module docstring)."""

    def __init__(self, targets: Sequence[ScrapeTarget | str] = (),
                 config: Config | None = None,
                 metrics: Optional[FleetMetrics] = None,
                 clock: Callable[[], float] = time.time,
                 slo=None) -> None:
        self.config = config or default_config()
        self.targets: List[ScrapeTarget] = [
            target_from_spec(t) if isinstance(t, str) else t for t in targets]
        self._timeout = self.config.get_seconds(
            "surge.fleet.scrape-timeout-ms", 2_000)
        self.metrics = metrics if metrics is not None else fleet_metrics()
        self._clock = clock
        #: instance -> {"families", "ts", "up", "error"} — a down target's
        #: last-good families keep serving with a staleness stamp
        self._cache: Dict[str, dict] = {}
        self._lock = threading.Lock()
        self._pool: Optional[ThreadPoolExecutor] = None
        self._grpc_fetchers: Dict[str, Callable[[], str]] = {}
        self._grpc_channels: List = []  # closed by stop()
        #: optional surge_tpu.observability.slo.SLOEngine evaluated per pass
        self.slo = slo
        self._server: Optional[MetricsHTTPServer] = None
        self._stopped = False
        #: single-use stash of the merge scrape_once built for the SLO pass,
        #: so an immediately-following render/row-extract reuses it instead
        #: of re-merging every cached payload (stale-by-milliseconds only)
        self._merged_stash: Optional[List[Family]] = None

    # -- fetch --------------------------------------------------------------------------

    def _fetcher(self, target: ScrapeTarget) -> Callable[[], str]:
        if target.fetch is not None:
            return target.fetch
        if target.address.startswith(("http://", "https://")):
            url = target.address
            if "://" in url and "/" not in url.split("://", 1)[1]:
                url += "/metrics"

            def fetch_http() -> str:
                with urllib.request.urlopen(url, timeout=self._timeout) as r:
                    return r.read().decode()

            return fetch_http
        key = f"{target.role}@{target.address}"
        # cache under the lock: serve() runs scrape_once on concurrent HTTP
        # handler threads — two first GETs must not open two channels for
        # one target (the loser would be unreferenced AND unclosable)
        with self._lock:
            hit = self._grpc_fetchers.get(key)
            if hit is None:
                hit = (self._admin_fetcher(target.address)
                       if target.role == "engine"
                       else self._broker_fetcher(target.address))
                self._grpc_fetchers[key] = hit
        return hit

    def _channel(self, address: str):
        """One cached sync channel per address, closed by :meth:`stop`."""
        from surge_tpu.remote.security import secure_sync_channel

        channel = secure_sync_channel(address, self.config)
        self._grpc_channels.append(channel)
        return channel

    def _broker_fetcher(self, address: str) -> Callable[[], str]:
        """Scrape-over-gRPC against the log service (no scrape port needed)."""
        from surge_tpu.log import log_service_pb2 as pb
        from surge_tpu.log.server import SERVICE

        channel = self._channel(address)
        call = channel.unary_unary(
            f"/{SERVICE}/GetMetricsText",
            request_serializer=pb.ListTopicsRequest.SerializeToString,
            response_deserializer=pb.TxnReply.FromString)

        def fetch() -> str:
            reply = call(pb.ListTopicsRequest(), timeout=self._timeout)
            if not reply.ok:
                raise RuntimeError(f"GetMetricsText failed: {reply.error}")
            return reply.records[0].value.decode()

        return fetch

    def _admin_fetcher(self, address: str) -> Callable[[], str]:
        """Scrape-over-gRPC against an engine's admin service."""
        from surge_tpu.admin import admin_pb2 as pb
        from surge_tpu.admin.server import SERVICE

        channel = self._channel(address)
        call = channel.unary_unary(
            f"/{SERVICE}/GetMetricsText",
            request_serializer=pb.Empty.SerializeToString,
            response_deserializer=pb.MetricsReply.FromString)

        def fetch() -> str:
            return call(pb.Empty(), timeout=self._timeout).metrics_json.decode()

        return fetch

    # -- the federation pass ------------------------------------------------------------

    def scrape_once(self) -> dict:
        """One pass: every target concurrently, per-target timeout; updates
        the per-target cache, the fleet quiver, and the attached SLO engine.
        Returns ``{"targets", "up", "errors": {instance: error}}``."""
        t0 = self._clock()
        # pool management under the lock: serve() runs this concurrently on
        # HTTP handler threads, and stop() may tear the pool down mid-GET
        with self._lock:
            if self._pool is None and self.targets and not self._stopped:
                self._pool = ThreadPoolExecutor(
                    max_workers=min(len(self.targets), 16),
                    thread_name_prefix="surge-fleet-scrape")
            pool = self._pool
        if pool is None:
            return {"targets": len(self.targets), "up": 0,
                    "errors": {"": "scraper stopped"} if self._stopped else {}}
        futures = {t.instance: pool.submit(self._fetcher(t))
                   for t in self.targets}
        # per-target network timeouts bound each fetch; the pass deadline is
        # a belt on top so a misbehaving fetcher cannot wedge the fleet view
        _futures_wait(list(futures.values()), timeout=self._timeout * 2 + 1.0)
        errors: Dict[str, str] = {}
        up = 0
        for target in self.targets:
            fut = futures[target.instance]
            try:
                if not fut.done():
                    raise TimeoutError(
                        f"scrape exceeded {self._timeout:.1f}s")
                families = parse_openmetrics(fut.result())
            except Exception as exc:  # noqa: BLE001 — one target must not kill the pass
                errors[target.instance] = repr(exc)
                self.metrics.fleet_scrape_errors.record()
                with self._lock:
                    entry = self._cache.setdefault(
                        target.instance, {"families": [], "ts": None})
                    entry["up"] = False
                    entry["error"] = repr(exc)
                continue
            up += 1
            with self._lock:
                self._cache[target.instance] = {
                    "families": families, "ts": self._clock(),
                    "up": True, "error": None}
        self.metrics.fleet_targets.record(len(self.targets))
        self.metrics.fleet_up_targets.record(up)
        self.metrics.fleet_scrape_timer.record_ms(
            (self._clock() - t0) * 1000.0)
        if self.slo is not None:
            try:
                merged = self.merged_families()
                self.slo.evaluate(merged, now=self._clock())
                self._merged_stash = merged
            except Exception:  # noqa: BLE001 — SLO math must not break the scrape
                logger.exception("SLO evaluation failed")
        return {"targets": len(self.targets), "up": up, "errors": errors}

    # -- merge --------------------------------------------------------------------------

    def _relabel(self, fam: Family, target: ScrapeTarget) -> Family:
        base = (("instance", target.instance), ("role", target.role))
        out = Family(name=fam.name, mtype=fam.mtype, help=fam.help)
        for s in fam.samples:
            kept = tuple((k if k not in RESERVED_LABELS else f"exported_{k}",
                          v) for k, v in s.labels)
            out.samples.append(Sample(s.suffix, base + kept, s.value,
                                      exemplar=s.exemplar))
        return out

    def merged_families(self) -> List[Family]:
        """The merged exposition as families, sorted by name: fleet
        self-instruments + every cached target payload (instance/role
        labelled) + ``up`` and per-instance staleness gauges."""
        merged: Dict[str, Family] = {}

        def absorb(fam: Family) -> None:
            hit = merged.get(fam.name)
            if hit is None:
                merged[fam.name] = fam
                return
            if hit.mtype != fam.mtype:
                # a cross-registry TYPE conflict: re-home under a
                # type-suffixed name instead of emitting a duplicate TYPE
                renamed = Family(name=f"{fam.name}_{fam.mtype}",
                                 mtype=fam.mtype, help=fam.help,
                                 samples=fam.samples)
                absorb(renamed)
                return
            hit.samples.extend(fam.samples)

        up = Family(name="up", mtype="gauge",
                    help="1 if the instance answered the last federation "
                         "pass (0 = serving its last payload, stale)")
        stale = Family(name="surge_fleet_scrape_staleness_seconds",
                       mtype="gauge",
                       help="age of the payload served for this instance "
                            "(grows while the target is down)")
        now = self._clock()
        max_staleness = 0.0
        with self._lock:
            cache = {k: dict(v) for k, v in self._cache.items()}
        for target in self.targets:
            entry = cache.get(target.instance)
            labels = (("instance", target.instance), ("role", target.role))
            up.samples.append(Sample(
                "", labels, 1.0 if entry and entry.get("up") else 0.0))
            if entry is None or entry.get("ts") is None:
                continue  # never scraped: nothing cached to serve or stamp
            age = max(0.0, now - entry["ts"])
            max_staleness = max(max_staleness, age)
            stale.samples.append(Sample("", labels, age))
            for fam in entry["families"]:
                absorb(self._relabel(fam, target))
        self.metrics.fleet_max_staleness.record(max_staleness)
        absorb(up)
        absorb(stale)
        # self-instruments join the same payload. The merged-families gauge
        # must be recorded BEFORE the registry VALUE snapshot (so this
        # pass's own number renders) yet count exactly what absorb() will
        # produce — names/types are static, so simulate the absorption
        # without values (federating another federated scraper collides on
        # these very names and must not overcount)
        names = {name: fam.mtype for name, fam in merged.items()}

        def would_add(name: str, mtype: str) -> int:
            hit = names.get(name)
            if hit is None:
                names[name] = mtype
                return 1
            if hit == mtype:
                return 0
            return would_add(f"{name}_{mtype}", mtype)

        added = sum(would_add(n, m)
                    for n, m in _registry_shapes(self.metrics.registry))
        self.metrics.fleet_merged_families.record(len(merged) + added)
        for fam in registry_families(self.metrics.registry):
            absorb(fam)
        return [merged[name] for name in sorted(merged)]

    def instance_values(self, family: str, suffix: str = "",
                        merged: Optional[List[Family]] = None
                        ) -> Dict[str, float]:
        """{instance: value} for one merged family's bare samples — the
        per-member extraction the autobalancer and surgetop score from.
        Pass ``merged`` (one ``last_merged()`` call) when extracting several
        families from the same pass — the stash is single-use, so repeated
        bare calls would re-merge every payload."""
        out: Dict[str, float] = {}
        for fam in (merged if merged is not None else self.last_merged()):
            if fam.name != family:
                continue
            for s in fam.samples:
                if s.suffix != suffix:
                    continue
                inst = dict(s.labels).get("instance")
                if inst is not None:
                    out[inst] = s.value
        return out

    def last_merged(self) -> List[Family]:
        """The families the most recent ``scrape_once`` built for its SLO
        pass (single-use stash — a back-to-back render/row-extract reuses
        that pass's own merge instead of re-merging every payload), or a
        fresh merge when nothing is stashed."""
        stash, self._merged_stash = self._merged_stash, None
        return stash if stash is not None else self.merged_families()

    def render(self) -> str:
        """The merged exposition from CACHE (``# EOF``-terminated; no pass)."""
        lines: List[str] = []
        for fam in self.last_merged():
            _render_family(lines, fam)
        lines.append("# EOF")
        return "\n".join(lines) + "\n"

    def scrape_and_render(self) -> str:
        """One federation pass, then the merged payload (what the scrape
        port serves per GET)."""
        self.scrape_once()
        return self.render()

    # -- serving ------------------------------------------------------------------------

    def serve(self, host: str = "127.0.0.1", port: int = 0) -> int:
        """Serve the merged exposition from the scraper's own scrape port
        (a fresh federation pass per GET); returns the bound port."""
        if self._server is not None:
            return self._server.bound_port
        self._server = MetricsHTTPServer(None, host=host, port=port,
                                         render=self.scrape_and_render)
        return self._server.start()

    def stop(self) -> None:
        if self._server is not None:
            server, self._server = self._server, None
            server.stop()
        with self._lock:
            self._stopped = True
            pool, self._pool = self._pool, None
            channels, self._grpc_channels = self._grpc_channels, []
            self._grpc_fetchers.clear()
        if pool is not None:
            pool.shutdown(wait=False)
        for channel in channels:
            try:
                channel.close()
            except Exception:  # noqa: BLE001 — teardown best-effort
                pass
