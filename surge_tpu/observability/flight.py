"""Broker flight recorder: a bounded ring of structured events + the merge
that reconstructs a failover timeline from several brokers' dumps.

The black-box tradition of production event-sourcing systems: metrics tell an
operator *that* a failover happened (``surge.log.failover.*`` counters); the
flight recorder tells them *what happened in what order* — role transitions,
epoch bumps, truncations, promotion decisions, compaction barriers, fault
firings, journal rotations — without grepping broker logs. Recording is
allocation-cheap (one tuple into a ``deque(maxlen=...)`` under a short lock)
so the sites stay armed in production; dumps are pulled over the broker's
``DumpFlight`` RPC, auto-written on fault-plane crash trips, and merged by
:func:`merge_dumps` into a single ordered timeline
(``tools/flight_timeline.py`` is the CLI; ``SURGE_BENCH_FAILOVER=1`` emits
the reconstruction alongside its 0-lost/0-dup verdict).

**Timestamps.** Every event carries ``mono`` (``time.monotonic()`` — ordering
truth within one host: CLOCK_MONOTONIC is shared by all processes on a Linux
host and never steps) and ``wall`` (``time.time()`` — human anchor, and the
only cross-host merge key). :func:`merge_dumps` orders by ``mono`` when every
dump names the same clock domain (host), by ``wall`` otherwise.
"""

from __future__ import annotations

import json
import os
import socket
import threading
import time
from collections import deque
from typing import Dict, List, Optional, Sequence

__all__ = ["FlightRecorder", "merge_dumps", "reconstruct_failover",
           "same_clock_domain", "host_wall_offset"]


def same_clock_domain(dumps: Sequence[dict]) -> bool:
    """Whether every dump came from one host — monotonic timestamps are then
    comparable across them (CLOCK_MONOTONIC is host-shared on Linux); across
    hosts only wall time is, and consumers must key offsets accordingly."""
    return len({d.get("node") for d in dumps if d.get("events")}) <= 1


class FlightRecorder:
    """Bounded ring buffer of ``(seq, mono, wall, type, attrs)`` events.

    One per broker (and, since the fleet telemetry plane, one per ENGINE —
    publisher lane transitions, rebalance fan-out, resident-plane moves and
    health-bus restarts land in the same envelope shape, so engine and broker
    dumps interleave through :func:`merge_dumps` into one incident timeline).
    The cluster autobalancer records into a third lane (``role="balancer"``):
    a self-healing incident reconstructs end to end — kill, page, grace
    reassignment, balancer move, page clear — from one merged timeline.
    Thread-safe: the sites span gRPC handler threads, the replication worker,
    the group-sync thread and the liveness prober.
    """

    def __init__(self, capacity: int = 1024, name: str = "",
                 role: str = "broker") -> None:
        self._ring: "deque" = deque(maxlen=max(capacity, 8))
        self._lock = threading.Lock()
        self._seq = 0
        #: events the bounded ring evicted to make room — an operator reading
        #: a mid-incident dump must be able to tell the ring wrapped
        self._dropped = 0
        #: who recorded (the broker's advertised address, set lazily at
        #: start() — dumps from several brokers must be tellable apart)
        self.name = name
        #: which lane this recorder's events belong to on a merged timeline
        #: ("broker" | "engine"); carried in the dump envelope
        self.role = role
        self.node = socket.gethostname()

    def record(self, etype: str, **attrs) -> None:
        """Append one event; never raises (a recording site must not be able
        to take down the path it observes)."""
        try:
            with self._lock:
                self._seq += 1
                if len(self._ring) == self._ring.maxlen:
                    self._dropped += 1
                self._ring.append((self._seq, time.monotonic(), time.time(),
                                   etype, attrs or None))
        except Exception:  # noqa: BLE001 — observability must stay passive
            pass

    def stats(self) -> dict:
        """Ring occupancy view for status surfaces (BrokerStatus / the engine
        admin plane): whether the bounded ring has wrapped mid-incident."""
        with self._lock:
            return self._stats_locked()

    def _stats_locked(self) -> dict:
        return {"events": len(self._ring),
                "capacity": self._ring.maxlen,
                "dropped": self._dropped}

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)

    def events(self, last: Optional[int] = None) -> List[dict]:
        """The recorded events, oldest first (``last`` keeps only the tail)."""
        with self._lock:
            items = list(self._ring)
        return self._format_events(items, last)

    @staticmethod
    def _format_events(items, last: Optional[int]) -> List[dict]:
        if last is not None:
            items = items[-last:] if last > 0 else []
        out = []
        for seq, mono, wall, etype, attrs in items:
            ev = {"seq": seq, "mono": mono, "wall": wall, "type": etype}
            if attrs:
                ev.update(attrs)
            out.append(ev)
        return out

    def dump(self, last: Optional[int] = None) -> dict:
        """The merge-ready dump envelope: events + clock-domain identity.
        ``dumped_mono``/``dumped_wall`` pair the host's two clocks at ONE
        instant — the header :func:`merge_dumps` estimates the per-host
        mono↔wall offset from, so cross-host merges survive wall-clock skew
        during the incident. Stats and events snapshot under ONE lock hold:
        a mid-incident dump's dropped count must describe exactly the event
        list it ships, not the ring three records later."""
        with self._lock:
            stats = self._stats_locked()
            items = list(self._ring)
        return {"recorder": self.name, "node": self.node, "pid": os.getpid(),
                "role": self.role, "stats": stats,
                "dumped_wall": time.time(), "dumped_mono": time.monotonic(),
                "events": self._format_events(items, last)}

    def dump_to(self, path: str, last: Optional[int] = None) -> None:
        """Write the dump as JSON (the crash auto-dump sink). Best-effort:
        a full disk must not mask the crash being dumped."""
        try:
            with open(path, "w") as f:
                json.dump(self.dump(last), f)
        except OSError:
            pass


def host_wall_offset(dump: dict) -> Optional[float]:
    """The per-host mono→wall offset estimated from the dump HEADER: the
    recorder stamps both clocks at the same instant when dumping, so
    ``dumped_wall - dumped_mono`` maps any of this host's monotonic stamps
    onto its wall timeline AS OF DUMP TIME. Placing events at
    ``offset + ev.mono`` instead of their raw ``wall`` stamp makes the
    cross-host merge immune to wall steps/skew DURING the incident (the NTP
    correction that lands mid-failover and would otherwise scramble raw wall
    ordering) — only the residual skew between hosts at dump time remains.
    None for a legacy dump without the header pair (raw wall fallback)."""
    dw, dm = dump.get("dumped_wall"), dump.get("dumped_mono")
    if dw is None or dm is None:
        return None
    return float(dw) - float(dm)


def merge_dumps(dumps: Sequence[dict]) -> List[dict]:
    """Merge several brokers' dumps into one ordered timeline.

    Each returned event gains ``recorder`` (who recorded it). Ordering: by
    ``mono`` when every dump came from the same host (CLOCK_MONOTONIC is
    host-shared, comparable across the brokers' processes and immune to NTP
    steps); across hosts by the ESTIMATED wall time ``host_wall_offset(dump)
    + ev.mono`` (per-host mono↔wall offsets from the dump headers — wall
    steps during the incident cannot scramble the order), falling back to
    each event's raw ``wall`` stamp only for legacy dumps without the header
    pair. Ties break by wall then per-recorder seq."""
    merged: List[dict] = []
    same_clock = same_clock_domain(dumps)
    for d in dumps:
        who = d.get("recorder") or d.get("node") or "?"
        lane = d.get("role") or "broker"
        offset = host_wall_offset(d)
        for ev in d.get("events", ()):
            e = dict(ev)
            e["recorder"] = who
            e["lane"] = lane
            e["_est_wall"] = (offset + e.get("mono", 0.0)
                              if offset is not None else e.get("wall", 0.0))
            merged.append(e)
    key = ((lambda e: (e.get("mono", 0.0), e.get("wall", 0.0), e.get("seq", 0)))
           if same_clock else
           (lambda e: (e.get("_est_wall", 0.0), e.get("wall", 0.0),
                       e.get("seq", 0))))
    merged.sort(key=key)
    for e in merged:
        del e["_est_wall"]
    return merged


#: the failover phases an incident review walks, in causal order, mapped to
#: the event types the broker records
_PHASE_NAMES = ("promotion_decision", "promotion", "fence", "truncation",
                "first_acked_commit")


def reconstruct_failover(merged: Sequence[dict]) -> dict:
    """Extract the failover phases from a merged timeline: promotion decision
    → promotion → fence → truncation → first acked post-failover commit.

    Phases are ANCHORED to the newest promotion in the ring (the incident an
    operator is looking at): the decision is the latest ``promote-decision``
    at or before it (the promotion itself when promotion was manual — no
    prober ever decided anything), and fence/truncation/first-ack are the
    first matching events from the decision onward. Without the anchor, a
    ring holding two incidents would stitch one incident's promotion to
    another's fence and report a healed failover that never healed.

    Returns ``{"phases": {name: event-or-None}, "complete": bool,
    "span_ms": float-or-None}`` — ``span_ms`` is decision → first ack in
    host-monotonic time (same-host dumps; None when either end is missing).

    Tolerates timelines with NO broker-shaped events at all — a merged set
    holding only engine-lane dumps (lane transitions, rebalances, SLO
    breaches) reconstructs to all-None phases with ``complete=False``
    instead of raising, and events missing ``mono`` stamps (hand-built or
    legacy dumps) simply yield no span."""
    merged = list(merged)
    phases: Dict[str, Optional[dict]] = {n: None for n in _PHASE_NAMES}
    promo_idx = max((i for i, e in enumerate(merged)
                     if e.get("type") == "role.promote"), default=None)
    if promo_idx is not None:
        phases["promotion"] = merged[promo_idx]
        decision_idx = max(
            (i for i, e in enumerate(merged[:promo_idx + 1])
             if e.get("type") == "role.promote-decision"),
            default=promo_idx)
        phases["promotion_decision"] = merged[decision_idx]
        for name, etype in (("fence", "role.fence"),
                            ("truncation", "log.truncate"),
                            ("first_acked_commit", "txn.first-ack")):
            phases[name] = next(
                (e for e in merged[decision_idx:] if e.get("type") == etype),
                None)
    complete = all(phases[n] is not None for n in _PHASE_NAMES)
    span_ms = None
    start, end = phases["promotion_decision"], phases["first_acked_commit"]
    if (start is not None and end is not None
            and start.get("recorder") == end.get("recorder")
            and start.get("mono") is not None
            and end.get("mono") is not None):
        # both phases are recorded by the PROMOTING broker (its prober
        # decides, its Transact acks), so their monotonic stamps share a
        # clock; a mismatch means hand-built dumps — no comparable span
        span_ms = round((end["mono"] - start["mono"]) * 1000.0, 1)
    return {"phases": phases, "complete": complete, "span_ms": span_ms}
