"""Roofline recorder: measured device-fold figures as append-only JSONL.

docs/roofline.md holds the measured walls every fold decision rests on
(~58 µs scan-step floor, d2h ~25 MB/s, the ~8 µs/event-slot steady-fold
dispatch with ~9× padding over-dispatch — BENCH_NOTES round 9). Those rows
were hand-carried out of bench runs; this module makes the measurement
continuous: a :class:`RooflineRecorder` snapshots a refresh-round ledger's
:meth:`~surge_tpu.replay.ledger.ReplayLedger.summary` (measured ev/s,
µs/slot, µs/event, padding-waste ratio) into one JSON line per snapshot —
append-only, so a file accumulates the machine's trajectory across runs
and regressions show as rows, not as a reverted doc table.

``tools/roofline_record.py`` is the operator CLI (pulls ``DumpReplayLedger``
from a live engine, or reads a saved dump file); :data:`REFERENCE` carries
the docs/roofline.md anchor figures so a row can be compared against the
published wall in one call (:func:`against_reference`).
"""

from __future__ import annotations

import json
import os
import time
from typing import Dict, Iterator, Optional

__all__ = ["REFERENCE", "RooflineRecorder", "against_reference",
           "roofline_row"]

#: docs/roofline.md anchor figures (the published walls new rows are read
#: against). Keys name the measured regime; values the doc's figures.
REFERENCE: Dict[str, Dict[str, float]] = {
    # BENCH_NOTES round 9: steady ragged incremental folds on the CPU
    # backend — ~8 µs of host-observed dispatch per padded event slot,
    # ~9× padding over-dispatch (pow8 lane bucket × pow2 window tail)
    "steady-ragged-cpu": {"us_per_slot": 8.0, "waste_ratio": 9.0},
}

#: the summary keys a roofline row carries (the derived ratios first — the
#: figures docs/roofline.md tabulates — then the raw totals they came from)
_ROW_KEYS = ("fold_events_per_sec", "us_per_slot", "us_per_event",
             "waste_ratio", "rounds", "events", "dispatched_slots",
             "occupied_slots", "dispatch_us", "encode_us", "feed_us",
             "gathers", "gathered_rows", "gather_wait_us")


def roofline_row(summary: Dict[str, object], *, source: str = "",
                 note: str = "", wall: Optional[float] = None) -> dict:
    """One JSONL row from a ledger summary (``ReplayLedger.summary()`` or
    the ``summary`` key of a ``DumpReplayLedger`` payload)."""
    row = {"wall": round(wall if wall is not None else time.time(), 3),
           "source": source, "note": note}
    for k in _ROW_KEYS:
        if k in summary:
            row[k] = summary[k]
    return row


def against_reference(row: Dict[str, object], name: str = "steady-ragged-cpu"
                      ) -> Dict[str, float]:
    """Measured/published ratios against a :data:`REFERENCE` anchor
    (``{figure: measured/reference}`` — 1.0 means the wall holds; missing
    figures are omitted, an unknown anchor raises KeyError)."""
    ref = REFERENCE[name]
    out: Dict[str, float] = {}
    for k, published in ref.items():
        v = row.get(k)
        if isinstance(v, (int, float)) and published:
            out[k] = round(float(v) / published, 3)
    return out


class RooflineRecorder:
    """Append-only JSONL sink for roofline rows.

    Each :meth:`record` call appends one line and returns the row it wrote;
    the file is opened per append (the recorder holds no handle — several
    bench processes may share one trajectory file, and a crashed run can
    never leave a torn writer)."""

    def __init__(self, path: str) -> None:
        self.path = path

    def record(self, summary: Dict[str, object], *, source: str = "",
               note: str = "", wall: Optional[float] = None) -> dict:
        row = roofline_row(summary, source=source, note=note, wall=wall)
        d = os.path.dirname(self.path)
        if d:
            os.makedirs(d, exist_ok=True)
        with open(self.path, "a") as f:
            f.write(json.dumps(row) + "\n")
        return row

    def rows(self) -> Iterator[dict]:
        """Every recorded row, oldest first (missing file → no rows;
        torn/blank lines are skipped — append-only files on crashed hosts
        end mid-line)."""
        try:
            f = open(self.path)
        except OSError:
            return
        with f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    yield json.loads(line)
                except ValueError:
                    continue

    def latest(self) -> Optional[dict]:
        row = None
        for row in self.rows():  # noqa: B007 — want the last one
            pass
        return row
