"""SLO burn-rate engine: declarative objectives over the federated payload.

Raw histograms answer "what IS the p99"; an operator needs "are we meeting
the target, and how fast are we spending the error budget if not". This
module implements the Google-SRE multiwindow burn-rate method over the
instruments the repo already ships, evaluated from the
:class:`~surge_tpu.observability.federation.FederatedScraper`'s merged
families (one evaluation per federation pass — no second collection path):

- an :class:`SLO` names a metric FAMILY in the merged payload plus an
  objective (the fraction of good events): ``latency`` objectives read a
  histogram family (good = observations at or under ``threshold`` ms),
  ``availability`` objectives read a bad-event counter against a
  good-event counter (attempts = bad + good), and ``bound`` objectives
  sample a gauge per pass (good = the gauge satisfies the bound —
  staleness/lag style targets, and the fleet-level ``up`` gauge);
- the engine keeps a cumulative-snapshot history per objective and computes
  the **burn rate** — bad-fraction over a window divided by the error budget
  ``1 - objective`` — over a FAST and a SLOW window
  (``surge.slo.fast-window-ms`` / ``surge.slo.slow-window-ms``); a breach
  fires only when BOTH windows exceed ``surge.slo.burn-threshold`` (fast
  alone = noise spike, slow alone = old news: the multiwindow page
  condition);
- a breach increments ``surge.slo.breaches``, flips the ``slo`` health
  component to **degraded** (never down — an SLO page must not trip restart
  supervision), emits an ``slo.breach.<name>`` signal on the attached health
  bus, and stamps an ``slo.breach`` flight-recorder event so the breach
  appears on reconstructed incident timelines next to the promotion/fence
  events that caused it.

Every objective must reference a CATALOGED instrument — surgelint's
``metric-catalog`` rule and ``tests/test_lint.py`` reject an ``SLO`` whose
``family``/``good_family`` appears in no golden exposition (no dead
objectives watching metrics nothing emits).
"""

from __future__ import annotations

import time
from collections import deque
from dataclasses import dataclass, field
from typing import Deque, Dict, List, Optional, Sequence, Tuple

from surge_tpu.config import Config, default_config
from surge_tpu.health import HealthCheck

__all__ = ["DEFAULT_SLOS", "SLO", "SLOEngine"]


@dataclass(frozen=True)
class SLO:
    """One declarative objective over a merged-payload family.

    ``kind``:
      - ``latency`` — ``family`` is a histogram (``_bucket``/``_count``);
        good events are observations with value <= ``threshold`` (ms);
      - ``availability`` — ``family`` is a BAD-event counter and
        ``good_family`` the GOOD-event counter (both ``_total`` samples);
        total = bad + good, so a window of 100% failures burns at full
        rate instead of dividing by a success counter that never moved;
      - ``bound`` — ``family`` is a gauge; each instance sample per
        evaluation is one observation, bad when it violates ``threshold``
        per ``op`` (``"gt"``: bad when value > threshold; ``"lt"``: bad
        when value < threshold).
    """

    name: str
    family: str
    kind: str  # "latency" | "availability" | "bound"
    objective: float  # fraction of good events, e.g. 0.99
    threshold: float = 0.0
    op: str = "gt"  # bound kind only: which violation direction is "bad"
    good_family: str = ""  # availability kind only
    description: str = ""

    @property
    def budget(self) -> float:
        """The error budget: the bad fraction the objective tolerates."""
        return max(1.0 - self.objective, 1e-9)


#: the shipped fleet objectives — every family cited here is rendered by a
#: golden exposition (tests/golden/*.om), which tests/test_lint.py enforces
DEFAULT_SLOS: Tuple[SLO, ...] = (
    SLO("command-latency",
        family="surge_aggregate_command_handling_timer_ms",
        kind="latency", objective=0.99, threshold=100.0,
        description="99% of commands handle in <= 100ms"),
    SLO("publish-availability",
        family="surge_producer_publish_failures",
        good_family="surge_producer_batch_commits",
        kind="availability", objective=0.999,
        description="99.9% of publish batches commit (failures are "
                    "dominated by broker failover windows)"),
    SLO("resident-staleness",
        family="surge_replay_resident_fold_lag_records",
        kind="bound", objective=0.99, threshold=4096.0, op="gt",
        description="the resident plane's fold lag stays within the "
                    "read-path staleness bound"),
    SLO("resident-fold-efficiency",
        family="surge_replay_resident_padding_waste_ratio",
        kind="bound", objective=0.99, threshold=16.0, op="gt",
        description="refresh rounds keep padding over-dispatch within the "
                    "pow8-lane x window-tail envelope (waste ratio <= 16x; "
                    "beyond it ragged traffic is mostly padding the device)"),
    SLO("quorum-hwm-lag",
        family="surge_log_hwm_lag_records",
        kind="bound", objective=0.99, threshold=10_000.0, op="gt",
        description="the quorum-acked high-watermark keeps up with the "
                    "applied frontier"),
    SLO("fleet-up",
        family="up",
        kind="bound", objective=0.99, threshold=1.0, op="lt",
        description="every fleet member answers its scrape (an instance "
                    "down burns this objective's budget)"),
    SLO("state-divergence",
        family="surge_audit_unresolved_divergences",
        kind="bound", objective=0.99, threshold=0.0, op="gt",
        description="the consistency auditor holds no unresolved divergence "
                    "(slab rows byte-match their shadow refold, replica "
                    "digests agree below the hwm, dedup probes replay) — "
                    "any finding burns this objective until re-verified "
                    "clean"),
)


@dataclass
class _Track:
    """Cumulative (bad, total) snapshots for one objective, newest last."""

    history: Deque[Tuple[float, float, float]] = field(default_factory=deque)
    breached: bool = False
    #: evaluation-clock stamp of the CURRENT breach's onset (None while
    #: healthy) — how long a page has been open, and whether it cleared
    #: after a heal, readable straight off the status rows
    breached_at: Optional[float] = None
    burn_fast: float = 0.0
    burn_slow: float = 0.0
    last_bad_fraction: float = 0.0


class SLOEngine:
    """Evaluates a set of objectives from merged families per pass."""

    def __init__(self, slos: Sequence[SLO] = DEFAULT_SLOS,
                 config: Config | None = None, metrics=None,
                 on_signal=None, flight=None, tail=None, anatomy=None,
                 clock=time.time) -> None:
        cfg = config or default_config()
        self.slos = list(slos)
        self.fast_window_s = cfg.get_seconds("surge.slo.fast-window-ms",
                                             300_000)
        self.slow_window_s = cfg.get_seconds("surge.slo.slow-window-ms",
                                             3_600_000)
        self.burn_threshold = cfg.get_float("surge.slo.burn-threshold", 14.4)
        self.metrics = metrics  # FleetMetrics quiver (optional)
        self.on_signal = on_signal or (lambda name, level: None)
        self.flight = flight  # FlightRecorder (optional): breaches join the ring
        #: TailSampler (optional, ISSUE 14): a breach opens its keep-window
        #: (breach-adjacent traces become anatomy evidence) and the breach
        #: event cites the newest kept trace ids as exemplars
        self.tail = tail
        #: zero-arg callable returning {"dominant", "dominant_share",
        #: "traces"} (anatomy.dominant_leg over live/ring dumps) or None —
        #: a breach then fires a `trace.anatomy` flight event naming the
        #: dominant critical-path leg
        self.anatomy = anatomy
        self._clock = clock
        self._tracks: Dict[str, _Track] = {s.name: _Track() for s in self.slos}

    # -- extraction ---------------------------------------------------------------------

    @staticmethod
    def _counts(slo: SLO, families: Dict[str, object]) -> Tuple[float, float]:
        """Cumulative (bad, total) for one objective, summed across every
        instance's samples in the merged payload."""
        fam = families.get(slo.family)
        if slo.kind == "latency":
            if fam is None:
                return 0.0, 0.0
            good = bad = total = 0.0
            # per-instance histograms: within one instance's label set, the
            # good count is the cumulative bucket at the largest bound <=
            # threshold; totals come from _count
            per_inst: Dict[tuple, Dict[str, float]] = {}
            for s in fam.samples:
                inst = tuple(kv for kv in s.labels if kv[0] == "instance")
                slot = per_inst.setdefault(inst, {"good": 0.0, "total": 0.0})
                if s.suffix == "_count":
                    slot["total"] = s.value
                elif s.suffix == "_bucket":
                    le = dict(s.labels).get("le", "")
                    try:
                        bound = float(le.replace("+Inf", "inf"))
                    except ValueError:
                        continue
                    if bound <= slo.threshold:
                        slot["good"] = max(slot["good"], s.value)
            for slot in per_inst.values():
                good += slot["good"]
                total += slot["total"]
            bad = max(total - good, 0.0)
            return bad, total
        if slo.kind == "availability":
            bad = sum(s.value for s in fam.samples) if fam is not None else 0.0
            good_fam = families.get(slo.good_family)
            good = (sum(s.value for s in good_fam.samples)
                    if good_fam is not None else 0.0)
            # attempts = failures + successes: a window of pure failures
            # must burn at full rate, not divide by a success counter that
            # never moved (total=0 would read as burn 0 mid-outage)
            return bad, bad + good
        # bound: each instance gauge sample this pass is one observation
        if fam is None:
            return 0.0, 0.0
        bad = total = 0.0
        for s in fam.samples:
            if s.suffix:
                continue
            total += 1.0
            violated = (s.value > slo.threshold if slo.op == "gt"
                        else s.value < slo.threshold)
            if violated:
                bad += 1.0
        return bad, total

    # -- burn-rate math -----------------------------------------------------------------

    def _burn(self, track: _Track, window_s: float, now: float,
              budget: float, cumulative: bool) -> float:
        """Bad-fraction over the window / error budget. ``cumulative``
        snapshots (counters, histograms) difference the window's endpoints;
        per-pass snapshots (bound gauges) sum the window's observations."""
        hist = [h for h in track.history if h[0] >= now - window_s]
        if not hist:
            return 0.0
        older = [h for h in track.history if h[0] < now - window_s]
        if not older and len(hist) < 2:
            # the engine's first-ever snapshot trivially satisfies BOTH
            # windows at once — one cold-start sample (a member caught
            # mid-restart, a cumulative counter's lifetime total) must not
            # page; persistence needs at least a second observation
            return 0.0
        if cumulative:
            # increase()-style: delta vs the newest snapshot BEFORE the
            # window, or vs the window's own first snapshot when the engine
            # is younger than the window — a cold first scrape of a
            # long-running fleet must not attribute its whole cumulative
            # history to one window. Counter resets (a restarted process)
            # clamp at 0 rather than going negative.
            base = older[-1] if older else hist[0]
            bad = max(hist[-1][1] - base[1], 0.0)
            total = max(hist[-1][2] - base[2], 0.0)
        else:
            bad = sum(h[1] for h in hist)
            total = sum(h[2] for h in hist)
        if total <= 0.0:
            return 0.0
        return (bad / total) / budget

    # -- evaluation ---------------------------------------------------------------------

    def evaluate(self, families, now: Optional[float] = None) -> List[dict]:
        """One evaluation pass over merged families (a list or a
        name-keyed dict); returns the per-objective status rows."""
        now = self._clock() if now is None else now
        if not isinstance(families, dict):
            families = {f.name: f for f in families}
        rows: List[dict] = []
        active = 0
        max_burn = 0.0
        for slo in self.slos:
            track = self._tracks[slo.name]
            bad, total = self._counts(slo, families)
            track.history.append((now, bad, total))
            while (len(track.history) > 2
                   and track.history[1][0] < now - self.slow_window_s):
                # keep ONE snapshot older than the slow window: cumulative
                # deltas need the pre-window base
                track.history.popleft()
            cumulative = slo.kind in ("latency", "availability")
            track.burn_fast = self._burn(track, self.fast_window_s, now,
                                         slo.budget, cumulative)
            track.burn_slow = self._burn(track, self.slow_window_s, now,
                                         slo.budget, cumulative)
            breached = (track.burn_fast >= self.burn_threshold
                        and track.burn_slow >= self.burn_threshold)
            if breached and not track.breached:
                track.breached_at = now
                if self.metrics is not None:
                    self.metrics.slo_breaches.record()
                self.on_signal(f"slo.breach.{slo.name}", "warning")
                exemplars = None
                if self.tail is not None:
                    # keep breach-adjacent traces (the anatomy evidence) and
                    # cite the newest already-kept ids on the breach event
                    try:
                        self.tail.open_breach_window()
                        exemplars = self.tail.ring.trace_ids(3)
                    except Exception:  # noqa: BLE001 — paging must not die
                        exemplars = None
                if self.flight is not None:
                    self.flight.record(
                        "slo.breach", objective=slo.name,
                        burn_fast=round(track.burn_fast, 2),
                        burn_slow=round(track.burn_slow, 2),
                        threshold=self.burn_threshold,
                        exemplar_trace_ids=exemplars or None)
                self._record_anatomy(slo.name)
            elif track.breached and not breached:
                self.on_signal(f"slo.recovered.{slo.name}", "trace")
                if self.flight is not None:
                    self.flight.record(
                        "slo.recovered", objective=slo.name,
                        open_s=(round(now - track.breached_at, 2)
                                if track.breached_at is not None else None))
                track.breached_at = None
            track.breached = breached
            if breached:
                active += 1
            max_burn = max(max_burn, track.burn_fast)
            rows.append(self.status_row(slo))
        if self.metrics is not None:
            self.metrics.slo_objectives.record(len(self.slos))
            self.metrics.slo_evaluations.record()
            self.metrics.slo_active_breaches.record(active)
            self.metrics.slo_max_burn_rate.record(max_burn)
        return rows

    def _record_anatomy(self, objective: str) -> None:
        """Fire the `trace.anatomy` flight event on a breach: which
        critical-path leg dominates the tail-kept traces (the where-did-the-
        time-go answer, right next to the breach on the incident timeline).
        Best-effort — the anatomy source may need RPCs that fail mid-
        incident, and a page must still fire without it."""
        if self.anatomy is None or self.flight is None:
            return
        try:
            verdict = self.anatomy()
        except Exception:  # noqa: BLE001 — anatomy is evidence, not gating
            verdict = None
        if verdict:
            self.flight.record(
                "trace.anatomy", objective=objective,
                dominant_leg=verdict.get("dominant"),
                share=verdict.get("dominant_share"),
                traces=verdict.get("traces"))

    def status_row(self, slo: SLO) -> dict:
        track = self._tracks[slo.name]
        return {"objective": slo.name, "kind": slo.kind,
                "target": slo.objective,
                "burn_fast": round(track.burn_fast, 3),
                "burn_slow": round(track.burn_slow, 3),
                "breached": track.breached,
                "breached_since": track.breached_at,
                "description": slo.description}

    def status(self) -> List[dict]:
        """Per-objective burn/breach rows (what ``surgetop`` renders)."""
        return [self.status_row(s) for s in self.slos]

    def breached(self) -> List[str]:
        return [s.name for s in self.slos if self._tracks[s.name].breached]

    def health_component(self) -> HealthCheck:
        """The ``slo`` component for a health tree: degraded while any
        objective burns over threshold, never down — an SLO page means "go
        look", not "restart things"."""
        names = self.breached()
        return HealthCheck(name="slo",
                           status="degraded" if names else "up")
