"""Inter-node remote delivery — the distributed control-plane transport.

The reference forwards envelopes between nodes over Akka remoting (Artery TCP
``ActorSelection`` built from HostPort, KafkaPartitionShardRouterActor.scala:265-271,
serialized with Jackson-CBOR). The TPU-native build replaces that with gRPC over
DCN (SURVEY.md §5.8): each engine node runs a :class:`NodeTransportServer`; routers
forward to remote owners through a :class:`GrpcRemoteDeliver` whose channels are
keyed by HostPort. Payloads cross in the app's own formats (``command_format`` /
``event_format`` / ``state_format`` from the business logic), and trace context
rides the request headers like TracedMessage carries W3C headers.
"""

from surge_tpu.remote.control_plane import (
    ControlPlaneClient,
    ControlPlaneServer,
)
from surge_tpu.remote.transport import GrpcRemoteDeliver, NodeTransportServer

__all__ = [
    "ControlPlaneClient",
    "ControlPlaneServer",
    "GrpcRemoteDeliver",
    "NodeTransportServer",
]
