"""Cross-process control plane: membership, assignments, allocations, one epoch.

The reference distributes this metadata through the Kafka consumer-group protocol
(rebalances produce assignments) and Akka remoting (the assignment registry actor
broadcasts them — KafkaConsumerStateTrackingActor.scala:39-118; the cluster-sharding
listener pushes external shard allocations — KafkaClusterShardingRebalanceListener
.scala:144-181). Here a small gRPC service is the single authority:

- **ControlPlaneServer** owns the member set (heartbeat-expired), the partition
  assignments (auto-balanced across live members on every membership change — the
  consumer-group-rebalance role), the shard-allocation table, and a monotonically
  increasing **epoch** stamped on every state broadcast.
- **ControlPlaneClient** joins, watches the server-streamed state, and applies each
  epoch-ordered update into *remote mirror* objects — drop-in subclasses of
  :class:`PartitionTracker` / :class:`ClusterMembership` /
  :class:`ExternalShardAllocation` whose mutators forward to the server instead of
  mutating locally. Engines and routers are wired to the mirrors unchanged.
- **Dual-leader closure**: ``UpdateShardLocations`` is compare-and-set on the epoch
  AND verified against the server's own leader view, so two nodes that transiently
  both believe they are the lowest-address leader cannot both win — the stale one
  gets a Conflict and reconverges from the next watch update.
"""

from __future__ import annotations

import asyncio
import time
from typing import Callable, Dict, List, Mapping, Optional

import grpc

from surge_tpu.common import logger
from surge_tpu.config import Config, default_config
from surge_tpu.engine.cluster import ClusterMembership, ExternalShardAllocation
from surge_tpu.engine.partition import (
    AssignmentChanges,
    Assignments,
    HostPort,
    PartitionTracker,
)
from surge_tpu.remote import control_plane_pb2 as pb

SERVICE = "surge_tpu.control.ControlPlane"
UNARY_METHODS = {
    "Join": (pb.JoinRequest, pb.ClusterState),
    "Leave": (pb.MemberRequest, pb.ControlAck),
    "Ping": (pb.MemberRequest, pb.ControlAck),
    "UpdateAssignments": (pb.UpdateAssignmentsRequest, pb.ControlAck),
    "UpdateShardLocations": (pb.AllocateRequest, pb.ControlAck),
}


def _hp(member: pb.Member) -> HostPort:
    return HostPort(member.host, member.port)


def _hp_str(s: str) -> HostPort:
    host, _, port = s.rpartition(":")
    return HostPort(host, int(port))


class ControlPlaneServer:
    """The epoch authority. One per cluster (like the reference's broker/seed role)."""

    def __init__(self, num_partitions: int, host: str = "127.0.0.1", port: int = 0,
                 auto_balance: bool = True,
                 member_timeout_s: Optional[float] = None,
                 config: Config | None = None,
                 persist_path: Optional[str] = None) -> None:
        self.num_partitions = num_partitions
        self.auto_balance = auto_balance
        cfg = config or default_config()
        self.member_timeout_s = (
            member_timeout_s if member_timeout_s is not None
            else cfg.get_seconds("surge.control-plane.member-timeout-ms", 3_000))
        self._host = host
        self._port = port
        self._config = config
        self.epoch = 0
        self._members: Dict[HostPort, dict] = {}  # -> {last_ping, transport_target}
        self._assignments: Dict[HostPort, List[int]] = {}
        self._locations: Dict[int, HostPort] = {}
        self._watchers: List[asyncio.Queue] = []
        self._server: Optional[grpc.aio.Server] = None
        self.bound_port: Optional[int] = None
        self._expiry_task: Optional[asyncio.Task] = None
        self._thread = None
        self._thread_loop = None
        # durability: every epoch bump snapshots (epoch, members, assignments,
        # allocations) to disk, and a restarted seed resumes from it — clients
        # re-joining after the restart see a CONTINUED epoch instead of a reset
        # one, and no allocation/assignment state is lost with the process
        # (coordinator durability role, KafkaConsumerStateTrackingActor.scala:
        # 39-118 backed by the consumer-group store in the reference)
        self._persist_path = persist_path
        import threading

        self._save_lock = threading.Lock()
        self._saved_epoch = -1
        if persist_path:
            self._load()

    # -- persistence ----------------------------------------------------------------------

    def _load(self) -> None:
        import json
        import os

        if not self._persist_path or not os.path.exists(self._persist_path):
            return
        try:
            with open(self._persist_path) as f:
                snap = json.load(f)
        except (OSError, ValueError) as exc:
            logger.warning("control-plane snapshot %s unreadable (%r); "
                           "starting fresh", self._persist_path, exc)
            return
        self.epoch = int(snap.get("epoch", 0))
        now = time.monotonic()
        # restored members get a fresh heartbeat window: they were alive at the
        # snapshot and their ping loop re-registers within member_timeout anyway
        self._members = {
            _hp_str(m["member"]): {"last_ping": now,
                                   "transport_target": m.get("target", "")}
            for m in snap.get("members", [])}
        self._assignments = {
            _hp_str(host): list(parts)
            for host, parts in snap.get("assignments", {}).items()}
        self._locations = {int(p): _hp_str(m)
                           for p, m in snap.get("locations", {}).items()}

    def _save(self) -> None:
        """Snapshot state to disk. The dict is built synchronously (cheap); the
        write+fsync runs in the default executor so membership churn on a slow
        disk never stalls the event loop past ping timeouts. A version guard
        keeps out-of-order executor completions from persisting an older epoch
        over a newer one."""
        if not self._persist_path:
            return
        snap = {
            "epoch": self.epoch,
            "members": [{"member": str(m), "target": info["transport_target"]}
                        for m, info in self._members.items()],
            "assignments": {str(m): parts
                            for m, parts in self._assignments.items()},
            "locations": {str(p): str(m) for p, m in self._locations.items()},
        }
        try:
            loop = asyncio.get_running_loop()
        except RuntimeError:
            self._write_snapshot(snap)
            return
        loop.run_in_executor(None, self._write_snapshot, snap)

    def _write_snapshot(self, snap: dict) -> None:
        import json
        import os

        with self._save_lock:
            if snap["epoch"] <= self._saved_epoch:
                return  # a newer snapshot already landed
            tmp = self._persist_path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(snap, f)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, self._persist_path)
            self._saved_epoch = snap["epoch"]

    # -- state ----------------------------------------------------------------------------

    def _state_msg(self) -> pb.ClusterState:
        state = pb.ClusterState(epoch=self.epoch)
        for m in sorted(self._members):
            state.members.append(pb.Member(
                host=m.host, port=m.port,
                transport_target=self._members[m]["transport_target"]))
        for m, parts in self._assignments.items():
            state.assignments[str(m)].partitions.extend(sorted(parts))
        for p, m in self._locations.items():
            state.shard_locations[p] = str(m)
        return state

    def _leader(self) -> Optional[HostPort]:
        return min(self._members) if self._members else None

    def _bump_and_broadcast(self) -> None:
        self.epoch += 1
        self._save()
        msg = self._state_msg()
        for q in list(self._watchers):
            q.put_nowait(msg)

    def _rebalance(self) -> None:
        """Round-robin the partition range across live members (the consumer-group
        rebalance role). Deterministic: members sorted, partitions in order."""
        members = sorted(self._members)
        if not members:
            self._assignments = {}
            return
        new: Dict[HostPort, List[int]] = {m: [] for m in members}
        for p in range(self.num_partitions):
            new[members[p % len(members)]].append(p)
        self._assignments = new

    def _remove_member(self, member: HostPort) -> bool:
        if member not in self._members:
            return False
        del self._members[member]
        self._assignments.pop(member, None)
        # a departed member must not keep owning shards; the leader (or the next
        # assignment application) re-allocates the now-unowned partitions
        self._locations = {p: m for p, m in self._locations.items() if m != member}
        if self.auto_balance:
            self._rebalance()
        return True

    # -- handlers -------------------------------------------------------------------------

    async def Join(self, request: pb.JoinRequest, context) -> pb.ClusterState:
        member = _hp(request.member)
        self._members[member] = {
            "last_ping": time.monotonic(),
            "transport_target": request.member.transport_target,
        }
        if self.auto_balance:
            self._rebalance()
        self._bump_and_broadcast()
        return self._state_msg()

    async def Leave(self, request: pb.MemberRequest, context) -> pb.ControlAck:
        if self._remove_member(_hp(request.member)):
            self._bump_and_broadcast()
        return pb.ControlAck(ok=True, epoch=self.epoch)

    async def Ping(self, request: pb.MemberRequest, context) -> pb.ControlAck:
        info = self._members.get(_hp(request.member))
        if info is None:  # expired or never joined: tell the node to re-join
            return pb.ControlAck(ok=False, error="unknown member", epoch=self.epoch)
        info["last_ping"] = time.monotonic()
        return pb.ControlAck(ok=True, epoch=self.epoch)

    async def UpdateAssignments(self, request: pb.UpdateAssignmentsRequest,
                                context) -> pb.ControlAck:
        # Same closure as UpdateShardLocations (advisor r3 #3): when the server
        # auto-balances, IT owns assignments — a member wholesale-overwriting them
        # (e.g. from a stale membership view) would reinstate dead members'
        # partitions until the next rebalance. Manual mode stays writable but is
        # epoch-CAS'd so a stale writer loses and reconverges from the watch.
        if self.auto_balance:
            return pb.ControlAck(ok=False, epoch=self.epoch,
                                 error="assignments are auto-balanced")
        if request.observed_epoch != self.epoch:
            return pb.ControlAck(
                ok=False, epoch=self.epoch,
                error=f"stale epoch {request.observed_epoch} != {self.epoch}")
        self._assignments = {
            _hp_str(host): list(pl.partitions)
            for host, pl in request.assignments.items()}
        self._bump_and_broadcast()
        return pb.ControlAck(ok=True, epoch=self.epoch)

    async def UpdateShardLocations(self, request: pb.AllocateRequest,
                                   context) -> pb.ControlAck:
        sender = _hp(request.member)
        leader = self._leader()
        if sender != leader:
            return pb.ControlAck(
                ok=False, epoch=self.epoch,
                error=f"not leader (leader is {leader})")
        if request.observed_epoch != self.epoch:
            return pb.ControlAck(
                ok=False, epoch=self.epoch,
                error=f"stale epoch {request.observed_epoch} != {self.epoch}")
        changed = False
        for p, target in request.locations.items():
            owner = _hp_str(target)
            if self._locations.get(p) != owner:
                self._locations[p] = owner
                changed = True
        if changed:
            self._bump_and_broadcast()
        return pb.ControlAck(ok=True, epoch=self.epoch)

    async def Watch(self, request: pb.WatchRequest, context):
        queue: asyncio.Queue = asyncio.Queue()
        self._watchers.append(queue)
        try:
            if self.epoch > request.from_epoch:
                yield self._state_msg()
            while True:
                yield await queue.get()
        finally:
            self._watchers.remove(queue)

    # -- expiry ---------------------------------------------------------------------------

    async def _expiry_loop(self) -> None:
        interval = max(self.member_timeout_s / 3.0, 0.05)
        while True:
            await asyncio.sleep(interval)
            cutoff = time.monotonic() - self.member_timeout_s
            expired = [m for m, info in self._members.items()
                       if info["last_ping"] < cutoff]
            changed = False
            for m in expired:
                logger.warning("control plane: member %s heartbeat-expired", m)
                changed |= self._remove_member(m)
            if changed:
                self._bump_and_broadcast()

    # -- lifecycle ------------------------------------------------------------------------

    def _handler(self) -> grpc.GenericRpcHandler:
        rpc = {}
        for name, (req_cls, reply_cls) in UNARY_METHODS.items():
            rpc[name] = grpc.unary_unary_rpc_method_handler(
                getattr(self, name), request_deserializer=req_cls.FromString,
                response_serializer=reply_cls.SerializeToString)
        rpc["Watch"] = grpc.unary_stream_rpc_method_handler(
            self.Watch, request_deserializer=pb.WatchRequest.FromString,
            response_serializer=pb.ClusterState.SerializeToString)
        return grpc.method_handlers_generic_handler(SERVICE, rpc)

    async def start(self) -> int:
        from surge_tpu.remote.security import add_secure_port

        self._server = grpc.aio.server()
        self._server.add_generic_rpc_handlers((self._handler(),))
        self.bound_port = add_secure_port(
            self._server, f"{self._host}:{self._port}", self._config)
        await self._server.start()
        self._expiry_task = asyncio.ensure_future(self._expiry_loop())
        return self.bound_port

    async def stop(self, grace: float = 1.0) -> None:
        if self._expiry_task is not None:
            self._expiry_task.cancel()
            self._expiry_task = None
        if self._server is not None:
            await self._server.stop(grace)
            self._server = None

    def serve_background(self) -> int:
        """Dedicated thread + loop (standalone seed process or sync tests)."""
        import threading

        ready = threading.Event()
        port_box = {}

        def run() -> None:
            loop = asyncio.new_event_loop()
            asyncio.set_event_loop(loop)
            self._thread_loop = loop
            port_box["port"] = loop.run_until_complete(self.start())
            ready.set()
            loop.run_forever()
            loop.run_until_complete(self.stop())
            loop.close()

        self._thread = threading.Thread(target=run, name="surge-control-plane",
                                        daemon=True)
        self._thread.start()
        ready.wait(10.0)
        return port_box["port"]

    def shutdown_background(self) -> None:
        if self._thread_loop is not None:
            self._thread_loop.call_soon_threadsafe(self._thread_loop.stop)
        if self._thread is not None:
            self._thread.join(5.0)
            self._thread = None


# -- client-side remote mirrors ----------------------------------------------------------


class RemotePartitionTracker(PartitionTracker):
    """Tracker mirror: ``update`` forwards to the control plane; local state (and
    listener broadcasts) change only when the watch stream applies a new epoch."""

    def __init__(self, client: "ControlPlaneClient") -> None:
        super().__init__()
        self._client = client

    def update(self, new: Assignments) -> AssignmentChanges:
        self._client.push_assignments(new)
        return AssignmentChanges(revoked={}, added={})

    def _apply(self, new: Assignments) -> None:
        if new != self.assignments.assignments:
            super().update(new)


class RemoteClusterMembership(ClusterMembership):
    """Membership mirror: join/leave forward to the control plane."""

    def __init__(self, client: "ControlPlaneClient") -> None:
        super().__init__()
        self._client = client

    def join(self, member: HostPort) -> None:
        self._client.request_join()

    def leave(self, member: HostPort) -> None:
        self._client.request_leave()

    def _apply(self, members: List[HostPort]) -> None:
        if sorted(members) != self._members:
            self._members = sorted(members)
            self._broadcast()


class RemoteExternalShardAllocation(ExternalShardAllocation):
    """Allocation mirror: updates are epoch-CAS'd through the control plane."""

    def __init__(self, client: "ControlPlaneClient") -> None:
        super().__init__()
        self._client = client

    def update_shard_locations(self, mapping: Mapping[int, HostPort]) -> None:
        self._client.push_allocations(mapping)

    def deallocate_member(self, member: HostPort) -> None:
        pass  # the server prunes a departed member's allocations itself

    def _apply(self, locations: Dict[int, HostPort]) -> None:
        if locations != self._locations:
            self._locations = dict(locations)
            self._broadcast()


class ControlPlaneClient:
    """One node's connection to the control plane.

    Owns the remote mirrors (``tracker``/``membership``/``allocation``) that the
    engine and router are constructed with, a watch task applying epoch-ordered
    state, and a heartbeat task. ``on_peers`` fires with ``{HostPort: target}`` on
    every membership application so the caller can (re)point its
    :class:`GrpcRemoteDeliver` address book.
    """

    def __init__(self, target: str, local: HostPort, transport_target: str = "",
                 config: Config | None = None,
                 on_peers: Callable[[Dict[HostPort, str]], None] | None = None,
                 ping_interval_s: float | None = None) -> None:
        self.target = target
        self.local = local
        self.transport_target = transport_target
        self.config = config or default_config()
        self.on_peers = on_peers
        self.applied_epoch = 0
        self.tracker = RemotePartitionTracker(self)
        self.membership = RemoteClusterMembership(self)
        self.allocation = RemoteExternalShardAllocation(self)
        self._ping_interval_s = (
            ping_interval_s if ping_interval_s is not None
            else self.config.get_seconds("surge.control-plane.ping-interval-ms", 500))
        self._channel: Optional[grpc.aio.Channel] = None
        self._calls: Dict[str, object] = {}
        self._watch_call = None
        self._tasks: List[asyncio.Task] = []
        self._inflight: set = set()

    def _member_msg(self) -> pb.Member:
        return pb.Member(host=self.local.host, port=self.local.port,
                         transport_target=self.transport_target)

    async def start(self) -> None:
        from surge_tpu.remote.security import secure_channel

        self._channel = secure_channel(self.target, self.config)
        for name, (req_cls, reply_cls) in UNARY_METHODS.items():
            self._calls[name] = self._channel.unary_unary(
                f"/{SERVICE}/{name}",
                request_serializer=req_cls.SerializeToString,
                response_deserializer=reply_cls.FromString)
        self._watch_call = self._channel.unary_stream(
            f"/{SERVICE}/Watch",
            request_serializer=pb.WatchRequest.SerializeToString,
            response_deserializer=pb.ClusterState.FromString)
        state = await self._calls["Join"](pb.JoinRequest(member=self._member_msg()))
        self._apply_state(state, force=True)
        self._tasks = [asyncio.ensure_future(self._watch_loop()),
                       asyncio.ensure_future(self._ping_loop())]

    async def stop(self) -> None:
        for t in self._tasks:
            t.cancel()
        self._tasks = []
        if self._calls:
            try:
                await self._calls["Leave"](
                    pb.MemberRequest(member=self._member_msg()), timeout=2.0)
            except Exception:  # noqa: BLE001 — seed may already be gone
                pass
        if self._channel is not None:
            await self._channel.close()
            self._channel = None

    # -- state application ----------------------------------------------------------------

    def _apply_state(self, state: pb.ClusterState, force: bool = False) -> None:
        """Apply an epoch-ordered update. ``force`` accepts a LOWER epoch — used for
        Join responses, where a lower epoch means the seed restarted with fresh
        state (its epochs restarted too); without force the mirrors would discard
        every post-restart update until the new epoch caught up."""
        if state.epoch <= self.applied_epoch and not force:
            return
        self.applied_epoch = state.epoch
        members = [_hp(m) for m in state.members]
        targets = {_hp(m): (m.transport_target or str(_hp(m)))
                   for m in state.members}
        if self.on_peers is not None:
            try:
                self.on_peers(targets)
            except Exception:  # noqa: BLE001
                logger.exception("on_peers callback failed")
        # order matters: peers/members first so leader checks and remote routing
        # see the new topology before assignment/allocation listeners fire
        self.membership._apply(members)
        self.tracker._apply({
            _hp_str(host): list(pl.partitions)
            for host, pl in state.assignments.items()})
        self.allocation._apply({
            p: _hp_str(t) for p, t in state.shard_locations.items()})

    async def _watch_loop(self) -> None:
        while True:
            try:
                stream = self._watch_call(pb.WatchRequest(
                    member=self._member_msg(), from_epoch=self.applied_epoch))
                async for state in stream:
                    self._apply_state(state)
            except asyncio.CancelledError:
                raise
            except Exception as exc:  # noqa: BLE001 — reconnect after seed restart
                logger.warning("control-plane watch dropped (%r); retrying", exc)
                await asyncio.sleep(0.5)

    async def _ping_loop(self) -> None:
        while True:
            await asyncio.sleep(self._ping_interval_s)
            try:
                ack = await self._calls["Ping"](
                    pb.MemberRequest(member=self._member_msg()), timeout=2.0)
                if not ack.ok:  # expired server-side (or seed restarted): re-join
                    state = await self._calls["Join"](
                        pb.JoinRequest(member=self._member_msg()))
                    self._apply_state(state, force=True)
            except asyncio.CancelledError:
                raise
            except Exception as exc:  # noqa: BLE001
                logger.warning("control-plane ping failed: %r", exc)

    # -- mutator forwarding (fire-and-forget; convergence via the watch stream) -----------

    def _spawn(self, coro, what: str = "control-plane rpc") -> None:
        async def guarded() -> None:
            # transient seed unavailability must not silently drop a mutation —
            # retry a few times; a still-failing update is logged loudly and
            # recovered by the next epoch-driven listener re-fire
            for attempt in range(3):
                try:
                    await coro()
                    return
                except asyncio.CancelledError:
                    raise
                except Exception as exc:  # noqa: BLE001
                    logger.warning("%s failed (attempt %d/3): %r",
                                   what, attempt + 1, exc)
                    await asyncio.sleep(0.5)
            logger.error("%s dropped after 3 attempts", what)

        task = asyncio.ensure_future(guarded())
        self._inflight.add(task)
        task.add_done_callback(self._inflight.discard)

    def push_assignments(self, new: Assignments) -> None:
        async def send() -> None:
            req = pb.UpdateAssignmentsRequest(member=self._member_msg(),
                                              observed_epoch=self.applied_epoch)
            for hp, parts in new.items():
                req.assignments[str(hp)].partitions.extend(parts)
            ack = await self._calls["UpdateAssignments"](req)
            if not ack.ok:
                # auto-balanced server or CAS conflict: the authoritative state
                # arrives on the watch stream
                logger.info("assignment update rejected: %s", ack.error)

        self._spawn(send, "assignment update")

    def push_allocations(self, mapping: Mapping[int, HostPort]) -> None:
        async def send() -> None:
            req = pb.AllocateRequest(member=self._member_msg(),
                                     observed_epoch=self.applied_epoch)
            for p, hp in mapping.items():
                req.locations[p] = str(hp)
            ack = await self._calls["UpdateShardLocations"](req)
            if not ack.ok:
                # CAS conflict or leadership change: the newer epoch arrives on the
                # watch stream and re-triggers the allocation listeners
                logger.info("allocation update rejected: %s", ack.error)

        self._spawn(send, "allocation update")

    async def advertise(self, transport_target: str) -> None:
        """Update this member's advertised transport target by re-joining.

        Lets a node join the control plane (so assignments — and therefore a
        partition-scoped restore — happen first) and publish its routable
        address only once its transport server is actually bound."""
        self.transport_target = transport_target
        state = await self._calls["Join"](pb.JoinRequest(member=self._member_msg()))
        self._apply_state(state, force=True)

    def request_join(self) -> None:
        if not self._calls:  # pre-start (router.start's membership.join); the
            return           # client's own start() performs the Join
        async def join() -> None:
            state = await self._calls["Join"](pb.JoinRequest(member=self._member_msg()))
            self._apply_state(state, force=True)

        self._spawn(join, "join")

    def request_leave(self) -> None:
        if not self._calls:
            return
        self._spawn(lambda: self._calls["Leave"](
            pb.MemberRequest(member=self._member_msg())), "leave")
