"""EngineNode — one process's full node assembly.

Composes what a real deployment runs per process (the reference's "application
embedding a Surge engine" unit, SurgeMessagePipeline.scala:33-87 + remoting):

- a :class:`GrpcLogTransport` (or any provided log) to the shared log broker,
- the engine wired to **control-plane mirrors** (tracker/membership/allocation)
  so partition assignment metadata flows through the ControlPlane service,
- a :class:`NodeTransportServer` accepting forwarded envelopes, and
- a :class:`GrpcRemoteDeliver` whose address book tracks the control plane's
  member list (each member advertises its transport target on Join).

Start order matters and is encapsulated here: the control-plane client joins
FIRST (with no transport target yet) so assignments exist before the engine
starts — the engine's cold restore is then scoped to this node's partitions
(SURVEY.md §3.3 per-task restore) and the router creates exactly the owned
regions. Only after the transport server binds does the node advertise its
routable address; until then peers cannot forward to it, which mirrors the
reference's rebalance → restore → serve sequence (a joining node's partitions
are unavailable while its state store rebuilds)."""

from __future__ import annotations

from typing import Optional

from surge_tpu.config import Config, default_config
from surge_tpu.engine.partition import HostPort
from surge_tpu.engine.pipeline import SurgeEngine
from surge_tpu.remote.control_plane import ControlPlaneClient
from surge_tpu.remote.transport import GrpcRemoteDeliver, NodeTransportServer


class EngineNode:
    """One engine process participating in a cluster."""

    def __init__(self, logic, control_plane_target: str, log,
                 node_name: str, config: Config | None = None,
                 advertise_host: str = "127.0.0.1",
                 cluster_sharding: bool = False, tracer=None) -> None:
        self.config = config or default_config()
        if cluster_sharding:
            self.config = self.config.with_overrides({
                "surge.feature-flags.experimental.enable-cluster-sharding": True})
        # logical node identity (stable across transport-port changes); the actual
        # gRPC target is advertised separately via the control plane
        self.local = HostPort(node_name, 0)
        self.client = ControlPlaneClient(control_plane_target, self.local,
                                         config=self.config,
                                         on_peers=self._on_peers)
        self.deliver = GrpcRemoteDeliver(logic, config=self.config,
                                         tracer=tracer)
        if tracer is not None and hasattr(log, "tracer"):
            # broker-hop spans: a GrpcLogTransport (or LogServer-shaped peer)
            # exposes a settable tracer; other log impls simply lack the attr
            log.tracer = tracer
        self.engine = SurgeEngine(
            logic, log=log, config=self.config, local_host=self.local,
            tracker=self.client.tracker, remote_deliver=self.deliver,
            membership=self.client.membership,
            shard_allocation=self.client.allocation, tracer=tracer)
        self.server = NodeTransportServer(self.engine)
        self._advertise_host = advertise_host

    def _on_peers(self, targets) -> None:
        for member, target in targets.items():
            if member != self.local and target:
                self.deliver.set_address(member, target)

    async def start(self) -> None:
        await self.client.start()  # join: assignments arrive before restore
        await self.engine.start()  # partition-scoped restore + owned regions
        port = await self.server.start()
        await self.client.advertise(f"{self._advertise_host}:{port}")

    async def stop(self) -> None:
        await self.client.stop()  # leave first so peers stop routing to us
        await self.server.stop()
        await self.engine.stop()
        await self.deliver.close()

    def aggregate_for(self, aggregate_id: str):
        return self.engine.aggregate_for(aggregate_id)
