"""Transport security for the gRPC surfaces — the KafkaSecurityConfiguration analog.

The reference secures its data plane with Kafka SASL/SSL properties derived from
config (modules/common/.../KafkaSecurityConfiguration.scala); surge_tpu's inter-node
and gateway planes are gRPC, so the equivalent is TLS (optionally mutual) driven by
the same layered config:

    surge.grpc.tls.enabled        (false)  — plaintext by default
    surge.grpc.tls.cert-file               — this process's certificate chain (PEM)
    surge.grpc.tls.key-file                — this process's private key (PEM)
    surge.grpc.tls.root-ca-file            — CA bundle used to verify peers
    surge.grpc.tls.require-client-auth (false) — servers demand client certs (mTLS)

``add_secure_port`` / ``secure_channel`` are used by every server/client in
surge_tpu.remote, surge_tpu.multilanguage, and surge_tpu.admin; with TLS disabled
they fall back to the insecure variants, so single-process and test setups need no
certificates.
"""

from __future__ import annotations

from typing import Optional

import grpc

from surge_tpu.config import Config, default_config


def tls_enabled(config: Optional[Config]) -> bool:
    cfg = config or default_config()
    return cfg.get_bool("surge.grpc.tls.enabled", False)


def _read(path: str) -> bytes:
    with open(path, "rb") as f:
        return f.read()


def server_credentials(config: Config) -> grpc.ServerCredentials:
    cert = config.get_str("surge.grpc.tls.cert-file")
    key = config.get_str("surge.grpc.tls.key-file")
    if not cert or not key:
        raise ValueError(
            "surge.grpc.tls.enabled requires surge.grpc.tls.cert-file and "
            "surge.grpc.tls.key-file")
    root = config.get_str("surge.grpc.tls.root-ca-file")
    require_client = config.get_bool("surge.grpc.tls.require-client-auth", False)
    return grpc.ssl_server_credentials(
        [(_read(key), _read(cert))],
        root_certificates=_read(root) if root else None,
        require_client_auth=require_client)


def channel_credentials(config: Config) -> grpc.ChannelCredentials:
    root = config.get_str("surge.grpc.tls.root-ca-file")
    cert = config.get_str("surge.grpc.tls.cert-file")
    key = config.get_str("surge.grpc.tls.key-file")
    return grpc.ssl_channel_credentials(
        root_certificates=_read(root) if root else None,
        private_key=_read(key) if key else None,
        certificate_chain=_read(cert) if cert else None)


def add_secure_port(server: grpc.aio.Server, address: str,
                    config: Optional[Config]) -> int:
    """Bind ``address`` with TLS when enabled, plaintext otherwise."""
    if tls_enabled(config):
        return server.add_secure_port(address, server_credentials(config))
    return server.add_insecure_port(address)


def secure_channel(target: str, config: Optional[Config]) -> grpc.aio.Channel:
    """Open a channel with TLS when enabled, plaintext otherwise."""
    if tls_enabled(config):
        return grpc.aio.secure_channel(target, channel_credentials(config))
    return grpc.aio.insecure_channel(target)


#: sync channels own their subchannels instead of sharing the process-global
#: pool: a broker-liveness probe that finds a peer down must not poison a
#: fresh client channel to the same address with a cached TRANSIENT_FAILURE
#: for the backoff window (failover clients reconnect to rebound/promoted
#: brokers immediately, not after the pooled subchannel's backoff elapses)
_SYNC_CHANNEL_OPTIONS = (("grpc.use_local_subchannel_pool", 1),)


def secure_sync_channel(target: str, config: Optional[Config]) -> grpc.Channel:
    """Synchronous-channel variant of :func:`secure_channel` (blocking clients)."""
    if tls_enabled(config):
        return grpc.secure_channel(target, channel_credentials(config),
                                   options=_SYNC_CHANNEL_OPTIONS)
    return grpc.insecure_channel(target, options=_SYNC_CHANNEL_OPTIONS)
