"""gRPC node transport: server (deliver into the local engine) + client (RemoteDeliver).

See package docstring. Service glue is hand-written like the multilanguage bridge
(grpcio-tools absent); the generated message classes live in ``node_transport_pb2``.
"""

from __future__ import annotations

import asyncio
from typing import Dict, Optional

import grpc

from surge_tpu.common import fail_future, logger, resolve_future
from surge_tpu.engine.entity import (
    ApplyEvents,
    CommandFailure,
    CommandRejected,
    CommandSuccess,
    Envelope,
    GetState,
    ProcessMessage,
)
from surge_tpu.engine.model import RejectedCommand
from surge_tpu.engine.partition import HostPort
from surge_tpu.multilanguage.service import generic_handler
from surge_tpu.remote import node_transport_pb2 as pb
from surge_tpu.serialization import SerializedMessage

SERVICE = "surge_tpu.node.NodeTransport"
METHODS = {"Deliver": (pb.DeliverRequest, pb.DeliverReply)}


class NodeTransportServer:
    """Receives forwarded envelopes and delivers them into the local engine's router
    (the remote PartitionRegion role)."""

    def __init__(self, engine, host: str = "127.0.0.1", port: int = 0) -> None:
        self.engine = engine
        self._host = host
        self._port = port
        self._server: Optional[grpc.aio.Server] = None
        self.bound_port: Optional[int] = None
        self._config = getattr(engine, "config", None)

    async def Deliver(self, request: pb.DeliverRequest, context) -> pb.DeliverReply:
        logic = self.engine.logic
        kind = request.WhichOneof("kind")
        if kind == "command":
            if logic.command_format is None:
                return pb.DeliverReply(outcome="failure",
                                       error="node has no command_format configured")
            message = ProcessMessage(
                logic.command_format.read_command(request.command))
        elif kind == "get_state":
            message = GetState()
        elif kind == "apply_events":
            message = ApplyEvents([
                logic.event_format.read_event(
                    SerializedMessage(key=request.aggregate_id, value=e))
                for e in request.apply_events.events])
        else:
            return pb.DeliverReply(outcome="failure", error=f"unknown kind {kind!r}")

        fut: "asyncio.Future" = asyncio.get_running_loop().create_future()
        env = Envelope(message=message, reply=fut, headers=dict(request.headers))
        span = None
        tracer = getattr(self.engine, "tracer", None)
        if tracer is not None:
            # receive-side transport span: child of the sender's forward span
            # via the traceparent riding the request headers
            from surge_tpu.tracing import inject_context

            span = tracer.start_span("transport.receive", headers=env.headers)
            span.set_attribute("aggregate_id", request.aggregate_id)
            span.set_attribute("partition", request.partition)
            span.set_attribute("kind", kind)
            env.headers = inject_context(span.context, env.headers)
        try:
            # the sender already resolved ownership to this node: deliver into the
            # addressed partition's local region (no re-route — see deliver_local)
            self.engine.router.deliver_local(request.partition, request.aggregate_id,
                                             env)
            result = await fut
        except Exception as exc:  # noqa: BLE001 — routing errors surface as failure
            if span is not None:
                span.record_exception(exc)
            return pb.DeliverReply(outcome="failure", error=repr(exc))
        finally:
            if span is not None:
                span.finish()

        if isinstance(message, GetState):
            if result is None:
                return pb.DeliverReply(outcome="no_state")
            return pb.DeliverReply(
                outcome="state", state=logic.state_format.write_state(result).value)
        if isinstance(result, CommandSuccess):
            if result.state is None:
                return pb.DeliverReply(outcome="success", has_state=False)
            written = logic.state_format.write_state(result.state).value
            return pb.DeliverReply(outcome="success", state=written or b"",
                                   has_state=True)
        if isinstance(result, CommandRejected):
            return pb.DeliverReply(outcome="rejected", error=str(result.reason))
        if isinstance(result, CommandFailure):
            return pb.DeliverReply(outcome="failure", error=repr(result.error))
        return pb.DeliverReply(outcome="failure", error=f"unexpected reply {result!r}")

    async def start(self) -> int:
        from surge_tpu.remote.security import add_secure_port

        self._server = grpc.aio.server()
        self._server.add_generic_rpc_handlers(
            (generic_handler(SERVICE, METHODS, self),))
        self.bound_port = add_secure_port(
            self._server, f"{self._host}:{self._port}", self._config)
        await self._server.start()
        return self.bound_port

    async def stop(self, grace: float = 1.0) -> None:
        if self._server is not None:
            await self._server.stop(grace)
            self._server = None


class GrpcRemoteDeliver:
    """The router's ``remote_deliver`` hook over gRPC: resolves the owner's channel
    from an address book and forwards the envelope, mapping the reply back onto the
    caller's future (ask semantics preserved across the wire)."""

    def __init__(self, logic, addresses: Dict[HostPort, str] | None = None,
                 timeout_s: float = 30.0, config=None, tracer=None) -> None:
        self.logic = logic
        self.config = config  # TLS when surge.grpc.tls.enabled (remote/security.py)
        self.tracer = tracer  # forward-hop spans (None = zero overhead)
        # HostPort -> "host:port" gRPC target; defaults to the HostPort itself
        self.addresses = dict(addresses or {})
        self.timeout_s = timeout_s
        self._channels: Dict[HostPort, grpc.aio.Channel] = {}
        self._calls: Dict[HostPort, object] = {}
        # strong refs: the loop only weakly references tasks, and a GC'd forward
        # task would leave the caller's reply future silently unresolved
        self._inflight: set = set()
        # per-aggregate forward chains: concurrent unary RPCs would otherwise race
        # and reorder same-aggregate envelopes, breaking the per-aggregate FIFO
        # guarantee local delivery (and the remoting channel it replaces) provides
        self._chains: Dict[tuple, asyncio.Task] = {}

    def set_address(self, node: HostPort, target: str) -> None:
        """(Re)point a node at a gRPC target; drops any cached channel so a node
        restarting on a new port takes effect immediately."""
        if self.addresses.get(node) == target:
            return
        self.addresses[node] = target
        self._calls.pop(node, None)
        channel = self._channels.pop(node, None)
        if channel is not None:
            task = asyncio.ensure_future(channel.close())
            self._inflight.add(task)
            task.add_done_callback(self._inflight.discard)

    def _call_for(self, node: HostPort):
        call = self._calls.get(node)
        if call is None:
            from surge_tpu.multilanguage.service import unary_callables
            from surge_tpu.remote.security import secure_channel

            target = self.addresses.get(node, f"{node.host}:{node.port}")
            channel = secure_channel(target, self.config)
            self._channels[node] = channel
            call = unary_callables(channel, SERVICE, METHODS)["Deliver"]
            self._calls[node] = call
        return call

    def __call__(self, owner: HostPort, partition: int, aggregate_id: str,
                 env: Envelope) -> None:
        span = None
        if self.tracer is not None:
            # sender-side transport span: child of the router span, open until
            # the remote reply resolves (the cross-node hop's wall time)
            from surge_tpu.tracing import inject_context

            span = self.tracer.start_span("remote.deliver", headers=env.headers)
            span.set_attribute("aggregate_id", aggregate_id)
            span.set_attribute("partition", partition)
            span.set_attribute("owner", str(owner))
            env.headers = inject_context(span.context, env.headers)
        try:
            request = self._encode(partition, aggregate_id, env)
        except Exception as exc:  # noqa: BLE001 — unserializable command etc.
            if span is not None:
                span.record_exception(exc)
                span.finish()
            fail_future(env.reply, exc)
            return
        # chain after the aggregate's previous in-flight forward (FIFO per aggregate)
        key = (owner, aggregate_id)
        prev = self._chains.get(key)
        task = asyncio.ensure_future(
            self._forward_after(prev, owner, request, env, span))
        self._chains[key] = task
        task.add_done_callback(lambda t, k=key: self._chain_done(k, t))
        self._inflight.add(task)
        task.add_done_callback(self._inflight.discard)

    def _chain_done(self, key: tuple, task: asyncio.Task) -> None:
        if self._chains.get(key) is task:
            del self._chains[key]

    async def _forward_after(self, prev: Optional[asyncio.Task], owner: HostPort,
                             request: pb.DeliverRequest, env: Envelope,
                             span=None) -> None:
        if prev is not None:
            await asyncio.wait({prev})  # _forward never raises; outcome irrelevant
        try:
            await self._forward(owner, request, env)
        finally:
            if span is not None:
                span.finish()

    def _encode(self, partition: int, aggregate_id: str,
                env: Envelope) -> pb.DeliverRequest:
        request = pb.DeliverRequest(aggregate_id=aggregate_id, partition=partition,
                                    headers=dict(env.headers))
        msg = env.message
        if isinstance(msg, ProcessMessage):
            if self.logic.command_format is None:
                raise TypeError(
                    "cross-node send_command requires business logic with a "
                    "command_format")
            request.command = self.logic.command_format.write_command(msg.command)
        elif isinstance(msg, GetState):
            request.get_state = True
        elif isinstance(msg, ApplyEvents):
            # SetInParent selects the oneof even for zero events, so an empty
            # ApplyEvents crosses the wire as the no-op it is locally
            request.apply_events.SetInParent()
            request.apply_events.events.extend(
                self.logic.event_format.write_event(e).value for e in msg.events)
        else:
            raise TypeError(f"unroutable message {type(msg).__name__}")
        return request

    async def _forward(self, owner: HostPort, request: pb.DeliverRequest,
                       env: Envelope) -> None:
        try:
            reply: pb.DeliverReply = await self._call_for(owner)(
                request, timeout=self.timeout_s)
        except Exception as exc:  # noqa: BLE001 — connectivity errors
            logger.warning("remote deliver to %s failed: %r", owner, exc)
            fail_future(env.reply, exc)
            return
        outcome = reply.outcome
        if outcome == "no_state":
            resolve_future(env.reply, None)
        elif outcome == "state":
            resolve_future(env.reply, self.logic.state_format.read_state(reply.state))
        elif outcome == "success":
            # has_state is the discriminator; non-empty state without it keeps
            # compatibility with servers predating the field
            exists = reply.has_state or bool(reply.state)
            state = self.logic.state_format.read_state(reply.state) if exists else None
            resolve_future(env.reply, CommandSuccess(state))
        elif outcome == "rejected":
            resolve_future(env.reply, CommandRejected(RejectedCommand(reply.error)))
        else:
            resolve_future(env.reply, CommandFailure(
                RuntimeError(f"remote failure: {reply.error}")))

    async def close(self) -> None:
        for channel in self._channels.values():
            await channel.close()
        self._channels.clear()
        self._calls.clear()
