"""TPU replay engine — batched aggregate-state reconstruction (the north star).

The reference rebuilds materialized state by a Kafka Streams restore: a scalar
per-aggregate ``handleEvent`` fold while scanning the log (SURVEY.md §3.3). Here that
fold is lifted onto the TPU:

- per-event-type JAX handlers → one step function via ``lax.switch`` (tagged union),
- ``jax.vmap`` across the aggregate batch dimension B,
- ``jax.lax.scan`` across the time dimension T (time-major event columns),
- padding masked by ``type_id == PAD_TYPE_ID`` (state carried through unchanged),
- carry donation + time-chunked streaming so a log bigger than HBM folds in segments,
- optional ``jax.sharding.Mesh`` data-parallel sharding of B (embarrassingly parallel;
  XLA inserts no collectives on the hot path).
"""

from surge_tpu.replay.engine import (
    ReplayEngine,
    ReplayResult,
    ResidentWire,
    make_step_fn,
    make_batch_fold,
)
from surge_tpu.replay.ledger import ReplayLedger
from surge_tpu.replay.mixed import MixedReplay, combine_replay_specs
from surge_tpu.replay.query import (
    Aggregate,
    Predicate,
    QueryEngine,
    QueryResult,
    ScanQuery,
    StateQuery,
)
from surge_tpu.replay.resident_state import ResidentStatePlane
from surge_tpu.replay.seqpar import AssociativeFold, replay_time_sharded

__all__ = ["ReplayEngine", "ReplayResult", "ResidentWire", "MixedReplay",
           "combine_replay_specs", "AssociativeFold", "replay_time_sharded",
           "make_step_fn", "make_batch_fold", "ReplayLedger",
           "ResidentStatePlane",
           "QueryEngine", "ScanQuery", "StateQuery", "Predicate", "Aggregate",
           "QueryResult"]
