"""Synthetic replay corpora, generated columnar (no per-event Python objects).

The benchmark workload from BASELINE.md — 1M aggregates / 100M events of cold replay —
can't be generated as Python object lists (that alone would dominate wall-clock on one
core). This module builds :class:`~surge_tpu.codec.tensor.ColumnarEvents` directly with
vectorized NumPy, along with a closed-form expected final state (per-aggregate bincount
sums) so the full corpus can be *verified* without ever folding it scalar-side.

The scalar CPU fold baseline (what the reference does during a Kafka Streams restore,
SURVEY.md §3.3) is measured on a decoded sample and extrapolated.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from surge_tpu.codec.tensor import ColumnarEvents
from surge_tpu.models import counter


@dataclass
class CounterCorpus:
    """A ragged counter-event corpus plus its closed-form expected fold result."""

    events: ColumnarEvents  # aggregate-sorted (time order within aggregate)
    lengths: np.ndarray  # [B] int64 events per aggregate
    expected_count: np.ndarray  # [B] int64: sum(inc) - sum(dec) per aggregate
    expected_version: np.ndarray  # [B] int32: last non-noop sequence number

    @property
    def num_aggregates(self) -> int:
        return int(self.lengths.shape[0])

    @property
    def num_events(self) -> int:
        return int(self.events.num_events)


def ragged_lengths(num_aggregates: int, num_events: int, rng: np.random.Generator,
                   spread: float = 0.6) -> np.ndarray:
    """Ragged per-aggregate log lengths summing exactly to ``num_events``.

    Lognormal-shaped (most aggregates short, a long tail), mirroring real event-sourced
    populations; ``spread`` is the lognormal sigma.
    """
    if num_aggregates <= 0:
        return np.zeros(0, dtype=np.int64)
    w = rng.lognormal(mean=0.0, sigma=spread, size=num_aggregates)
    lengths = np.floor(w * (num_events / w.sum())).astype(np.int64)
    # distribute the rounding remainder one event at a time over the first aggregates
    deficit = num_events - int(lengths.sum())
    if deficit > 0:
        lengths[:deficit] += 1
    return lengths


def synth_counter_corpus(num_aggregates: int, num_events: int, seed: int = 0,
                         spread: float = 0.6,
                         sort_by_length: bool = False,
                         lengths: np.ndarray | None = None) -> CounterCorpus:
    """Counter-model corpus: Increment/Decrement/NoOp/Unserializable events.

    Event mix: 45% inc (by 1..3), 35% dec (by 1..2), 15% noop, 5% unserializable —
    exercising all four tensor-path event types of the TestBoundedContext parity fixture
    (reference TestBoundedContext.scala:17-82). ``sort_by_length`` orders aggregates by
    log length (what the replay engine's bucketing does anyway) so fixed-size B-chunks
    have homogeneous T and minimal padding. An explicit ``lengths`` array overrides the
    lognormal distribution (warm-up corpora that must hit specific window widths).
    """
    rng = np.random.default_rng(seed)
    if lengths is None:
        lengths = ragged_lengths(num_aggregates, num_events, rng, spread)
    else:
        lengths = np.asarray(lengths, dtype=np.int64)
        num_aggregates = int(lengths.shape[0])
    if sort_by_length:
        order = np.argsort(lengths, kind="stable")
        lengths = lengths[order]
    n = int(lengths.sum())

    starts = np.zeros(num_aggregates + 1, dtype=np.int64)
    np.cumsum(lengths, out=starts[1:])
    agg_idx = np.repeat(np.arange(num_aggregates, dtype=np.int32), lengths)
    # within-aggregate ordinal, 1-based — this corpus stamps sequence_number as the
    # event's position in its aggregate's log, so the column is declared
    # device-derivable ("ordinal") and never stored or transferred (codec/wire.py)
    seq = (np.arange(n, dtype=np.int64) - starts[agg_idx] + 1).astype(np.int32)

    # threshold arithmetic instead of rng.choice(p=...): choice draws float64
    # per event (~5 s at 100M); a u16 draw + three comparisons is ~2 s. Relies
    # on INCREMENTED..UNSERIALIZABLE being 0..3 (counter.py:154).
    assert (counter.INCREMENTED, counter.DECREMENTED, counter.NOOP,
            counter.UNSERIALIZABLE) == (0, 1, 2, 3)
    draw = rng.integers(0, 10_000, size=n, dtype=np.uint16)
    type_ids = ((draw >= 4500).astype(np.int32)      # 45% inc
                + (draw >= 8000) + (draw >= 9500))   # 35% dec, 15% noop, 5% unser
    inc = np.where(type_ids == counter.INCREMENTED,
                   rng.integers(1, 4, size=n, dtype=np.int32), 0).astype(np.int32)
    dec = np.where(type_ids == counter.DECREMENTED,
                   rng.integers(1, 3, size=n, dtype=np.int32), 0).astype(np.int32)

    events = ColumnarEvents(
        num_aggregates=num_aggregates, agg_idx=agg_idx, type_ids=type_ids,
        cols={"increment_by": inc, "decrement_by": dec},
        derived_cols={"sequence_number": "ordinal"})

    # per-aggregate sums via segment reduceat (integer, one pass) — weighted
    # bincount converts through float64 and costs ~6 s/column at 100M.
    # reduceat over non-empty starts reduces each segment exactly (empty
    # segments in between have zero width and are scattered separately).
    nonempty = lengths > 0
    expected_count = np.zeros(num_aggregates, dtype=np.int64)
    expected_version = np.zeros(num_aggregates, dtype=np.int32)
    if n and nonempty.any():
        idx = starts[:-1][nonempty]
        expected_count[nonempty] = (
            np.add.reduceat(inc, idx, dtype=np.int64)
            - np.add.reduceat(dec, idx, dtype=np.int64))
        # version = sequence number of the last event whose handler writes
        # version (inc/dec/unserializable); NoOp carries it (counter.py)
        seq_masked = np.where(type_ids != counter.NOOP, seq, 0)
        expected_version[nonempty] = np.maximum.reduceat(
            seq_masked, idx).astype(np.int32)

    return CounterCorpus(events=events, lengths=lengths,
                         expected_count=expected_count,
                         expected_version=expected_version)


def decode_sample(corpus: CounterCorpus, indices) -> list[list]:
    """Materialize the logs at ``indices`` as Python event objects — input for the
    scalar CPU fold baseline (generously excludes deserialization cost)."""
    ev = corpus.events
    starts = np.zeros(corpus.num_aggregates + 1, dtype=np.int64)
    np.cumsum(corpus.lengths, out=starts[1:])
    ctors = {
        counter.INCREMENTED: lambda a, i, d, s: counter.CountIncremented(a, int(i), int(s)),
        counter.DECREMENTED: lambda a, i, d, s: counter.CountDecremented(a, int(d), int(s)),
        counter.NOOP: lambda a, i, d, s: counter.NoOpEvent(a, int(s)),
        counter.UNSERIALIZABLE: lambda a, i, d, s: counter.UnserializableEvent(a, int(s), ""),
    }
    inc, dec = ev.cols["increment_by"], ev.cols["decrement_by"]
    logs = []
    for b in indices:
        lo, hi = int(starts[b]), int(starts[b + 1])
        agg = f"agg-{b}"
        # sequence_number is a derived ordinal column: position within the log + 1
        logs.append([ctors[int(ev.type_ids[k])](agg, inc[k], dec[k], k - lo + 1)
                     for k in range(lo, hi)])
    return logs


def sample_indices(corpus: CounterCorpus, target_events: int) -> np.ndarray:
    """Stratified aggregate sample (every k-th, so length-sorted corpora stay
    representative) totaling roughly ``target_events`` events."""
    b = corpus.num_aggregates
    total = corpus.num_events
    if total <= target_events:
        return np.arange(b)
    k = max(int(np.ceil(total / target_events)), 1)
    return np.arange(0, b, k)
