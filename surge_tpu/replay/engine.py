"""Core batched fold: vmap(switch-step) scanned over time-major event columns.

Scale discipline (SURVEY.md §7 hard-part 2, BASELINE.md 1M-aggregate/100M-event target):

- **B-chunking**: ``surge.replay.batch-size`` bounds the aggregates resident on device at
  once; larger batches stream through in fixed-size chunks so HBM usage is constant and
  one compiled program serves every chunk.
- **T-chunking**: ``surge.replay.time-chunk`` bounds the scanned window; tail windows are
  padded to full width (padding is masked inside the step), again pinning compiled shapes.
- **Donation safety**: caller-visible carries are always copied into fresh padded host
  buffers before entering the donated jit, so external arrays are never consumed.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field as dc_field
from typing import Any, Callable, Iterable, Mapping, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from surge_tpu.codec.tensor import (
    ColumnarEvents,
    EncodedEvents,
    bucket_lengths,
    columnar_to_batch,
    encode_states,
)
from surge_tpu.codec.wire import WireFormat
from surge_tpu.config import Config, default_config
from surge_tpu.engine.model import ReplaySpec, StateTree


def make_step_fn(spec: ReplaySpec, dispatch: str = "switch"
                 ) -> Callable[[StateTree, Mapping[str, Any]], StateTree]:
    """One-event step for a single aggregate: dispatch on type_id, mask padding.

    The returned function is scalar over the batch dim (engine vmaps it). Any type_id
    outside ``[0, num_types)`` — padding (-1) or corrupt positive ids — carries state
    through unchanged rather than dispatching to an arbitrary handler.

    ``dispatch`` picks the lowering:

    - ``"switch"`` — ``lax.switch`` on the (clipped) type id; under ``vmap``
      XLA turns this into predicated branches.
    - ``"select"`` — branchless: EVERY handler runs on every slot and results
      mask-combine with ``where``. More FLOPs but pure VPU data flow with no
      per-branch control overhead; event handlers are a few scalar ops each,
      so on TPU the extra arithmetic is usually cheaper than the branch
      machinery (``surge.replay.dispatch`` selects it engine-wide).
    """
    num_types = spec.registry.num_event_types
    handlers = spec.handlers.ordered(num_types)
    state_fields = spec.registry.state.field_names

    def normalize(new: StateTree, old: StateTree) -> StateTree:
        # handlers may return partial dicts; missing columns carry through, and dtypes
        # are pinned to the schema so the scan carry shape is stable
        out = {}
        for name in state_fields:
            v = new.get(name, old[name])
            out[name] = jnp.asarray(v, dtype=old[name].dtype)
        return out

    if dispatch == "select":
        def step(state: StateTree, event: Mapping[str, Any]) -> StateTree:
            tid = event["type_id"]
            fields = {k: v for k, v in event.items() if k != "type_id"}
            out = state
            for t, h in enumerate(handlers):
                new = normalize(h(state, fields), state)
                hit = tid == t
                out = {k: jnp.where(hit, new[k], out[k]) for k in out}
            return out

        return step
    if dispatch != "switch":
        raise ValueError(f"unknown dispatch {dispatch!r} (switch|select)")

    def step(state: StateTree, event: Mapping[str, Any]) -> StateTree:
        tid = event["type_id"]
        branch = jnp.clip(tid, 0, num_types - 1)
        fields = {k: v for k, v in event.items() if k != "type_id"}
        wrapped = [
            (lambda h: lambda s: normalize(h(s, fields), s))(h) for h in handlers
        ]
        new_state = jax.lax.switch(branch, wrapped, state)
        is_real = (tid >= 0) & (tid < num_types)
        return {k: jnp.where(is_real, new_state[k], state[k]) for k in state}

    return step


def make_batch_fold(spec: ReplaySpec, *, unroll: int = 1, dispatch: str = "switch"):
    """Batched fold: ``(carry {name:[B]}, events {col:[T,B]}) -> carry``.

    The per-aggregate fold of CommandModels.scala:20-21 / PersistentActor's applyEvents,
    vectorized: ``lax.scan`` over T of ``vmap``-over-B of the switch step. jit-compiled by
    the caller (ReplayEngine) with carry donation.
    """
    step = make_step_fn(spec, dispatch)
    vstep = jax.vmap(step, in_axes=(0, 0))

    def fold(carry: StateTree, events: Mapping[str, jnp.ndarray]) -> StateTree:
        def scan_body(c, ev_t):
            return vstep(c, ev_t), None

        out, _ = jax.lax.scan(scan_body, carry, events, unroll=unroll)
        return out

    return fold


@dataclass
class ResidentCorpus:
    """A corpus uploaded once to the device for gather-based replay."""

    derived_key: dict
    flat_wire: Any  # packed u8 [N, nbytes] on device (word-expanded per tile)
    flat_side: dict  # {name: [N]} on device
    starts: np.ndarray  # i32 [B] (length-sorted order, host copy for planning)
    lengths: np.ndarray  # i32 [B]
    perm: Optional[np.ndarray]  # sorted-rank -> original index (None = identity)
    starts_dev: Any  # i32 [b_pad] on device
    lens_dev: Any  # i32 [b_pad] on device
    b_pad: int  # lane count padded to the dispatch batch
    num_events: int
    wire_bytes: int  # bytes actually shipped to the device
    upload_s: float
    #: per-corpus device caches (tile plan, dense tile buffers, worklists) —
    #: populated lazily by the engine, keyed by plan geometry
    cache: dict = dc_field(default_factory=dict)


#: minimum guard rows appended past the wire corpus, so a wire packed under a
#: small tile-width cap still satisfies engines configured with a larger one
_WIRE_GUARD_MIN = 8192

#: t_base sentinel marking a dense work-list padding entry: past every real
#: lane length (lengths are int32 event counts ≪ 2^29) yet small enough that
#: start+t arithmetic stays far from int32 overflow. Ordinal arithmetic has
#: the same shape: the fold body computes ``ord_base + t_base`` (pallas) or
#: ``ord_base + t + 1`` per step (xla/assoc), and ``ord_base`` is itself an
#: int32 already-folded event count < 2^29, so a sentinel tile's derived
#: ordinals reach at most 2^30 + width — still far from int32 overflow. A
#: resumed ``ordinal_base`` ABOVE 2^30 would wrap in a sentinel tile, but
#: every sentinel slot decodes under a False mask (t ≥ lens for all lanes),
#: so the wrapped value is provably never folded; the pallas branch clamps
#: the sentinel before the add anyway so its ord_rel input stays in-range
#: (see _make_fold_body).
_NOOP_TILE_T = np.int32(1 << 29)


def _make_fold_body(spec: ReplaySpec, wire: WireFormat, width: int, bs: int,
                    unroll: int, dispatch: str, tile_backend: str):
    """The tile-interior fold shared by the flat-gather and dense-layout
    resident tiles: ``(carry {f: [bs]}, words u32 [width, bs],
    sides {n: [width, bs]}, lens [bs], ord_base [bs], t_base) -> carry``.

    Three lowerings per ``tile_backend``: the sequential XLA time scan, the
    Pallas VMEM kernel, or — when the spec ships a law-checked
    ``AssociativeFold`` — a liftless-scan tree reduction (no per-step loop
    machinery at all)."""
    batch_step = jax.vmap(make_step_fn(spec, dispatch), in_axes=(0, 0))
    pallas_scan = None
    afold = None
    if tile_backend == "pallas":
        from surge_tpu.replay.pallas_fold import make_tile_scan

        pallas_scan = make_tile_scan(spec, wire, width, bs, unroll)
    elif tile_backend == "assoc":
        from surge_tpu.replay.seqpar import ensure_validated

        afold = spec.associative
        if afold is None:
            raise ValueError(
                "surge.replay.tile-backend = assoc requires the ReplaySpec to "
                "carry an AssociativeFold (spec.associative) — this model "
                "only supports the sequential xla/pallas tile scan")
        if width & (width - 1):
            raise ValueError(
                f"assoc tile backend needs a power-of-two time width, got {width}")
        # same one-time law check as the time-sharded path: a wrong combine
        # must raise here, never silently corrupt a replay
        ensure_validated(afold, spec)

    def fold_body(carry, words, sides, lens, ord_base, t_base):
        if pallas_scan is not None:
            # the dense scan as a VMEM-resident kernel (relative time).
            # t_base is clamped before the ordinal add: a _NOOP_TILE_T
            # sentinel tile (dense-layout work-list padding) would otherwise
            # push ord_base + t_base past 2^30, wrapping int32 for resumed
            # ordinal bases above ~2^30 — harmless (every sentinel slot masks
            # to padding via the hugely-negative lens - t_base) but the clamp
            # keeps the kernel's ord_rel input in-range by construction:
            # ord_base (< 2^29) + the clamped sentinel (2^29 - 1) < 2^30.
            # Real tiles always have t_base < max lane length ≪ 2^29, so the
            # clamp is the identity for every tile that folds anything.
            t_ord = jnp.minimum(jnp.asarray(t_base, jnp.int32),
                                jnp.int32((1 << 29) - 1))
            return pallas_scan(carry, words, sides, lens - t_base,
                               ord_base + t_ord)

        if afold is not None:
            # no scan at all: lift every slot of the [width, bs] tile at once,
            # pairwise tree-reduce the summaries over TIME (combine is
            # associative but not commutative — adjacent-pair combining keeps
            # left-to-right order), then one apply. log2(width) full-vector
            # passes replace width sequential scan steps; per-tile
            # homomorphism (law 2) makes chained tiles equal chained
            # step-folds.
            ts2 = (jnp.arange(width, dtype=jnp.int32) + t_base)[:, None]
            valid = ts2 < lens[None, :]
            events = wire.decode_words(words, sides, valid,
                                       ord_base[None, :], ts2)
            s = afold.lift(events)  # padding (type_id -1) lifts to identity
            w = width
            while w > 1:
                s = afold.combine({k: v[0::2] for k, v in s.items()},
                                  {k: v[1::2] for k, v in s.items()})
                w //= 2
            out = afold.apply(carry, {k: v[0] for k, v in s.items()})
            return {k: out.get(k, carry[k]) for k in carry}

        ts = jnp.arange(width, dtype=jnp.int32) + t_base

        def body(c, xs):
            w_row, side_row, t = xs
            events = wire.decode_words(w_row, side_row, t < lens, ord_base, t)
            return batch_step(c, events), None

        out, _ = jax.lax.scan(body, carry, (words, sides, ts),
                              unroll=unroll)
        return out

    return fold_body


def _make_tile(spec: ReplaySpec, wire: WireFormat, width: int, bs: int,
               unroll: int, dispatch: str, tile_backend: str):
    """The flat-gather tile of the resident programs (single-device AND
    mesh-sharded): ``(state_slab {f: [b_pad]}, flat_wire u8 [N, nbytes],
    side_flat, starts [b_pad], lens [b_pad], ord_base [b_pad], i0, t_base)
    -> state_slab``.

    One tile folds events ``[t_base, t_base+width)`` of lanes
    ``[i0, i0+bs)``: per-lane contiguous ``dynamic_slice`` slabs out of the
    flat packed corpus (events of one aggregate are adjacent), byte→word
    expansion in-register, one transpose to time-major, the shared fold body
    (:func:`_make_fold_body`), and a contiguous write-back into the state
    slab. ``i0``/``t_base`` are traced scalars."""
    nbytes = wire.nbytes
    fold_body = _make_fold_body(spec, wire, width, bs, unroll, dispatch,
                                tile_backend)

    def tile(slab_state, flat_wire, side_flat, starts_all, lens_all,
             ord_all, i0, t_base):
        starts = jax.lax.dynamic_slice(starts_all, (i0,), (bs,))
        lens = jax.lax.dynamic_slice(lens_all, (i0,), (bs,))
        ord_base = jax.lax.dynamic_slice(ord_all, (i0,), (bs,))
        carry = {k: jax.lax.dynamic_slice(v, (i0,), (bs,))
                 for k, v in slab_state.items()}

        def slab(arr):
            # dynamic_slice clamps out-of-range starts (finished/padding
            # lanes); clamped garbage decodes under a False mask
            cut = jax.vmap(
                lambda s0: jax.lax.dynamic_slice(arr, (s0,), (width,)))
            return cut(starts + t_base).T  # [width, bs], rows contiguous

        word = jax.vmap(
            lambda s0: jax.lax.dynamic_slice(
                flat_wire, (s0, 0), (width, nbytes)))(starts + t_base)
        word = wire.expand_flat(word.reshape(bs * width, nbytes))
        words = word.reshape(bs, width).T  # [width, bs]
        sides = {name: slab(arr) for name, arr in side_flat.items()}

        out = fold_body(carry, words, sides, lens, ord_base, t_base)
        return {k: jax.lax.dynamic_update_slice(slab_state[k], out[k], (i0,))
                for k in slab_state}

    return tile


def _make_tile_dense(spec: ReplaySpec, wire: WireFormat, width: int, bs: int,
                     unroll: int, dispatch: str, tile_backend: str):
    """The dense-layout tile: ``(state_slab, dense_words u8
    [k_cap, width, bs, nbytes], dense_sides {n: [k_cap, width, bs]},
    lens_all, ord_all, i0, t_base, k) -> state_slab``.

    Reads tile ``k`` from buffers pre-gathered by :func:`_make_densify` —
    the per-lane gather (measured at HALF the whole fold's on-chip time,
    BENCH_ONCHIP.json r5) is paid once per corpus upload instead of once per
    replay pass."""
    nbytes = wire.nbytes
    fold_body = _make_fold_body(spec, wire, width, bs, unroll, dispatch,
                                tile_backend)

    def tile(slab_state, dense_words, dense_sides, lens_all, ord_all,
             i0, t_base, k):
        lens = jax.lax.dynamic_slice(lens_all, (i0,), (bs,))
        ord_base = jax.lax.dynamic_slice(ord_all, (i0,), (bs,))
        carry = {f: jax.lax.dynamic_slice(v, (i0,), (bs,))
                 for f, v in slab_state.items()}
        wslab = jax.lax.dynamic_index_in_dim(dense_words, k, 0,
                                             keepdims=False)
        words = wire.expand_flat(
            wslab.reshape(width * bs, nbytes)).reshape(width, bs)
        sides = {n: jax.lax.dynamic_index_in_dim(arr, k, 0, keepdims=False)
                 for n, arr in dense_sides.items()}
        out = fold_body(carry, words, sides, lens, ord_base, t_base)
        return {f: jax.lax.dynamic_update_slice(slab_state[f], out[f], (i0,))
                for f in slab_state}

    return tile


def _make_densify(wire: WireFormat, width: int, bs: int):
    """One-time device-side tile gather: ``(flat_wire u8 [N, nbytes],
    side_flat {n: [N]}, starts_all, i0s [k_cap], t_bases [k_cap]) ->
    (dense_words u8 [k_cap, width, bs, nbytes], dense_sides
    {n: [k_cap, width, bs]})``.

    Work-list entries past ``k_n`` gather lane 0's window — garbage the fold
    never reads (its trip count is ``k_n``)."""
    nbytes = wire.nbytes

    def densify(flat_wire, side_flat, starts_all, i0s, t_bases):
        def one(args):
            i0, tb = args
            starts = jax.lax.dynamic_slice(starts_all, (i0,), (bs,))
            rows = jax.vmap(lambda s0: jax.lax.dynamic_slice(
                flat_wire, (s0, 0), (width, nbytes)))(starts + tb)
            w = jnp.transpose(rows, (1, 0, 2))  # [width, bs, nbytes]
            sides = {n: jax.vmap(lambda s0: jax.lax.dynamic_slice(
                arr, (s0,), (width,)))(starts + tb).T
                for n, arr in side_flat.items()}
            return w, sides

        return jax.lax.map(one, (i0s, t_bases))

    return densify


def _chunked_put(arr: np.ndarray, chunk_mb: int):
    """``jax.device_put`` in row pieces of ~chunk_mb, reassembled on device
    with one concatenate; 0 (the default) keeps the single put.

    Caveat: reassembly transiently holds BOTH the pieces and the concatenated
    output in HBM (~2× the buffer); keep the knob off for corpora sized near
    device memory."""
    if chunk_mb <= 0 or arr.nbytes <= chunk_mb * 1024 * 1024:
        return jax.device_put(arr)
    row_bytes = max(arr.nbytes // max(arr.shape[0], 1), 1)
    rows = max((chunk_mb * 1024 * 1024) // row_bytes, 1)
    parts = [jax.device_put(arr[i: i + rows])
             for i in range(0, arr.shape[0], rows)]
    return jnp.concatenate(parts, axis=0)


def _apply_perm(perm: Optional[np.ndarray],
                init_carry: Mapping[str, Any] | None,
                ordinal_base: np.ndarray | None):
    """Reorder caller inputs (original aggregate order) into the wire's
    length-sorted lane order."""
    init_sorted = None
    if init_carry is not None:
        init_sorted = {k: (np.asarray(v)[perm] if perm is not None
                           else np.asarray(v))
                       for k, v in init_carry.items()}
    ord_sorted = None
    if ordinal_base is not None:
        src = np.asarray(ordinal_base)
        ord_sorted = src[perm] if perm is not None else src
    return init_sorted, ord_sorted


def _unapply_perm(perm: Optional[np.ndarray],
                  out_sorted: dict) -> dict:
    """Scatter sorted-order state columns back to the original order."""
    if perm is None:
        return out_sorted
    out = {name: np.empty_like(col) for name, col in out_sorted.items()}
    for name, col in out_sorted.items():
        out[name][perm] = col
    return out


def _bucket_len(n: int) -> int:
    """Next power of two ≥ n (min 64Ki) — the bucketed buffer length."""
    target = 1 << 16
    while target < n:
        target <<= 1
    return target


def _bucket_rows(arr: np.ndarray, pow2: bool) -> np.ndarray:
    """Zero-pad the leading axis to the next power of two (min 64Ki rows) so
    program shapes bucket; identity when bucketing is off or already sized."""
    if not pow2:
        return np.ascontiguousarray(arr)
    target = _bucket_len(arr.shape[0])
    if target == arr.shape[0]:
        return np.ascontiguousarray(arr)
    pad = [(0, target - arr.shape[0])] + [(0, 0)] * (arr.ndim - 1)
    return np.pad(arr, pad)


@dataclass
class ResidentWire:
    """The host/disk wire form of a resident corpus (pure numpy, mmap-able).

    Produced by :meth:`ReplayEngine.pack_resident`; consumed by
    :meth:`ReplayEngine.upload_resident`. Saving this next to the log segment
    makes the pack a one-time build cost: every later cold start mmaps the
    wire bytes and streams them straight onto the device."""

    derived_key: dict
    packed: np.ndarray  # u8 [N+guard, nbytes]
    side: dict  # {name: np [N+guard]}
    starts: np.ndarray  # i32 [B] (length-sorted order)
    lengths: np.ndarray  # i32 [B]
    perm: Optional[np.ndarray]  # sorted-rank -> original index
    guard: int
    num_events: int
    #: WireFormat.layout_fingerprint() of the packing schema; None only for
    #: wires saved before fingerprints existed (upload falls back to the
    #: structural byte/side checks)
    layout: Optional[dict] = None

    def save(self, root: str) -> None:
        import json
        import os

        os.makedirs(root, exist_ok=True)
        np.save(os.path.join(root, "packed.npy"), self.packed)
        np.save(os.path.join(root, "starts.npy"), self.starts)
        np.save(os.path.join(root, "lengths.npy"), self.lengths)
        if self.perm is not None:
            np.save(os.path.join(root, "perm.npy"), self.perm)
        for name, col in self.side.items():
            np.save(os.path.join(root, f"side_{name}.npy"), col)
        meta = {"derived_key": self.derived_key, "guard": self.guard,
                "num_events": self.num_events,
                "side_names": sorted(self.side),
                "has_perm": self.perm is not None,
                # layout fingerprint: a consuming engine whose schema evolved
                # must refuse the wire rather than decode misaligned bytes
                "nbytes": int(self.packed.shape[1]),
                "side_dtypes": {k: str(np.dtype(v.dtype))
                                for k, v in self.side.items()},
                "layout": self.layout}
        with open(os.path.join(root, "wire.json"), "w") as f:
            json.dump(meta, f)

    @classmethod
    def load(cls, root: str) -> "ResidentWire":
        import json
        import os

        with open(os.path.join(root, "wire.json")) as f:
            meta = json.load(f)
        mm = lambda name: np.load(os.path.join(root, name), mmap_mode="r")  # noqa: E731
        return cls(
            derived_key=dict(meta["derived_key"]),
            packed=mm("packed.npy"),
            side={name: mm(f"side_{name}.npy") for name in meta["side_names"]},
            starts=np.asarray(mm("starts.npy")),
            lengths=np.asarray(mm("lengths.npy")),
            perm=np.asarray(mm("perm.npy")) if meta["has_perm"] else None,
            guard=int(meta["guard"]), num_events=int(meta["num_events"]),
            layout=meta.get("layout"))


@dataclass
class ResidentPlan:
    """Tile schedule for one resident replay (two lane granularities)."""

    width: int
    bs_big: int
    bs_small: int
    big_i0: np.ndarray  # i32 [k_big]
    big_tb: np.ndarray  # i32 [k_big]
    small_i0: np.ndarray  # i32 [k_small]
    small_tb: np.ndarray  # i32 [k_small]

    @property
    def padded_slots(self) -> int:
        return (len(self.big_i0) * self.bs_big
                + len(self.small_i0) * self.bs_small) * self.width


@dataclass
class ReplayResult:
    """Folded states + accounting for throughput metrics."""

    states: dict[str, np.ndarray]  # {col: [B]} in the original aggregate order
    num_aggregates: int
    num_events: int
    padded_events: int  # B*T actually scanned (padding overhead indicator)
    # aggregate-id strings aligned with the state columns, when the inputs carried
    # them (segment chunks) — lets callers write states back to the keyed store
    aggregate_ids: Optional[list] = None


class ReplayEngine:
    """Drives batched replay for one model family.

    Equivalent role: the bulk-restore path of AggregateStateStoreKafkaStreams
    (common/.../kafka/streams/AggregateStateStoreKafkaStreams.scala:53-178) with
    ``replayBackend = tpu`` (BASELINE.json). Consumes ``EncodedEvents`` /
    ``ColumnarEvents`` batches (from surge_tpu.codec) and produces state columns; the
    KTable-equivalent store ingests the writeback.

    Parameters
    ----------
    spec: the model's ReplaySpec.
    config: batch size / time chunk / bucket knobs (``surge.replay.*``).
    mesh: optional ``jax.sharding.Mesh``; batch dim B is sharded over ``mesh_axis``.
    """

    def __init__(self, spec: ReplaySpec, config: Config | None = None,
                 mesh: Optional[jax.sharding.Mesh] = None,
                 mesh_axis: Optional[str] = None, unroll: int = 1,
                 profiler=None) -> None:
        self.spec = spec
        self.config = config or default_config()
        self.mesh = mesh
        # optional surge_tpu.replay.profiler.ReplayProfiler: every hook below
        # is behind one `is None` check so the default path pays nothing
        self.profiler = profiler
        # batch-axis name: explicit arg > surge.replay.mesh-axes (first entry)
        if mesh_axis is None:
            mesh_axis = (self.config.get_str("surge.replay.mesh-axes", "data")
                         .split(",")[0].strip() or "data")
        self.mesh_axis = mesh_axis
        self.donate_carry = self.config.get_bool("surge.replay.donate-carry", True)
        self.time_chunk = self.config.get_int("surge.replay.time-chunk")
        self.min_time_window = self.config.get_int("surge.replay.min-time-window", 8)
        self.sort_by_length = self.config.get_bool("surge.replay.sort-by-length", True)
        lane = self._lane_multiple()
        self.batch_size = _round_up(
            max(self.config.get_int("surge.replay.batch-size"), lane), lane)
        self.buckets = self.config.get_int_list("surge.replay.length-buckets", "64,256,1024,4096")

        self._unroll = unroll
        self._dispatch = self.config.get_str("surge.replay.dispatch", "switch")
        self._tile_backend = self.config.get_str("surge.replay.tile-backend",
                                                 "auto")
        if self._tile_backend not in ("auto", "xla", "pallas", "assoc"):
            raise ValueError(
                f"unknown surge.replay.tile-backend "
                f"{self._tile_backend!r} (auto|xla|pallas|assoc)")
        # "auto" resolves lazily (the choice is backend-dependent and reading
        # the backend here would initialize it in engine-constructing
        # processes that never dispatch)
        self._tile_backend_resolved: str | None = None
        # resident tile layout: "dense" pre-gathers every tile once per corpus
        # (the per-lane gather is half the on-chip fold cost), "flat" gathers
        # per pass, "auto" picks dense when the buffers fit the HBM budget
        self._resident_layout = self.config.get_str(
            "surge.replay.resident-layout", "auto")
        if self._resident_layout not in ("auto", "flat", "dense"):
            raise ValueError(
                f"unknown surge.replay.resident-layout "
                f"{self._resident_layout!r} (auto|flat|dense)")
        self._dense_cap_mb = self.config.get_int(
            "surge.replay.dense-cap-mb", 2048)
        # one (wire, jitted fold) per derived-column declaration the inputs carry —
        # in practice at most two: framework logs (ordinal seq) and object-test logs
        self._wire_folds: dict[frozenset, tuple[WireFormat, Any]] = {}
        # resident-corpus gather-folds, same keying
        self._resident_folds: dict[frozenset, Any] = {}
        # dense-layout programs: jitted densify gathers and dense folds
        self._densify_programs: dict = {}
        self._resident_dense_folds: dict = {}
        # on-device fresh init-slab builders per b_pad (zero host transfers)
        self._slab_programs: dict = {}
        # the two state-pull finalize programs (wide/narrow), built once per
        # engine — jax.jit's own shape cache handles differing batch sizes
        # (streamed pieces are rebuilt per call; a per-corpus cache would
        # re-jit them inside timed passes)
        self._finalize_programs: dict = {}
        # distinct (fold-variant, window-shape) signatures — every entry corresponds
        # to one XLA compilation (shapes are static under jit), counted without any
        # private JAX internals
        self._signatures: set = set()
        # host-side phase accounting (bench breakdown): seconds spent wire-packing
        # and explicitly transferring windows, and windows dispatched
        self.stats = {"pack_s": 0.0, "h2d_s": 0.0, "windows": 0,
                      "densify_s": 0.0}
        if mesh is not None:
            pspec = jax.sharding.PartitionSpec(mesh_axis)
            self._sharding = jax.sharding.NamedSharding(mesh, pspec)
            self._packed_sharding = jax.sharding.NamedSharding(
                mesh, jax.sharding.PartitionSpec(None, mesh_axis, None))
            self._ev_sharding = jax.sharding.NamedSharding(
                mesh, jax.sharding.PartitionSpec(None, mesh_axis))
        else:
            self._sharding = None
            self._packed_sharding = None
            self._ev_sharding = None

    def _wire_fold(self, derived_cols: Mapping[str, str]
                   ) -> tuple[frozenset, WireFormat, Any]:
        """The (cache key, WireFormat, jitted fold) triple for one derived-column
        declaration.

        The fold consumes wire-packed windows directly — decode happens inside the
        jit so XLA fuses unpacking into the scan and only wire bytes cross the link:
        ``fold(carry {name:[B]}, packed u8 [T,B,nbytes], side {name:[T,B]},
        ord_base i32 [B]) -> carry``.
        """
        key = frozenset(dict(derived_cols).items())
        hit = self._wire_folds.get(key)
        if hit is not None:
            return (key, *hit)
        wire = WireFormat(self.spec.registry, derived_cols)
        batch_fold = make_batch_fold(self.spec, unroll=self._unroll,
                                     dispatch=self._dispatch)

        def fold(carry: StateTree, packed, side, ord_base) -> StateTree:
            return batch_fold(carry, wire.decode(packed, side, ord_base))

        donate = (0,) if self.donate_carry else ()
        if self.mesh is not None:
            carry_sh = jax.tree_util.tree_map(lambda _: self._sharding,
                                              self._carry_struct())
            jitted = jax.jit(fold, donate_argnums=donate,
                             in_shardings=(carry_sh, None, None, None),
                             out_shardings=carry_sh)
        else:
            jitted = jax.jit(fold, donate_argnums=donate)
        self._wire_folds[key] = (wire, jitted)
        return key, wire, jitted

    # -- helpers ------------------------------------------------------------------------

    def _fetch_stage(self):
        """Profiler context for a device→host state pull (the fetch barrier
        that closes the chunk's device time); no-op without a profiler."""
        if self.profiler is None:
            from contextlib import nullcontext

            return nullcontext()
        return self.profiler.stage("fetch")

    def _carry_struct(self) -> StateTree:
        return {f.name: None for f in self.spec.registry.state.fields}

    def _lane_multiple(self) -> int:
        """Pad B to a multiple of device count (for even mesh sharding) × 8."""
        n = 1 if self.mesh is None else int(np.prod(self.mesh.devices.shape))
        return max(8 * n, n)

    def num_compiles(self) -> int:
        """Compiled-program count across fold variants (compile-stability
        instrumentation): the number of distinct static shape signatures dispatched.
        Under ``jax.jit`` each distinct signature triggers exactly one compilation,
        so this equals the XLA program count without relying on private JAX APIs
        (VERDICT r3 weak #6)."""
        return len(self._signatures)

    def init_carry_np(self, batch: int) -> dict[str, np.ndarray]:
        """Host-side initial carry columns ``{name: [batch]}``."""
        init = self.spec.init_state_tree()
        return {k: np.broadcast_to(np.asarray(v), (batch,)).copy()
                for k, v in init.items()}

    def init_carry(self, batch: int) -> StateTree:
        carry = self.init_carry_np(batch)
        return self._device_carry(carry)

    def _device_carry(self, carry: Mapping[str, np.ndarray]) -> StateTree:
        if self._sharding is not None:
            return {k: jax.device_put(np.asarray(v), self._sharding)
                    for k, v in carry.items()}
        return {k: jnp.asarray(np.asarray(v)) for k, v in carry.items()}

    def carry_from_states(self, states: Sequence[Any]) -> dict[str, np.ndarray]:
        """Resume from snapshots (checkpointed carry, SURVEY.md §5.4 TPU mapping)."""
        return encode_states(self.spec.registry.state, states)

    def _carry_slice(self, init_carry: Mapping[str, Any] | None,
                     start: int, stop: int, bp: int,
                     idxs: np.ndarray | None = None) -> StateTree:
        """Fresh padded device carry for aggregates [start:stop) — or the explicit
        ``idxs`` gather when the batch was length-reordered. Donation-safe:
        external arrays are copied to host buffers first, never handed to the jit."""
        if init_carry is None:
            return self._device_carry(self.init_carry_np(bp))
        defaults = self.init_carry_np(bp)
        out = {}
        for k, full in init_carry.items():
            piece = (np.asarray(full)[idxs] if idxs is not None
                     else np.asarray(full)[start:stop])
            buf = defaults[k]
            buf[: len(piece)] = piece
            out[k] = buf
        return self._device_carry(out)

    def _device_window(self, packed: np.ndarray, side: Mapping[str, np.ndarray],
                       ord_base: np.ndarray):
        if self._ev_sharding is not None:
            return (jax.device_put(packed, self._packed_sharding),
                    {k: jax.device_put(v, self._ev_sharding) for k, v in side.items()},
                    jax.device_put(ord_base, self._sharding))
        return packed, side, ord_base

    # -- core entry points --------------------------------------------------------------

    def replay_encoded(self, enc: EncodedEvents,
                       init_carry: Mapping[str, Any] | None = None,
                       ordinal_base: np.ndarray | None = None) -> ReplayResult:
        """Fold one encoded batch. The aggregate axis is chunked to
        ``surge.replay.batch-size`` and the time axis to ``surge.replay.time-chunk`` so
        arbitrarily large batches and arbitrarily long (padded) logs stream through a
        fixed-size compiled program with bounded HBM.

        When resuming (``init_carry`` from a snapshot) and the batch declares derived
        ordinal columns, ``ordinal_base`` must carry each aggregate's already-folded
        event count ``[B]`` so the derived ordinals continue rather than restart."""
        b, t = enc.batch_size, enc.max_len
        bs = min(self.batch_size, _round_up(max(b, 1), self._lane_multiple()))
        state_fields = self.spec.registry.state.fields
        out = {f.name: np.zeros((b,), dtype=f.dtype) for f in state_fields}
        padded = 0

        for start in range(0, max(b, 1), bs):
            stop = min(start + bs, b)
            if stop <= start:
                break
            carry = self._carry_slice(init_carry, start, stop, bs)
            carry, scanned = self._fold_window(
                carry, enc.type_ids[start:stop],
                {k: v[start:stop] for k, v in enc.cols.items()}, bs,
                derived_cols=enc.derived_cols,
                ordinal_base=None if ordinal_base is None else ordinal_base[start:stop])
            with self._fetch_stage():
                for name in out:
                    out[name][start:stop] = np.asarray(carry[name])[: stop - start]
            padded += bs * scanned

        return ReplayResult(states=out, num_aggregates=b,
                            num_events=int(enc.lengths.sum()), padded_events=padded)

    def replay_columnar(self, colev: ColumnarEvents,
                        init_carry: Mapping[str, Any] | None = None,
                        ordinal_base: np.ndarray | None = None) -> ReplayResult:
        """Fold a flat columnar log (the log-segment storage layout) directly.

        Densifies per B-chunk, never the whole batch: each chunk pads only to its own
        max log length, so host memory stays bounded by ``batch-size × local max T``
        even when one aggregate's log dwarfs the rest.

        With ``surge.replay.sort-by-length`` (default on) aggregates are ordered by
        log length before B-chunking, so a chunk's local max ≈ its members' lengths
        — together with the tail-window ladder this is the pad_ratio lever (VERDICT
        r3 next #2). Output state columns stay in the caller's aggregate order."""
        b = colev.num_aggregates
        bs = min(self.batch_size, _round_up(max(b, 1), self._lane_multiple()))
        # ordering only changes chunk composition when there IS more than one chunk
        if self.sort_by_length and b > bs:
            lengths_all = np.bincount(colev.agg_idx, minlength=b).astype(np.int64)
            perm = np.argsort(lengths_all, kind="stable").astype(np.int32)
            if np.array_equal(perm, np.arange(b, dtype=np.int32)):
                perm = None  # already length-ordered: skip the O(N) relabel
            else:
                inv = np.empty_like(perm)
                inv[perm] = np.arange(b, dtype=np.int32)
                # relabel each event's aggregate to its length rank; the stable
                # aggregate sort below then groups by rank while preserving each
                # aggregate's time order
                colev = ColumnarEvents(
                    num_aggregates=b, agg_idx=inv[colev.agg_idx],
                    type_ids=colev.type_ids, cols=colev.cols,
                    derived_cols=dict(colev.derived_cols))
        else:
            perm = None
        sorted_ev = colev.sorted_by_aggregate()
        state_fields = self.spec.registry.state.fields
        out = {f.name: np.zeros((b,), dtype=f.dtype) for f in state_fields}
        padded = 0
        total_events = 0
        for start in range(0, max(b, 1), bs):
            stop = min(start + bs, b)
            if stop <= start:
                break
            idxs = None if perm is None else perm[start:stop]
            enc = columnar_to_batch(sorted_ev.slice_aggregates(start, stop))
            carry = self._carry_slice(init_carry, start, stop, bs, idxs=idxs)
            ob = (None if ordinal_base is None else
                  np.asarray(ordinal_base)[idxs] if idxs is not None
                  else ordinal_base[start:stop])
            carry, scanned = self._fold_window(carry, enc.type_ids, enc.cols, bs,
                                               derived_cols=enc.derived_cols,
                                               ordinal_base=ob)
            with self._fetch_stage():
                for name in out:
                    chunk_states = np.asarray(carry[name])[: stop - start]
                    if idxs is None:
                        out[name][start:stop] = chunk_states
                    else:
                        out[name][idxs] = chunk_states
            padded += bs * scanned
            total_events += int(enc.lengths.sum())
        return ReplayResult(states=out, num_aggregates=b,
                            num_events=total_events, padded_events=padded)

    def _window_plan(self, t: int) -> list[tuple[int, int]]:
        """Decompose a T-length window into ``(start, padded_width)`` pieces.

        Full pieces are ``time-chunk`` wide; the tail descends a power-of-two
        ladder down to ``min-time-window`` instead of padding to a full chunk —
        the T-quantization half of the pad_ratio lever (VERDICT r3 weak #2).
        Every width in the ladder is a distinct compiled program, so the program
        count stays bounded at ``1 + log2(chunk/min)`` per fold variant."""
        if t <= 0:
            return []  # nothing to fold: no dispatch (and no all-pad program)
        chunk = self.time_chunk if self.time_chunk > 0 else t
        plan = []
        s = 0
        while t - s >= chunk:
            plan.append((s, chunk))
            s += chunk
        rem = t - s
        if rem > 0 and self.min_time_window <= 0:
            plan.append((s, chunk))  # ladder disabled: full-pad tail
        elif rem > 0:
            # bit-decompose the tail into descending ladder windows so scanned
            # slots ≈ round_up(tail, min) — a single covering window would waste
            # up to 2× on the tail, which dominates when logs are much shorter
            # than a full time-chunk. Widths always come from ladder_widths()
            # (min × powers of two), never from halving the chunk, so a
            # non-power-of-two time-chunk cannot produce sub-min or
            # unpredictable widths.
            ladder = self.ladder_widths()
            w = ladder[-1]
            while rem > 0:
                while w > ladder[0] and w > rem:
                    w //= 2
                plan.append((s, w))
                take = min(w, rem)
                s += take
                rem -= take
        return plan

    def ladder_widths(self) -> list[int]:
        """The tail-window widths _window_plan can dispatch (ascending):
        ``min-time-window × 2^k``, strictly below the time-chunk (a tail is
        always < chunk, so a chunk-sized ladder entry could never fire). Every
        entry is a distinct compiled program; warm-up should cover all of them
        plus the full chunk (see bench.py)."""
        min_w = max(self.min_time_window, 1)
        chunk = self.time_chunk if self.time_chunk > 0 else min_w
        ladder = [min_w]
        while ladder[-1] * 2 < chunk:
            ladder.append(ladder[-1] * 2)
        return ladder

    def _fold_window(self, carry: StateTree, type_ids: np.ndarray,
                     cols: Mapping[str, np.ndarray], bs: int,
                     derived_cols: Mapping[str, str] | None = None,
                     t_base: int = 0,
                     ordinal_base: np.ndarray | None = None
                     ) -> tuple[StateTree, int]:
        """Fold one [b?, T] window (b? ≤ bs) through T-chunked fixed-width programs;
        returns ``(carry, scanned_t)`` where scanned_t is the padded slot count per
        aggregate actually dispatched.

        Each chunk is wire-packed on the host (uint8 word + side columns) and decoded
        inside the fold jit. The ordinal base of device-derived positional columns is
        ``ordinal_base[b] + t_base + s``: per-aggregate already-folded event counts
        (resume) plus the window's global time offset (replay_stream's cumulative
        width of prior chunks)."""
        key, wire, fold = self._wire_fold(derived_cols or {})
        b, t = type_ids.shape
        base = np.zeros((bs,), dtype=np.int32)
        if ordinal_base is not None:
            base[:b] = np.asarray(ordinal_base, dtype=np.int32)[:b]
        scanned = 0
        for s, width in self._window_plan(t):
            e = min(s + width, t)
            t0 = time.perf_counter()
            packed, side = wire.pack_window(type_ids, cols, s, e, width, bs)
            ord_base = base + np.int32(t_base + s)
            t1 = time.perf_counter()
            window = self._device_window(packed, side, ord_base)
            t2 = time.perf_counter()
            self.stats["pack_s"] += t1 - t0
            self.stats["h2d_s"] += t2 - t1
            self.stats["windows"] += 1
            scanned += width
            sig = (key, packed.shape,
                   tuple((k, v.shape) for k, v in sorted(side.items())))
            first_dispatch = sig not in self._signatures
            self._signatures.add(sig)
            if self.profiler is None:
                carry = fold(carry, *window)
            else:
                self.profiler.count_windows()
                self.profiler.record("encode", t1 - t0, width=width)
                self.profiler.record("h2d", t2 - t1, width=width)
                # a fresh signature means this dispatch pays the XLA compile;
                # steady dispatches only pay the async host-side handoff
                with self.profiler.stage(
                        "compile" if first_dispatch else "dispatch",
                        width=width, batch=bs):
                    carry = fold(carry, *window)
        return carry, scanned

    # -- resident-corpus path (single upload, on-device densify) ------------------------

    def pack_resident(self, colev: ColumnarEvents) -> "ResidentWire":
        """Host-side half of :meth:`prepare_resident`: length-sort, flat-pack
        and guard-pad the corpus into its device wire form. The result is pure
        numpy and :meth:`ResidentWire.save`-able — a log segment built once can
        be mmapped and uploaded on every later cold start without re-packing
        (the pack is one-time work, like the reference's log compaction).

        Fast path: an input whose events are already GROUPED per aggregate
        (``agg_idx`` non-decreasing — every encode/segment path produces this)
        is packed in ITS OWN event order and lanes point at their segments by
        indirection (``starts[k] = start of aggregate perm[k]``). Nothing in
        the device fold requires lane slabs to be buffer-contiguous — each
        tile gathers from per-lane bases — so the 100M-event stable sort plus
        three full-column gathers the old path paid (~17 s of a ~26 s pack at
        bench scale) disappear; only the O(B) length argsort remains."""
        b = colev.num_aggregates
        agg = np.asarray(colev.agg_idx)
        lengths = np.bincount(agg, minlength=b).astype(np.int64)
        if self.sort_by_length and b > 1:
            # DESCENDING by length: the lanes still active after t events form a
            # prefix, so each tile round dispatches a contiguous lane range
            perm = np.argsort(-lengths, kind="stable").astype(np.int32)
            if np.array_equal(perm, np.arange(b, dtype=np.int32)):
                perm = None
        else:
            perm = None

        grouped = bool((np.diff(agg) >= 0).all()) if agg.size > 1 else True
        if grouped:
            to_pack = colev
        else:
            # ungrouped input: materialize the sorted order (rare —
            # interleaved hand-built columns); lanes end up buffer-contiguous
            if perm is not None:
                inv = np.empty_like(perm)
                inv[perm] = np.arange(b, dtype=np.int32)
                colev = ColumnarEvents(
                    num_aggregates=b, agg_idx=inv[colev.agg_idx],
                    type_ids=colev.type_ids, cols=colev.cols,
                    derived_cols=dict(colev.derived_cols))
                lengths = lengths[perm]
            to_pack = colev.sorted_by_aggregate()

        wire = WireFormat(self.spec.registry, dict(to_pack.derived_cols))
        t0 = time.perf_counter()
        packed, side_flat = wire.pack_flat(to_pack.type_ids, to_pack.cols)
        # tail padding so every [start + t_base, width) slab slice stays in
        # bounds without clamping (clamped slices would shift lane data);
        # content is irrelevant — slots past lens decode to the pad sentinel
        guard = max(self.resident_tile_width(), _WIRE_GUARD_MIN)
        packed = np.pad(packed, ((0, guard), (0, 0)))
        side_flat = {k: np.pad(v, (0, guard)) for k, v in side_flat.items()}
        pack_elapsed = time.perf_counter() - t0
        self.stats["pack_s"] += pack_elapsed
        if self.profiler is not None:
            self.profiler.record("encode", pack_elapsed,
                                 events=to_pack.num_events, kind="pack_resident")
        # lengths/starts are in the PACKED stream's aggregate-id order; the
        # grouped path then permutes the lane VIEW only (indirection), the
        # ungrouped path already permuted the stream itself
        starts = np.zeros(b + 1, dtype=np.int64)
        np.cumsum(lengths, out=starts[1:])
        starts_lane, lens_lane = starts[:-1], lengths
        if grouped and perm is not None:
            starts_lane = starts_lane[perm]
            lens_lane = lengths[perm]
        return ResidentWire(
            derived_key=dict(to_pack.derived_cols), packed=packed,
            side=side_flat, starts=starts_lane.astype(np.int32),
            lengths=lens_lane.astype(np.int32), perm=perm, guard=guard,
            num_events=to_pack.num_events,
            layout=wire.layout_fingerprint())

    def check_wire(self, w: "ResidentWire") -> WireFormat:
        """Validate a (possibly disk-loaded) wire against this engine: guard
        rows cover the tile width, and the packing layout matches the engine's
        schema bit-for-bit. Returns the engine's WireFormat for the wire's
        derived-column declaration. Shared by the single-device and sharded
        upload paths — a stale wire must never decode silently-wrong states."""
        if w.guard < self.resident_tile_width():
            raise ValueError(
                f"wire guard {w.guard} is smaller than the engine's tile width "
                f"{self.resident_tile_width()}; repack or lower "
                "surge.replay.time-chunk")
        # layout fingerprint check: never decode a wire packed under a
        # different schema (misaligned BITS would fold silently-wrong states —
        # the fingerprint pins field order, widths, shifts and type count, not
        # just the total byte width)
        wire = WireFormat(self.spec.registry, dict(w.derived_key))
        if w.layout is not None and w.layout != wire.layout_fingerprint():
            raise ValueError(
                f"wire layout mismatch: corpus was packed as {w.layout}, "
                f"engine schema packs {wire.layout_fingerprint()}; "
                "rebuild the wire with pack_resident")
        if wire.nbytes != w.packed.shape[1]:  # also guards corrupted buffers
            raise ValueError(
                f"wire layout mismatch: corpus packed {w.packed.shape[1]} "
                f"byte(s)/event but the engine's schema packs {wire.nbytes}; "
                "rebuild the wire with pack_resident")
        want_sides = {f.name: np.dtype(f.dtype) for f in wire.side_fields}
        got_sides = {k: np.dtype(v.dtype) for k, v in w.side.items()}
        if want_sides != got_sides:
            raise ValueError(
                f"wire side-column mismatch: corpus has {got_sides}, engine "
                f"schema expects {want_sides}; rebuild the wire")
        return wire

    def upload_resident(self, w: "ResidentWire") -> "ResidentCorpus":
        """Device-side half of :meth:`prepare_resident`: ship a packed wire
        corpus (fresh or mmapped from disk) and return the replay handle.

        Buffer lengths are bucketed to powers of two by default
        (``surge.replay.resident-len-bucket = pow2``), so consecutive uploads
        of different-sized corpora — segment chunks in a restore — reuse one
        compiled program per bucket instead of recompiling per exact length;
        ``exact`` skips the padding for single-corpus workloads that warm
        explicitly (bench)."""
        if self.mesh is not None:
            raise NotImplementedError(
                "this engine is mesh-backed; use prepare_resident_sharded / "
                "replay_resident_sharded for the resident path")
        self.check_wire(w)
        import jax

        b = w.lengths.shape[0]
        t0 = time.perf_counter()
        pow2 = self.config.get_str(
            "surge.replay.resident-len-bucket", "pow2") == "pow2"
        packed_b = _bucket_rows(w.packed, pow2)
        side_b = {k: _bucket_rows(v, pow2) for k, v in w.side.items()}
        # chunked H2D: on high-latency links a single large put can fall off
        # the fast path (measured: 100 MB at ~94 MB/s vs 16 MB pieces at
        # ~565 MB/s through the tunnel); pieces upload pipelined and are
        # reassembled on-device with one concatenate
        chunk_mb = self.config.get_int("surge.replay.upload-chunk-mb", 0)
        flat_wire = _chunked_put(packed_b, chunk_mb)
        flat_side = {k: _chunked_put(v, chunk_mb) for k, v in side_b.items()}
        bs = min(self.batch_size, _round_up(max(b, 1), self._lane_multiple()))
        b_pad = _round_up(max(b, 1), bs)
        if pow2:
            chunks = 1
            while chunks * bs < b_pad:
                chunks *= 2
            b_pad = chunks * bs
        starts_p = np.zeros((b_pad,), dtype=np.int32)
        starts_p[:b] = w.starts
        lens_p = np.zeros((b_pad,), dtype=np.int32)
        lens_p[:b] = w.lengths
        starts_dev = jax.device_put(starts_p)
        lens_dev = jax.device_put(lens_p)
        jax.block_until_ready(flat_wire)
        upload_s = time.perf_counter() - t0
        self.stats["h2d_s"] += upload_s
        if self.profiler is not None:
            self.profiler.record(
                "h2d", upload_s, kind="upload_resident",
                bytes=packed_b.nbytes + sum(v.nbytes for v in side_b.values()))
        return ResidentCorpus(
            derived_key=dict(w.derived_key), flat_wire=flat_wire,
            flat_side=flat_side, starts=w.starts,
            lengths=w.lengths, perm=w.perm,
            starts_dev=starts_dev, lens_dev=lens_dev, b_pad=b_pad,
            num_events=w.num_events,
            wire_bytes=packed_b.nbytes + sum(v.nbytes for v in side_b.values()),
            upload_s=upload_s)

    def prepare_resident_sharded(self, source):
        """Mesh form of :meth:`prepare_resident`: deal the packed corpus's
        lanes round-robin across the mesh axis and upload each device's shard
        (surge_tpu.replay.resident_mesh). ``source`` is a ColumnarEvents or an
        already-packed ResidentWire."""
        from surge_tpu.replay.resident_mesh import ShardedResident

        wire = (source if isinstance(source, ResidentWire)
                else self.pack_resident(source))
        return ShardedResident(self, wire)

    def replay_resident_sharded(self, sharded,
                                init_carry: Mapping[str, Any] | None = None,
                                ordinal_base: np.ndarray | None = None
                                ) -> ReplayResult:
        """Fold a :meth:`prepare_resident_sharded` corpus across the mesh —
        the tile-loop design with one shard_map dispatch per granularity and
        one device→host pull, no collectives (lanes are independent)."""
        from surge_tpu.replay.resident_mesh import replay_resident_sharded

        return replay_resident_sharded(self, sharded, init_carry=init_carry,
                                       ordinal_base=ordinal_base)

    def prepare_resident(self, colev: ColumnarEvents) -> "ResidentCorpus":
        """Upload the WHOLE corpus once as a flat wire buffer (exactly
        ``wire_bytes_per_event()`` per event — zero padding crosses the link)
        and return a handle for :meth:`replay_resident`.

        Every subsequent fold dispatch gathers its window on-device from the
        resident buffer, so per-window transfer drops to the B-chunk's
        starts/lens (KBs) — the right shape for hosts where the device link,
        not the fold, is the bottleneck (tunneled TPU; and on local hardware it
        turns replay into one streaming upload). For a corpus replayed more
        than once, :meth:`pack_resident` + :meth:`ResidentWire.save` persist
        the pack so later cold starts skip straight to the upload."""
        if self.mesh is not None:
            raise NotImplementedError(
                "this engine is mesh-backed; use prepare_resident_sharded / "
                "replay_resident_sharded for the resident path")
        return self.upload_resident(self.pack_resident(colev))

    def _resident_plan(self, resident: "ResidentCorpus") -> "ResidentPlan":
        """Host-side tile schedule. Tile k of a granularity folds events
        ``[t_bases[k], t_bases[k]+width)`` of lanes ``[i0s[k], i0s[k]+bs)``.

        Lanes are length-sorted descending, so the lanes still active in round
        r form a shrinking prefix. Each round covers it with full-width
        ``bs_big`` tiles plus narrow ``bs_small`` tiles over the remainder —
        the narrow granularity caps per-round lane padding at ``bs_small``
        instead of ``bs_big``. A lane only ever moves big→small as the prefix
        shrinks, so running ALL big tiles (in round order) before ALL small
        tiles (in round order) preserves per-lane event order."""
        b = resident.lengths.shape[0]
        lane = self._lane_multiple()
        bs_big = min(self.batch_size, _round_up(max(b, 1), lane))
        bs_small = min(bs_big, max(lane, bs_big // 8))
        # bs_small MUST divide bs_big: the small-tile walk covers
        # [n_big*bs_big, active) in bs_small steps while the device buffer is
        # only padded to a bs_big multiple (upload_resident b_pad) — a
        # non-divisor's last tile would start within bs_small of the buffer
        # end, dynamic_slice would clamp the lane start, and the tile would
        # silently RE-APPLY events to lanes the previous tile already folded
        # (ADVICE r4). Today bs_big is always a multiple of 8*lane so
        # bs_big//8 divides exactly; this guard keeps the invariant explicit
        # against future knob/rounding changes.
        if bs_big % bs_small:
            bs_small = max(c for c in range(lane, bs_small + 1, lane)
                           if bs_big % c == 0)  # lane | bs_big, so non-empty
        assert bs_big % bs_small == 0, (bs_big, bs_small)
        width = self.resident_tile_width()
        lens_host = resident.lengths
        max_len = int(lens_host.max(initial=0)) if b else 0
        sorted_desc = bool((np.diff(lens_host) <= 0).all()) if b > 1 else True
        big_i0: list[int] = []
        big_tb: list[int] = []
        small_i0: list[int] = []
        small_tb: list[int] = []
        if sorted_desc:
            lens_asc = lens_host[::-1]
            t_base = 0
            while t_base < max_len:
                active = b - int(np.searchsorted(lens_asc, t_base, side="right"))
                n_big = active // bs_big
                for k in range(n_big):
                    big_i0.append(k * bs_big)
                    big_tb.append(t_base)
                for i0 in range(n_big * bs_big, active, bs_small):
                    small_i0.append(i0)
                    small_tb.append(t_base)
                t_base += width
        else:
            # unsorted corpus: schedule each contiguous lane range only up to
            # its own local max length (the streaming path's per-chunk bound),
            # not the global max — lanes stay in one range, so ascending
            # t_base per range preserves per-lane event order
            for i0 in range(0, b, bs_big):
                local_max = int(lens_host[i0: i0 + bs_big].max(initial=0))
                for t_base in range(0, local_max, width):
                    big_i0.append(i0)
                    big_tb.append(t_base)
        return ResidentPlan(
            width=width, bs_big=bs_big, bs_small=bs_small,
            big_i0=np.asarray(big_i0, dtype=np.int32),
            big_tb=np.asarray(big_tb, dtype=np.int32),
            small_i0=np.asarray(small_i0, dtype=np.int32),
            small_tb=np.asarray(small_tb, dtype=np.int32))

    @staticmethod
    def _plan_cap(k: int) -> int:
        """Work-list buffer length bucket (next power of two ≥ 64): entries past
        the traced trip count are never read, so one compiled program serves
        every plan in the bucket."""
        cap = 64
        while cap < k:
            cap *= 2
        return cap

    def replay_resident(self, resident: "ResidentCorpus",
                        init_carry: Mapping[str, Any] | None = None,
                        ordinal_base: np.ndarray | None = None) -> ReplayResult:
        """Fold a prepared resident corpus. Results are in the ORIGINAL
        aggregate order of the ColumnarEvents given to :meth:`prepare_resident`.

        Design (measured on the tunneled v5e): a chained dispatch costs ~0.5 ms
        but ANY host⇄device traffic — a sync ~75 ms, even a scalar argument a
        few ms — so the ENTIRE fold pass is ONE dispatch: a ``fori_loop`` over
        a device-resident work list of (lane-range, time-offset) tiles,
        mutating a state slab ``{field: [b_pad]}``, with exactly one
        device→host pull of the folded states at the end."""
        if self.mesh is not None:
            raise NotImplementedError(
                "this engine is mesh-backed; use prepare_resident_sharded / "
                "replay_resident_sharded for the resident path")
        b = resident.lengths.shape[0]
        if b == 0:
            return ReplayResult(states={f.name: np.zeros((0,), dtype=f.dtype)
                                        for f in self.spec.registry.state.fields},
                                num_aggregates=0, num_events=0, padded_events=0)
        perm = resident.perm
        init_sorted, ord_sorted = _apply_perm(perm, init_carry, ordinal_base)
        if self.profiler is None:
            slab, padded = self._dispatch_resident(resident, init_sorted,
                                                   ord_sorted)
            # the single synchronization of the whole replay
            states = self._pull_states(slab, b, resident.perm, resident.cache)
        else:
            with self.profiler.replay_pass("replay.resident", aggregates=b,
                                           events=resident.num_events):
                n0 = self.num_compiles()
                t0 = time.perf_counter()
                slab, padded = self._dispatch_resident(resident, init_sorted,
                                                       ord_sorted)
                self.profiler.record(
                    "compile" if self.num_compiles() > n0 else "dispatch",
                    time.perf_counter() - t0, aggregates=b)
                # the fetch stage IS the single sync: a real device→host pull
                # whose data dependency closes every chained tile program
                # (fetch-barrier discipline — never block_until_ready)
                with self.profiler.stage("fetch", aggregates=b):
                    states = self._pull_states(slab, b, resident.perm,
                                               resident.cache)
        return ReplayResult(
            states=states,
            num_aggregates=b, num_events=resident.num_events,
            padded_events=padded)

    def fold_resident_slab(self, resident: "ResidentCorpus",
                           init_carry: Mapping[str, Any] | None = None,
                           ordinal_base: np.ndarray | None = None
                           ) -> tuple[dict, int]:
        """Fold a prepared resident corpus and return the DEVICE state slab
        instead of pulling states to the host: ``({field: [b_pad] device
        array}, padded_slots)``. Rows are in the corpus's SORTED lane order
        (``resident.perm`` maps sorted rank → original aggregate index; None =
        identity) and rows past ``b`` are padding.

        This is the seeding half of the resident state plane
        (surge_tpu.replay.resident_state): a cold-start replay whose result
        STAYS on device — the caller gathers rows into its own slab with zero
        device→host traffic. ``init_carry``/``ordinal_base`` are in the
        original aggregate order, exactly like :meth:`replay_resident`."""
        init_sorted, ord_sorted = _apply_perm(resident.perm, init_carry,
                                              ordinal_base)
        return self._dispatch_resident(resident, init_sorted, ord_sorted)

    def _pull_states(self, slab: Mapping[str, Any], b: int,
                     perm: Optional[np.ndarray],
                     cache: Optional[dict] = None) -> dict[str, np.ndarray]:
        """One-round-trip state pull: un-perm + truncate + bitcast-pack every
        column into a single u32 matrix ON DEVICE, fetch once, un-bitcast on
        the host. Each materialization of a computed device buffer costs a
        full tunnel round trip (~65-100 ms measured); per-field ``np.asarray``
        paid it once per column. ``cache`` (a per-corpus dict) memoizes the
        device inverse-perm; omit it for throwaway corpora (streamed pieces).
        """
        fields = self.spec.registry.state.fields
        if any(np.dtype(f.dtype).itemsize > 4 for f in fields):
            # >32-bit columns don't fit the u32 packing — per-field pull
            out_sorted = {name: np.asarray(col)[:b]
                          for name, col in slab.items()}
            return _unapply_perm(perm, out_sorted)
        inv = cache.get("invperm") if cache is not None else None
        if inv is None:
            if perm is not None:
                invp = np.empty((b,), np.int32)
                invp[perm] = np.arange(b, dtype=np.int32)
            else:
                invp = np.arange(b, dtype=np.int32)
            inv = jnp.asarray(invp)
            if cache is not None:
                cache["invperm"] = inv
        names = [f.name for f in fields]
        dts = [np.dtype(f.dtype) for f in fields]
        # all-integer/bool states ride the half-width wire: measured tunnel
        # d2h is ~25 MB/s (20× slower than h2d), so the result transfer is
        # the replay's long pole at 1M-aggregate scale. A u16 matrix with
        # device-computed fit flags halves it; any overflowing column
        # triggers one wide refetch (correctness never depends on the guess)
        narrow_ok = not any(np.issubdtype(dt, np.floating) for dt in dts)
        wide_prog = self._finalize_programs.get("wide")
        if wide_prog is None:

            def finalize_wide(sl, ip):
                cols = []
                for name, dt in zip(names, dts):
                    v = sl[name][ip]  # gather = un-perm + [:b] in one op
                    if np.issubdtype(dt, np.floating) and dt.itemsize < 4:
                        # f16/bf16 ride exactly as widened f32 bit patterns
                        v = jax.lax.bitcast_convert_type(
                            v.astype(jnp.float32), jnp.uint32)
                    elif dt == np.bool_ or dt.itemsize < 4:
                        v = v.astype(jnp.uint32)
                    elif dt != np.dtype(np.uint32):
                        v = jax.lax.bitcast_convert_type(v, jnp.uint32)
                    cols.append(v)
                return jnp.stack(cols)

            wide_prog = jax.jit(finalize_wide)
            self._finalize_programs["wide"] = wide_prog

        def decode_wide(mat):
            out: dict[str, np.ndarray] = {}
            for i, f in enumerate(fields):
                dt = np.dtype(f.dtype)
                raw = mat[i]
                if np.issubdtype(dt, np.floating) and dt.itemsize < 4:
                    out[f.name] = raw.view(np.float32).astype(dt)
                elif dt == np.bool_ or dt.itemsize < 4:
                    out[f.name] = raw.astype(dt)
                else:
                    out[f.name] = raw.view(dt).copy()
            return out

        if not narrow_ok:
            return decode_wide(np.asarray(wide_prog(slab, inv)))

        narrow_prog = self._finalize_programs.get("narrow")
        if narrow_prog is None:

            def finalize_narrow(sl, ip):
                cols, flags = [], []
                for name, dt in zip(names, dts):
                    v = sl[name][ip]
                    if dt == np.bool_:
                        fits = jnp.bool_(True)
                        v16 = v.astype(jnp.uint16)
                    elif np.issubdtype(dt, np.signedinteger):
                        fits = jnp.all((v >= -32768) & (v <= 32767))
                        v16 = v.astype(jnp.uint16)  # wrap; host sign-extends
                    else:
                        fits = jnp.all(v <= 65535)
                        v16 = v.astype(jnp.uint16)
                    cols.append(v16.ravel())
                    flags.append(fits.astype(jnp.uint16))
                # one flat buffer, flags at the tail — a second buffer (or a
                # full flag ROW) costs its own tunnel round trip / megabytes
                return jnp.concatenate(cols + [jnp.stack(flags)])

            narrow_prog = jax.jit(finalize_narrow)
            self._finalize_programs["narrow"] = narrow_prog

        buf16 = np.asarray(narrow_prog(slab, inv))  # the one device→host fetch
        nf = len(fields)
        if not buf16[nf * b:].all():
            # a column overflowed 16 bits — refetch wide (extra round trip,
            # still exact)
            return decode_wide(np.asarray(wide_prog(slab, inv)))
        out: dict[str, np.ndarray] = {}
        for i, f in enumerate(fields):
            dt = np.dtype(f.dtype)
            raw = buf16[i * b: (i + 1) * b]
            if dt == np.bool_:
                out[f.name] = raw.astype(dt)
            elif np.issubdtype(dt, np.signedinteger):
                out[f.name] = raw.view(np.int16).astype(dt)
            else:
                out[f.name] = raw.astype(dt)
        return out

    def _dispatch_resident(self, resident: "ResidentCorpus",
                           init_sorted: Mapping[str, np.ndarray] | None,
                           ord_sorted: np.ndarray | None
                           ) -> tuple[dict, int]:
        """Dispatch the whole fold of one resident corpus WITHOUT syncing:
        returns the (device) state slab and the padded-slot count. ``init``/
        ``ordinal`` inputs are already in the corpus's sorted lane order."""
        b = resident.lengths.shape[0]
        plan = self._plan_for(resident)
        b_pad = resident.b_pad
        key = frozenset(resident.derived_key.items())

        if init_sorted is None and ord_sorted is None:
            # fresh replay: build the init slab ON DEVICE (no host transfer —
            # the ~65 ms tunnel round trip would otherwise be paid per replay)
            slab, ord_d = self._fresh_slab(b_pad)
        else:
            ord_p = np.zeros((b_pad,), dtype=np.int32)
            if ord_sorted is not None:
                ord_p[:b] = np.asarray(ord_sorted).astype(np.int32)
            slab_np = self.init_carry_np(b_pad)
            if init_sorted is not None:
                for k, full in init_sorted.items():
                    slab_np[k][:b] = np.asarray(full)
            slab = {k: jnp.asarray(v) for k, v in slab_np.items()}
            ord_d = jnp.asarray(ord_p)

        use_dense = self._use_dense(resident, plan)
        # two chained dispatches (big tiles, then small); per-lane order holds
        # because a lane only ever migrates big→small as the prefix shrinks
        for bs, i0s, t_bases in ((plan.bs_big, plan.big_i0, plan.big_tb),
                                 (plan.bs_small, plan.small_i0, plan.small_tb)):
            k_n = len(i0s)
            if k_n == 0:
                continue
            k_cap = self._plan_cap(k_n)
            self.stats["windows"] += k_n
            if self.profiler is not None:
                self.profiler.count_windows(k_n)
            if use_dense:
                dw, ds, i0s_d, tbs_d = self._dense_tiles(
                    resident, plan, bs, i0s, t_bases, k_cap)
                fold = self._resident_program_dense(key, plan.width, bs,
                                                    k_cap)
                self._signatures.add(("resident-dense", key, plan.width, bs,
                                      k_cap, b_pad))
                slab = fold(slab, dw, ds, resident.lens_dev, ord_d,
                            i0s_d, tbs_d)
                continue
            fold = self._resident_program(key, plan.width, bs, k_cap)
            i0s_p = np.zeros((k_cap,), dtype=np.int32)
            i0s_p[:k_n] = i0s
            tb_p = np.zeros((k_cap,), dtype=np.int32)
            tb_p[:k_n] = t_bases
            self._signatures.add(("resident", key, plan.width, bs, k_cap,
                                  b_pad, int(resident.flat_wire.shape[0])))
            slab = fold(slab, resident.flat_wire, resident.flat_side,
                        resident.starts_dev, resident.lens_dev, ord_d,
                        jnp.asarray(i0s_p), jnp.asarray(tb_p), np.int32(k_n))
        return slab, plan.padded_slots

    @property
    def tile_backend(self) -> str:
        """The resolved tile backend. ``auto`` picks the scanless assoc tree
        fold only where it measured faster: models shipping a (law-checked)
        ``AssociativeFold``, power-of-two tile width, and a non-CPU backend —
        on chip the scan pays ~58 µs/step loop machinery (assoc fold ~7× the
        scan at full scale, BENCH_ONCHIP.json r5), while the 1-core host runs the
        scan ~2× FASTER than the tree (401M vs 188M ev/s). Only an EXPLICIT
        ``tile-backend = assoc`` raises on an unsupported spec/width."""
        if self._tile_backend != "auto":
            return self._tile_backend
        if self._tile_backend_resolved is None:
            w = self.resident_tile_width()
            self._tile_backend_resolved = (
                "assoc" if getattr(self.spec, "associative", None) is not None
                and (w & (w - 1)) == 0
                and jax.default_backend() != "cpu" else "xla")
        return self._tile_backend_resolved

    def _plan_for(self, resident: "ResidentCorpus") -> "ResidentPlan":
        """The corpus's tile plan, cached on the corpus (plan geometry only
        depends on engine config + corpus lengths; recomputing the host-side
        bucketing every pass costs tens of ms at 1M lanes)."""
        pkey = ("plan", self.resident_tile_width(), self.batch_size)
        plan = resident.cache.get(pkey)
        if plan is None:
            plan = self._resident_plan(resident)
            resident.cache[pkey] = plan
        return plan

    def _fresh_slab(self, b_pad: int):
        """Fresh init state slab + zero ordinal base, built by a jitted
        on-device program (fresh buffers every call, so carry donation can
        never invalidate a cached one)."""
        prog = self._slab_programs.get(b_pad)
        if prog is None:
            init = self.spec.init_state_tree()
            fields = [(f.name, f.dtype) for f in self.spec.registry.state.fields]

            def mk():
                slab = {name: jnp.full((b_pad,), init[name], dtype=dt)
                        for name, dt in fields}
                return slab, jnp.zeros((b_pad,), jnp.int32)

            prog = jax.jit(mk)
            self._slab_programs[b_pad] = prog
        return prog()

    def _use_dense(self, resident: "ResidentCorpus", plan: "ResidentPlan"
                   ) -> bool:
        if self._resident_layout == "flat":
            return False
        if resident.cache.get("oneshot"):
            # a corpus folded once pays the densify gather without ever
            # amortizing it — always gather per-pass
            return False
        if self._resident_layout == "dense":
            return True
        if jax.default_backend() == "cpu":
            # dense trades memory (pad_ratio × corpus, k_cap-padded) for the
            # accelerator's slow per-lane gather; the host gathers fine and
            # the extra RSS breaks bounded-memory restores
            return False
        if plan.padded_slots < 16_000_000:
            # the densify dispatch+compile carries ~1 s of fixed cost — below
            # this scale the per-pass gather it saves never adds up to that
            return False
        return self._dense_bytes(resident, plan) <= self._dense_cap_mb * 1024 * 1024

    def _dense_bytes(self, resident: "ResidentCorpus", plan: "ResidentPlan"
                     ) -> int:
        """HBM the dense tile buffers would occupy (k_cap-padded)."""
        nbytes = int(resident.flat_wire.shape[1])
        per_slot = nbytes + sum(np.dtype(arr.dtype).itemsize
                                for arr in resident.flat_side.values())
        total = 0
        for bs, i0s in ((plan.bs_big, plan.big_i0),
                        (plan.bs_small, plan.small_i0)):
            if len(i0s):
                total += self._plan_cap(len(i0s)) * bs * plan.width * per_slot
        return total

    def _dense_tiles(self, resident: "ResidentCorpus", plan: "ResidentPlan",
                     bs: int, i0s: np.ndarray, t_bases: np.ndarray,
                     k_cap: int):
        """Build-or-fetch the dense tile buffers for one work list (cached on
        the corpus; the gather runs once per corpus, not once per pass)."""
        key = frozenset(resident.derived_key.items())
        ckey = ("dense", plan.width, bs, k_cap,
                np.asarray(i0s, np.int32).tobytes(),
                np.asarray(t_bases, np.int32).tobytes())
        hit = resident.cache.get(ckey)
        if hit is not None:
            return hit
        dkey = (key, plan.width, bs)
        dens = self._densify_programs.get(dkey)
        if dens is None:
            wire = WireFormat(self.spec.registry, dict(resident.derived_key))
            dens = jax.jit(_make_densify(wire, plan.width, bs))
            self._densify_programs[dkey] = dens
        i0s_p = np.zeros((k_cap,), dtype=np.int32)
        i0s_p[: len(i0s)] = i0s
        # entries past k_n are provable no-ops (t_base beyond every lane's
        # length ⇒ every slot masks to padding ⇒ identity), so the dense fold
        # can run a STATIC k_cap trip count and one compiled program still
        # serves every plan in the bucket
        tb_p = np.full((k_cap,), _NOOP_TILE_T, dtype=np.int32)
        tb_p[: len(t_bases)] = t_bases
        i0s_d = jnp.asarray(i0s_p)
        tbs_d = jnp.asarray(tb_p)
        t0 = time.perf_counter()
        dw, ds = dens(resident.flat_wire, resident.flat_side,
                      resident.starts_dev, i0s_d, tbs_d)
        entry = (dw, ds, i0s_d, tbs_d)
        resident.cache[ckey] = entry
        self.stats["densify_s"] += time.perf_counter() - t0
        return entry

    def _resident_program_dense(self, key: frozenset, width: int, bs: int,
                                k_cap: int):
        """Dense-layout twin of :meth:`_resident_program`: the fori_loop reads
        pre-gathered ``[k_cap, width, bs, nbytes]`` tiles by index instead of
        gathering per-lane rows from the flat corpus each pass. The trip count
        is STATIC at ``k_cap`` (measured ~40 ms cheaper per pass on the v5e
        than a traced one) without per-``k_n`` recompiles: work-list entries
        past the plan's real tile count carry the ``_NOOP_TILE_T`` sentinel,
        whose slots all mask to padding — identity under every backend."""
        cache_key = (key, width, bs, k_cap)
        hit = self._resident_dense_folds.get(cache_key)
        if hit is not None:
            return hit

        wire = WireFormat(self.spec.registry, dict(key))
        tile = _make_tile_dense(self.spec, wire, width, bs, self._unroll,
                                self._dispatch, self.tile_backend)

        def fold(slab_state, dense_words, dense_sides, lens_all, ord_all,
                 i0s, t_bases):
            def body(k, st):
                return tile(st, dense_words, dense_sides, lens_all, ord_all,
                            i0s[k], t_bases[k], k)

            return jax.lax.fori_loop(0, k_cap, body, slab_state)

        donate = (0,) if self.donate_carry else ()
        jitted = jax.jit(fold, donate_argnums=donate)
        self._resident_dense_folds[cache_key] = jitted
        return jitted

    def replay_resident_streamed(self, w: "ResidentWire", *,
                                 segments: int | None = None,
                                 init_carry: Mapping[str, Any] | None = None,
                                 ordinal_base: np.ndarray | None = None
                                 ) -> ReplayResult:
        """Upload AND fold a packed wire in lane segments: segment s's tiles
        dispatch right after its upload initiates, so on backends that overlap
        transfers with compute the fold of earlier segments hides later
        segments' uploads — and on backends that don't, nothing is lost but
        per-segment overhead. Segments split at event-count boundaries
        (balanced bytes) and each piece is a zero-copy contiguous slice of the
        buffer: for a contiguous wire the piece's lanes are a lane RANGE; for
        an indirect wire (the grouped-input fast pack, whose lane slabs tile
        the buffer in buffer order, not lane order) the piece's lanes are the
        subset whose slabs fall in the slice, re-sorted desc for the tile
        plan. A wire whose slabs do not tile its buffer at all (hand-built
        subset/overlap) falls back to the plain single-upload path. Results
        are in the original aggregate order either way.

        ``segments`` defaults to ``surge.replay.upload-stream-segments``
        (0/1 = plain upload+replay)."""
        if segments is None:
            segments = self.config.get_int(
                "surge.replay.upload-stream-segments", 0)
        b = w.lengths.shape[0]
        if segments <= 1 or b == 0:
            return self.replay_resident(self.upload_resident(w),
                                        init_carry=init_carry,
                                        ordinal_base=ordinal_base)
        self.check_wire(w)
        perm = w.perm
        init_sorted, ord_sorted = _apply_perm(perm, init_carry, ordinal_base)
        state_fields = self.spec.registry.state.fields

        starts64 = w.starts.astype(np.int64)
        lens64 = w.lengths.astype(np.int64)
        cum = np.zeros(b + 1, dtype=np.int64)
        np.cumsum(lens64, out=cum[1:])
        total = int(cum[-1])
        contiguous = np.array_equal(starts64, cum[:-1])
        zero_lanes = np.array([], dtype=np.int64)
        if contiguous:
            # lanes tile the buffer in lane order: pieces are lane ranges
            lane_order = None
            piece_starts = cum
            n_lanes = b
        else:
            # indirect wire (grouped-input fast pack): lane slabs tile the
            # buffer in BUFFER order, not lane order — walk the NONZERO lanes
            # by start so each piece is still one zero-copy contiguous slice.
            # Zero-length lanes occupy no rows (their start is wherever the
            # next slab begins), so they are excluded from the tiling walk and
            # tacked onto the first piece, whose plan skips them.
            nz = np.nonzero(lens64 > 0)[0]
            zero_lanes = np.nonzero(lens64 == 0)[0]
            if nz.size == 0:
                return self.replay_resident(self.upload_resident(w),
                                            init_carry=init_carry,
                                            ordinal_base=ordinal_base)
            lane_order = nz[np.argsort(starts64[nz], kind="stable")]
            piece_starts = np.zeros(lane_order.size + 1, dtype=np.int64)
            np.cumsum(lens64[lane_order], out=piece_starts[1:])
            if not np.array_equal(starts64[lane_order], piece_starts[:-1]):
                # slabs don't tile the buffer (subset/overlapping wire):
                # stream piecewise is meaningless — plain path
                return self.replay_resident(self.upload_resident(w),
                                            init_carry=init_carry,
                                            ordinal_base=ordinal_base)
            n_lanes = lane_order.size

        # piece boundaries at ~equal event counts
        bounds = [0]
        for s in range(1, segments):
            cut = int(np.searchsorted(piece_starts, total * s // segments))
            bounds.append(min(max(cut, bounds[-1]), n_lanes))
        bounds.append(n_lanes)

        pieces: list = []
        padded = 0
        first_piece = True
        for lo, hi in zip(bounds[:-1], bounds[1:]):
            if hi <= lo:
                continue
            base = int(piece_starts[lo])
            end = int(piece_starts[hi])
            if lane_order is None:
                lanes = np.arange(lo, hi)
                sub_starts = starts64[lo:hi] - base
                sub_lens = w.lengths[lo:hi]
            else:
                lanes = lane_order[lo:hi]
                if first_piece and zero_lanes.size:
                    lanes = np.concatenate([lanes, zero_lanes])
                # piece-local DESC length order so the tile plan keeps its
                # shrinking-prefix schedule (zero lanes sort last, fold no-op)
                lanes = lanes[np.argsort(-lens64[lanes], kind="stable")]
                sub_starts = np.where(lens64[lanes] > 0,
                                      starts64[lanes] - base, 0)
                sub_lens = w.lengths[lanes]
            first_piece = False
            sub = ResidentWire(
                derived_key=dict(w.derived_key),
                packed=w.packed[base: end + w.guard],
                side={k: v[base: end + w.guard] for k, v in w.side.items()},
                starts=sub_starts.astype(np.int32),
                lengths=sub_lens, perm=None, guard=w.guard,
                num_events=end - base, layout=w.layout)
            piece = self.upload_resident(sub)  # upload initiates...
            # folded exactly once: the dense layout's one-time gather would
            # never amortize (measured 2.5× slower streaming in the r5 sweep)
            piece.cache["oneshot"] = True
            slab, pad = self._dispatch_resident(
                piece,
                None if init_sorted is None else
                {k: v[lanes] for k, v in init_sorted.items()},
                None if ord_sorted is None else ord_sorted[lanes])
            padded += pad
            # hold ONLY what the sync pass needs — keeping the piece corpus
            # itself would pin every piece's wire buffers in HBM at once
            pieces.append((lanes, slab))  # ...fold dispatched, NOT synced
        # one sync pass over every piece — a single packed fetch per piece
        # (every materialized buffer costs a full tunnel round trip; the old
        # per-piece-per-field np.asarray paid pieces × fields of them), then
        # global unsort
        out_sorted = {f.name: np.empty((b,), dtype=f.dtype)
                      for f in state_fields}
        for lanes, slab in pieces:
            with self._fetch_stage():
                piece_states = self._pull_states(slab, int(lanes.shape[0]), None)
            for name, col in piece_states.items():
                out_sorted[name][lanes] = col
        return ReplayResult(states=_unapply_perm(perm, out_sorted),
                            num_aggregates=b,
                            num_events=w.num_events, padded_events=padded)

    def resident_cap_width(self) -> int:
        """Largest tile width the HBM budget allows (pow2 multiple of the min
        window): one tile materializes a [batch, width] u32 slab and its
        transpose, so width is capped by resident-slab-cap-mb."""
        budget = self.config.get_int("surge.replay.resident-slab-cap-mb", 512)
        w = max(self.min_time_window, 1)
        while w * 2 * self.batch_size * 8 <= budget * 1_000_000:
            w *= 2
        return w

    def resident_tile_width(self) -> int:
        """The fixed tile width of :meth:`replay_resident` tiles: the
        time-chunk rounded up to a power of two, inside the HBM cap. One width
        → one compiled program for the whole replay."""
        w = max(self.min_time_window, 1)
        target = max(self.time_chunk, 1)
        cap = self.resident_cap_width()
        while w < target and w < cap:
            w *= 2
        return w

    def warm_resident(self, resident: "ResidentCorpus") -> None:
        """Compile every program a :meth:`replay_resident` of this corpus will
        dispatch, against the real corpus buffers, with zero-trip work lists —
        and, under the dense layout, run the one-time tile gather — so a
        timed pass runs with zero in-window compiles and zero data prep."""
        b = resident.lengths.shape[0]
        if b == 0:
            return
        plan = self._plan_for(resident)
        key = frozenset(resident.derived_key.items())
        b_pad = resident.b_pad
        use_dense = self._use_dense(resident, plan)
        for bs, i0s, t_bases in ((plan.bs_big, plan.big_i0, plan.big_tb),
                                 (plan.bs_small, plan.small_i0, plan.small_tb)):
            if len(i0s) == 0:
                continue
            k_cap = self._plan_cap(len(i0s))
            slab, ord_d = self._fresh_slab(b_pad)
            if use_dense:
                dw, ds, i0s_d, tbs_d = self._dense_tiles(resident, plan, bs,
                                                         i0s, t_bases, k_cap)
                fold = self._resident_program_dense(key, plan.width, bs,
                                                    k_cap)
                # the dense trip count is static, so the warm pass runs the
                # REAL fold (into a discarded fresh slab) — that's also what
                # materializes the dense tile cache
                out = fold(slab, dw, ds, resident.lens_dev, ord_d,
                           i0s_d, tbs_d)
                jax.block_until_ready(out)
                self._signatures.add(("resident-dense", key, plan.width, bs,
                                      k_cap, b_pad))
                continue
            fold = self._resident_program(key, plan.width, bs, k_cap)
            wl = jnp.zeros((k_cap,), dtype=jnp.int32)
            out = fold(slab, resident.flat_wire, resident.flat_side,
                       resident.starts_dev, resident.lens_dev, ord_d,
                       wl, wl, np.int32(0))
            jax.block_until_ready(out)
            self._signatures.add(("resident", key, plan.width, bs, k_cap, b_pad, int(resident.flat_wire.shape[0])))

    def _resident_program(self, key: frozenset, width: int, bs: int,
                          k_cap: int):
        """The jitted whole-replay program for one derived-column declaration:
        ``(state_slab {f: [b_pad]}, flat_wire u8 [N, nbytes], side_flat,
        starts [b_pad], lens [b_pad], ord_base [b_pad], i0s [k_cap],
        t_bases [k_cap], k_n) -> state_slab``.

        A ``fori_loop`` over the tile work list; tile k folds events
        ``[t_bases[k], t_bases[k]+width)`` of lanes ``[i0s[k], i0s[k]+bs)``:
        per-lane contiguous ``dynamic_slice`` slabs out of the flat packed
        corpus (events of one aggregate are adjacent), byte→word expansion
        in-register, one transpose to time-major, a dense scan, and a
        contiguous write-back into the state slab. The trip count is traced,
        so one compiled program serves every corpus in the k_cap bucket and
        the whole replay crosses the host⇄device boundary exactly twice
        (dispatch in, states out)."""
        cache_key = (key, width, bs, k_cap)
        hit = self._resident_folds.get(cache_key)
        if hit is not None:
            return hit
        import jax

        wire = WireFormat(self.spec.registry, dict(key))
        tile = _make_tile(self.spec, wire, width, bs, self._unroll,
                          self._dispatch, self.tile_backend)

        def fold(slab_state, flat_wire, side_flat, starts_all, lens_all,
                 ord_all, i0s, t_bases, k_n):
            def body(k, st):
                return tile(st, flat_wire, side_flat, starts_all, lens_all,
                            ord_all, i0s[k], t_bases[k])

            return jax.lax.fori_loop(0, k_n, body, slab_state)

        donate = (0,) if self.donate_carry else ()
        jitted = jax.jit(fold, donate_argnums=donate)
        self._resident_folds[cache_key] = jitted
        return jitted

    def replay_ragged(self, logs: Sequence[Sequence[Any]],
                      encode: Callable[[Any], Any] | None = None,
                      init_carry: Mapping[str, Any] | None = None) -> ReplayResult:
        """Length-bucketed replay of ragged logs (SURVEY.md §5.7).

        Groups aggregates by log length into padded buckets, folds each bucket, and
        scatters results back into original order. ``encode`` (if given) maps each raw
        event to its tensor-schema form first — e.g. bank_account's host-side Vocab
        dictionary encoding. ``init_carry`` (``{field: [len(logs)]}``, e.g. from
        :meth:`carry_from_states` over checkpoint snapshots) resumes each
        aggregate's fold from its snapshot instead of the init record — the
        bounded tail fold of a checkpointed cold start.
        """
        from surge_tpu.codec.tensor import encode_events

        if encode is not None:
            logs = [[encode(e) for e in log] for log in logs]
        lengths = [len(l) for l in logs]
        groups = bucket_lengths(lengths, self.buckets)
        state_fields = self.spec.registry.state.fields
        out = {f.name: np.zeros((len(logs),), dtype=f.dtype) for f in state_fields}
        total_events = 0
        padded = 0
        for cap in sorted(groups):
            idxs = groups[cap]
            sub = [logs[i] for i in idxs]
            enc = encode_events(self.spec.registry, sub, pad_to=cap)
            sub_init = (None if init_carry is None else
                        {k: np.asarray(v)[idxs] for k, v in init_carry.items()})
            res = self.replay_encoded(enc, init_carry=sub_init)
            for name in out:
                out[name][idxs] = res.states[name]
            total_events += res.num_events
            padded += res.padded_events
        return ReplayResult(states=out, num_aggregates=len(logs),
                            num_events=total_events, padded_events=padded)

    def replay_columnar_chunks(self, chunks: Iterable[ColumnarEvents]) -> ReplayResult:
        """Fold a stream of aggregate-range chunks (each covering a DISJOINT set of
        aggregates — the columnar segment layout, surge_tpu.log.columnar): chunks
        replay independently and their state columns concatenate in order. The
        whole-log array never materializes in host memory at once."""
        state_fields = self.spec.registry.state.fields
        parts: dict[str, list[np.ndarray]] = {f.name: [] for f in state_fields}
        total_aggregates = total_events = padded = 0
        ids: list = []
        saw_ids = True
        for colev in chunks:
            res = self.replay_columnar(colev)
            for name in parts:
                parts[name].append(res.states[name])
            total_aggregates += res.num_aggregates
            total_events += res.num_events
            padded += res.padded_events
            if colev.aggregate_ids is None:
                saw_ids = False
            elif saw_ids:
                ids.extend(colev.aggregate_ids)
        if total_aggregates == 0:
            return ReplayResult(states={f.name: np.zeros((0,), dtype=f.dtype)
                                        for f in state_fields},
                                num_aggregates=0, num_events=0, padded_events=0,
                                aggregate_ids=[] if saw_ids else None)
        return ReplayResult(
            states={name: np.concatenate(arrs) for name, arrs in parts.items()},
            num_aggregates=total_aggregates, num_events=total_events,
            padded_events=padded, aggregate_ids=ids if saw_ids else None)

    def replay_stream(self, chunks: Iterable[EncodedEvents], batch: int,
                      init_carry: Mapping[str, Any] | None = None,
                      ordinal_base: np.ndarray | None = None) -> ReplayResult:
        """Fold a stream of EncodedEvents chunks (same B, consecutive time windows),
        carrying state across chunks — the 100M-event-log path where the whole encoded
        log never exists in HBM at once. Every window is padded to ``time-chunk`` width
        so one compiled program serves the entire stream."""
        bs = min(self.batch_size, _round_up(max(batch, 1), self._lane_multiple()))
        n_bchunks = max((batch + bs - 1) // bs, 1)
        carries: list[StateTree | None] = [None] * n_bchunks
        total_events = 0
        padded = 0
        t_cursor = 0  # global time offset of the current chunk (ordinal base)
        for enc in chunks:
            if enc.batch_size != batch:
                raise ValueError(f"stream chunk batch {enc.batch_size} != {batch}")
            t = enc.max_len
            for ci in range(n_bchunks):
                start, stop = ci * bs, min((ci + 1) * bs, batch)
                if carries[ci] is None:
                    carries[ci] = self._carry_slice(init_carry, start, stop, bs)
                carries[ci], scanned = self._fold_window(
                    carries[ci], enc.type_ids[start:stop],
                    {k: v[start:stop] for k, v in enc.cols.items()}, bs,
                    derived_cols=enc.derived_cols, t_base=t_cursor,
                    ordinal_base=None if ordinal_base is None
                    else ordinal_base[start:stop])
                padded += bs * scanned
            total_events += int(enc.lengths.sum())
            t_cursor += t
        if carries[0] is None:
            raise ValueError("empty chunk stream")
        state_fields = self.spec.registry.state.fields
        out = {f.name: np.zeros((batch,), dtype=f.dtype) for f in state_fields}
        for ci in range(n_bchunks):
            start, stop = ci * bs, min((ci + 1) * bs, batch)
            for name in out:
                out[name][start:stop] = np.asarray(carries[ci][name])[: stop - start]
        return ReplayResult(states=out, num_aggregates=batch,
                            num_events=total_events, padded_events=padded)


def _round_up(n: int, m: int) -> int:
    return ((n + m - 1) // m) * m if m > 0 else n
