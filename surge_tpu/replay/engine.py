"""Core batched fold: vmap(switch-step) scanned over time-major event columns.

Scale discipline (SURVEY.md §7 hard-part 2, BASELINE.md 1M-aggregate/100M-event target):

- **B-chunking**: ``surge.replay.batch-size`` bounds the aggregates resident on device at
  once; larger batches stream through in fixed-size chunks so HBM usage is constant and
  one compiled program serves every chunk.
- **T-chunking**: ``surge.replay.time-chunk`` bounds the scanned window; tail windows are
  padded to full width (padding is masked inside the step), again pinning compiled shapes.
- **Donation safety**: caller-visible carries are always copied into fresh padded host
  buffers before entering the donated jit, so external arrays are never consumed.
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Any, Callable, Iterable, Mapping, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from surge_tpu.codec.tensor import (
    ColumnarEvents,
    EncodedEvents,
    bucket_lengths,
    columnar_to_batch,
    encode_states,
)
from surge_tpu.codec.wire import WireFormat
from surge_tpu.config import Config, default_config
from surge_tpu.engine.model import ReplaySpec, StateTree


def make_step_fn(spec: ReplaySpec) -> Callable[[StateTree, Mapping[str, Any]], StateTree]:
    """One-event step for a single aggregate: dispatch on type_id, mask padding.

    The returned function is scalar over the batch dim (engine vmaps it). Any type_id
    outside ``[0, num_types)`` — padding (-1) or corrupt positive ids — carries state
    through unchanged rather than dispatching to an arbitrary handler.
    """
    num_types = spec.registry.num_event_types
    handlers = spec.handlers.ordered(num_types)
    state_fields = spec.registry.state.field_names

    def normalize(new: StateTree, old: StateTree) -> StateTree:
        # handlers may return partial dicts; missing columns carry through, and dtypes
        # are pinned to the schema so the scan carry shape is stable
        out = {}
        for name in state_fields:
            v = new.get(name, old[name])
            out[name] = jnp.asarray(v, dtype=old[name].dtype)
        return out

    def step(state: StateTree, event: Mapping[str, Any]) -> StateTree:
        tid = event["type_id"]
        branch = jnp.clip(tid, 0, num_types - 1)
        fields = {k: v for k, v in event.items() if k != "type_id"}
        wrapped = [
            (lambda h: lambda s: normalize(h(s, fields), s))(h) for h in handlers
        ]
        new_state = jax.lax.switch(branch, wrapped, state)
        is_real = (tid >= 0) & (tid < num_types)
        return {k: jnp.where(is_real, new_state[k], state[k]) for k in state}

    return step


def make_batch_fold(spec: ReplaySpec, *, unroll: int = 1):
    """Batched fold: ``(carry {name:[B]}, events {col:[T,B]}) -> carry``.

    The per-aggregate fold of CommandModels.scala:20-21 / PersistentActor's applyEvents,
    vectorized: ``lax.scan`` over T of ``vmap``-over-B of the switch step. jit-compiled by
    the caller (ReplayEngine) with carry donation.
    """
    step = make_step_fn(spec)
    vstep = jax.vmap(step, in_axes=(0, 0))

    def fold(carry: StateTree, events: Mapping[str, jnp.ndarray]) -> StateTree:
        def scan_body(c, ev_t):
            return vstep(c, ev_t), None

        out, _ = jax.lax.scan(scan_body, carry, events, unroll=unroll)
        return out

    return fold


@dataclass
class ResidentCorpus:
    """A corpus uploaded once to the device for gather-based replay."""

    derived_key: dict
    flat_word: Any  # u32 [N] on device
    flat_side: dict  # {name: [N]} on device
    starts: np.ndarray  # i32 [B] (length-sorted order)
    lengths: np.ndarray  # i32 [B]
    perm: Optional[np.ndarray]  # sorted-rank -> original index (None = identity)
    num_events: int
    wire_bytes: int  # bytes actually shipped to the device
    upload_s: float


@dataclass
class ReplayResult:
    """Folded states + accounting for throughput metrics."""

    states: dict[str, np.ndarray]  # {col: [B]} in the original aggregate order
    num_aggregates: int
    num_events: int
    padded_events: int  # B*T actually scanned (padding overhead indicator)
    # aggregate-id strings aligned with the state columns, when the inputs carried
    # them (segment chunks) — lets callers write states back to the keyed store
    aggregate_ids: Optional[list] = None


class ReplayEngine:
    """Drives batched replay for one model family.

    Equivalent role: the bulk-restore path of AggregateStateStoreKafkaStreams
    (common/.../kafka/streams/AggregateStateStoreKafkaStreams.scala:53-178) with
    ``replayBackend = tpu`` (BASELINE.json). Consumes ``EncodedEvents`` /
    ``ColumnarEvents`` batches (from surge_tpu.codec) and produces state columns; the
    KTable-equivalent store ingests the writeback.

    Parameters
    ----------
    spec: the model's ReplaySpec.
    config: batch size / time chunk / bucket knobs (``surge.replay.*``).
    mesh: optional ``jax.sharding.Mesh``; batch dim B is sharded over ``mesh_axis``.
    """

    def __init__(self, spec: ReplaySpec, config: Config | None = None,
                 mesh: Optional[jax.sharding.Mesh] = None,
                 mesh_axis: Optional[str] = None, unroll: int = 1) -> None:
        self.spec = spec
        self.config = config or default_config()
        self.mesh = mesh
        # batch-axis name: explicit arg > surge.replay.mesh-axes (first entry)
        if mesh_axis is None:
            mesh_axis = (self.config.get_str("surge.replay.mesh-axes", "data")
                         .split(",")[0].strip() or "data")
        self.mesh_axis = mesh_axis
        self.donate_carry = self.config.get_bool("surge.replay.donate-carry", True)
        self.time_chunk = self.config.get_int("surge.replay.time-chunk")
        self.min_time_window = self.config.get_int("surge.replay.min-time-window", 8)
        self.sort_by_length = self.config.get_bool("surge.replay.sort-by-length", True)
        lane = self._lane_multiple()
        self.batch_size = _round_up(
            max(self.config.get_int("surge.replay.batch-size"), lane), lane)
        self.buckets = self.config.get_int_list("surge.replay.length-buckets", "64,256,1024,4096")

        self._unroll = unroll
        # one (wire, jitted fold) per derived-column declaration the inputs carry —
        # in practice at most two: framework logs (ordinal seq) and object-test logs
        self._wire_folds: dict[frozenset, tuple[WireFormat, Any]] = {}
        # resident-corpus gather-folds, same keying
        self._resident_folds: dict[frozenset, Any] = {}
        # distinct (fold-variant, window-shape) signatures — every entry corresponds
        # to one XLA compilation (shapes are static under jit), counted without any
        # private JAX internals
        self._signatures: set = set()
        # host-side phase accounting (bench breakdown): seconds spent wire-packing
        # and explicitly transferring windows, and windows dispatched
        self.stats = {"pack_s": 0.0, "h2d_s": 0.0, "windows": 0}
        if mesh is not None:
            pspec = jax.sharding.PartitionSpec(mesh_axis)
            self._sharding = jax.sharding.NamedSharding(mesh, pspec)
            self._packed_sharding = jax.sharding.NamedSharding(
                mesh, jax.sharding.PartitionSpec(None, mesh_axis, None))
            self._ev_sharding = jax.sharding.NamedSharding(
                mesh, jax.sharding.PartitionSpec(None, mesh_axis))
        else:
            self._sharding = None
            self._packed_sharding = None
            self._ev_sharding = None

    def _wire_fold(self, derived_cols: Mapping[str, str]
                   ) -> tuple[frozenset, WireFormat, Any]:
        """The (cache key, WireFormat, jitted fold) triple for one derived-column
        declaration.

        The fold consumes wire-packed windows directly — decode happens inside the
        jit so XLA fuses unpacking into the scan and only wire bytes cross the link:
        ``fold(carry {name:[B]}, packed u8 [T,B,nbytes], side {name:[T,B]},
        ord_base i32 [B]) -> carry``.
        """
        key = frozenset(dict(derived_cols).items())
        hit = self._wire_folds.get(key)
        if hit is not None:
            return (key, *hit)
        wire = WireFormat(self.spec.registry, derived_cols)
        batch_fold = make_batch_fold(self.spec, unroll=self._unroll)

        def fold(carry: StateTree, packed, side, ord_base) -> StateTree:
            return batch_fold(carry, wire.decode(packed, side, ord_base))

        donate = (0,) if self.donate_carry else ()
        if self.mesh is not None:
            carry_sh = jax.tree_util.tree_map(lambda _: self._sharding,
                                              self._carry_struct())
            jitted = jax.jit(fold, donate_argnums=donate,
                             in_shardings=(carry_sh, None, None, None),
                             out_shardings=carry_sh)
        else:
            jitted = jax.jit(fold, donate_argnums=donate)
        self._wire_folds[key] = (wire, jitted)
        return key, wire, jitted

    # -- helpers ------------------------------------------------------------------------

    def _carry_struct(self) -> StateTree:
        return {f.name: None for f in self.spec.registry.state.fields}

    def _lane_multiple(self) -> int:
        """Pad B to a multiple of device count (for even mesh sharding) × 8."""
        n = 1 if self.mesh is None else int(np.prod(self.mesh.devices.shape))
        return max(8 * n, n)

    def num_compiles(self) -> int:
        """Compiled-program count across fold variants (compile-stability
        instrumentation): the number of distinct static shape signatures dispatched.
        Under ``jax.jit`` each distinct signature triggers exactly one compilation,
        so this equals the XLA program count without relying on private JAX APIs
        (VERDICT r3 weak #6)."""
        return len(self._signatures)

    def init_carry_np(self, batch: int) -> dict[str, np.ndarray]:
        """Host-side initial carry columns ``{name: [batch]}``."""
        init = self.spec.init_state_tree()
        return {k: np.broadcast_to(np.asarray(v), (batch,)).copy()
                for k, v in init.items()}

    def init_carry(self, batch: int) -> StateTree:
        carry = self.init_carry_np(batch)
        return self._device_carry(carry)

    def _device_carry(self, carry: Mapping[str, np.ndarray]) -> StateTree:
        if self._sharding is not None:
            return {k: jax.device_put(np.asarray(v), self._sharding)
                    for k, v in carry.items()}
        return {k: jnp.asarray(np.asarray(v)) for k, v in carry.items()}

    def carry_from_states(self, states: Sequence[Any]) -> dict[str, np.ndarray]:
        """Resume from snapshots (checkpointed carry, SURVEY.md §5.4 TPU mapping)."""
        return encode_states(self.spec.registry.state, states)

    def _carry_slice(self, init_carry: Mapping[str, Any] | None,
                     start: int, stop: int, bp: int,
                     idxs: np.ndarray | None = None) -> StateTree:
        """Fresh padded device carry for aggregates [start:stop) — or the explicit
        ``idxs`` gather when the batch was length-reordered. Donation-safe:
        external arrays are copied to host buffers first, never handed to the jit."""
        if init_carry is None:
            return self._device_carry(self.init_carry_np(bp))
        defaults = self.init_carry_np(bp)
        out = {}
        for k, full in init_carry.items():
            piece = (np.asarray(full)[idxs] if idxs is not None
                     else np.asarray(full)[start:stop])
            buf = defaults[k]
            buf[: len(piece)] = piece
            out[k] = buf
        return self._device_carry(out)

    def _device_window(self, packed: np.ndarray, side: Mapping[str, np.ndarray],
                       ord_base: np.ndarray):
        if self._ev_sharding is not None:
            return (jax.device_put(packed, self._packed_sharding),
                    {k: jax.device_put(v, self._ev_sharding) for k, v in side.items()},
                    jax.device_put(ord_base, self._sharding))
        return packed, side, ord_base

    # -- core entry points --------------------------------------------------------------

    def replay_encoded(self, enc: EncodedEvents,
                       init_carry: Mapping[str, Any] | None = None,
                       ordinal_base: np.ndarray | None = None) -> ReplayResult:
        """Fold one encoded batch. The aggregate axis is chunked to
        ``surge.replay.batch-size`` and the time axis to ``surge.replay.time-chunk`` so
        arbitrarily large batches and arbitrarily long (padded) logs stream through a
        fixed-size compiled program with bounded HBM.

        When resuming (``init_carry`` from a snapshot) and the batch declares derived
        ordinal columns, ``ordinal_base`` must carry each aggregate's already-folded
        event count ``[B]`` so the derived ordinals continue rather than restart."""
        b, t = enc.batch_size, enc.max_len
        bs = min(self.batch_size, _round_up(max(b, 1), self._lane_multiple()))
        state_fields = self.spec.registry.state.fields
        out = {f.name: np.zeros((b,), dtype=f.dtype) for f in state_fields}
        padded = 0

        for start in range(0, max(b, 1), bs):
            stop = min(start + bs, b)
            if stop <= start:
                break
            carry = self._carry_slice(init_carry, start, stop, bs)
            carry, scanned = self._fold_window(
                carry, enc.type_ids[start:stop],
                {k: v[start:stop] for k, v in enc.cols.items()}, bs,
                derived_cols=enc.derived_cols,
                ordinal_base=None if ordinal_base is None else ordinal_base[start:stop])
            for name in out:
                out[name][start:stop] = np.asarray(carry[name])[: stop - start]
            padded += bs * scanned

        return ReplayResult(states=out, num_aggregates=b,
                            num_events=int(enc.lengths.sum()), padded_events=padded)

    def replay_columnar(self, colev: ColumnarEvents,
                        init_carry: Mapping[str, Any] | None = None,
                        ordinal_base: np.ndarray | None = None) -> ReplayResult:
        """Fold a flat columnar log (the log-segment storage layout) directly.

        Densifies per B-chunk, never the whole batch: each chunk pads only to its own
        max log length, so host memory stays bounded by ``batch-size × local max T``
        even when one aggregate's log dwarfs the rest.

        With ``surge.replay.sort-by-length`` (default on) aggregates are ordered by
        log length before B-chunking, so a chunk's local max ≈ its members' lengths
        — together with the tail-window ladder this is the pad_ratio lever (VERDICT
        r3 next #2). Output state columns stay in the caller's aggregate order."""
        b = colev.num_aggregates
        bs = min(self.batch_size, _round_up(max(b, 1), self._lane_multiple()))
        # ordering only changes chunk composition when there IS more than one chunk
        if self.sort_by_length and b > bs:
            lengths_all = np.bincount(colev.agg_idx, minlength=b).astype(np.int64)
            perm = np.argsort(lengths_all, kind="stable").astype(np.int32)
            if np.array_equal(perm, np.arange(b, dtype=np.int32)):
                perm = None  # already length-ordered: skip the O(N) relabel
            else:
                inv = np.empty_like(perm)
                inv[perm] = np.arange(b, dtype=np.int32)
                # relabel each event's aggregate to its length rank; the stable
                # aggregate sort below then groups by rank while preserving each
                # aggregate's time order
                colev = ColumnarEvents(
                    num_aggregates=b, agg_idx=inv[colev.agg_idx],
                    type_ids=colev.type_ids, cols=colev.cols,
                    derived_cols=dict(colev.derived_cols))
        else:
            perm = None
        sorted_ev = colev.sorted_by_aggregate()
        state_fields = self.spec.registry.state.fields
        out = {f.name: np.zeros((b,), dtype=f.dtype) for f in state_fields}
        padded = 0
        total_events = 0
        for start in range(0, max(b, 1), bs):
            stop = min(start + bs, b)
            if stop <= start:
                break
            idxs = None if perm is None else perm[start:stop]
            enc = columnar_to_batch(sorted_ev.slice_aggregates(start, stop))
            carry = self._carry_slice(init_carry, start, stop, bs, idxs=idxs)
            ob = (None if ordinal_base is None else
                  np.asarray(ordinal_base)[idxs] if idxs is not None
                  else ordinal_base[start:stop])
            carry, scanned = self._fold_window(carry, enc.type_ids, enc.cols, bs,
                                               derived_cols=enc.derived_cols,
                                               ordinal_base=ob)
            for name in out:
                chunk_states = np.asarray(carry[name])[: stop - start]
                if idxs is None:
                    out[name][start:stop] = chunk_states
                else:
                    out[name][idxs] = chunk_states
            padded += bs * scanned
            total_events += int(enc.lengths.sum())
        return ReplayResult(states=out, num_aggregates=b,
                            num_events=total_events, padded_events=padded)

    def _window_plan(self, t: int) -> list[tuple[int, int]]:
        """Decompose a T-length window into ``(start, padded_width)`` pieces.

        Full pieces are ``time-chunk`` wide; the tail descends a power-of-two
        ladder down to ``min-time-window`` instead of padding to a full chunk —
        the T-quantization half of the pad_ratio lever (VERDICT r3 weak #2).
        Every width in the ladder is a distinct compiled program, so the program
        count stays bounded at ``1 + log2(chunk/min)`` per fold variant."""
        if t <= 0:
            return []  # nothing to fold: no dispatch (and no all-pad program)
        chunk = self.time_chunk if self.time_chunk > 0 else t
        plan = []
        s = 0
        while t - s >= chunk:
            plan.append((s, chunk))
            s += chunk
        rem = t - s
        if rem > 0 and self.min_time_window <= 0:
            plan.append((s, chunk))  # ladder disabled: full-pad tail
        elif rem > 0:
            # bit-decompose the tail into descending ladder windows so scanned
            # slots ≈ round_up(tail, min) — a single covering window would waste
            # up to 2× on the tail, which dominates when logs are much shorter
            # than a full time-chunk. Widths always come from ladder_widths()
            # (min × powers of two), never from halving the chunk, so a
            # non-power-of-two time-chunk cannot produce sub-min or
            # unpredictable widths.
            ladder = self.ladder_widths()
            w = ladder[-1]
            while rem > 0:
                while w > ladder[0] and w > rem:
                    w //= 2
                plan.append((s, w))
                take = min(w, rem)
                s += take
                rem -= take
        return plan

    def ladder_widths(self) -> list[int]:
        """The tail-window widths _window_plan can dispatch (ascending):
        ``min-time-window × 2^k``, strictly below the time-chunk (a tail is
        always < chunk, so a chunk-sized ladder entry could never fire). Every
        entry is a distinct compiled program; warm-up should cover all of them
        plus the full chunk (see bench.py)."""
        min_w = max(self.min_time_window, 1)
        chunk = self.time_chunk if self.time_chunk > 0 else min_w
        ladder = [min_w]
        while ladder[-1] * 2 < chunk:
            ladder.append(ladder[-1] * 2)
        return ladder

    def _fold_window(self, carry: StateTree, type_ids: np.ndarray,
                     cols: Mapping[str, np.ndarray], bs: int,
                     derived_cols: Mapping[str, str] | None = None,
                     t_base: int = 0,
                     ordinal_base: np.ndarray | None = None
                     ) -> tuple[StateTree, int]:
        """Fold one [b?, T] window (b? ≤ bs) through T-chunked fixed-width programs;
        returns ``(carry, scanned_t)`` where scanned_t is the padded slot count per
        aggregate actually dispatched.

        Each chunk is wire-packed on the host (uint8 word + side columns) and decoded
        inside the fold jit. The ordinal base of device-derived positional columns is
        ``ordinal_base[b] + t_base + s``: per-aggregate already-folded event counts
        (resume) plus the window's global time offset (replay_stream's cumulative
        width of prior chunks)."""
        key, wire, fold = self._wire_fold(derived_cols or {})
        b, t = type_ids.shape
        base = np.zeros((bs,), dtype=np.int32)
        if ordinal_base is not None:
            base[:b] = np.asarray(ordinal_base, dtype=np.int32)[:b]
        scanned = 0
        for s, width in self._window_plan(t):
            e = min(s + width, t)
            t0 = time.perf_counter()
            packed, side = wire.pack_window(type_ids, cols, s, e, width, bs)
            ord_base = base + np.int32(t_base + s)
            t1 = time.perf_counter()
            window = self._device_window(packed, side, ord_base)
            t2 = time.perf_counter()
            self.stats["pack_s"] += t1 - t0
            self.stats["h2d_s"] += t2 - t1
            self.stats["windows"] += 1
            scanned += width
            self._signatures.add(
                (key, packed.shape, tuple((k, v.shape) for k, v in sorted(side.items()))))
            carry = fold(carry, *window)
        return carry, scanned

    # -- resident-corpus path (single upload, on-device densify) ------------------------

    def prepare_resident(self, colev: ColumnarEvents) -> "ResidentCorpus":
        """Upload the WHOLE corpus once as a flat wire buffer (exactly
        ``wire_bytes_per_event()`` per event — zero padding crosses the link)
        and return a handle for :meth:`replay_resident`.

        Every subsequent fold dispatch gathers its window on-device from the
        resident buffer, so per-window transfer drops to the B-chunk's
        starts/lens (KBs) — the right shape for hosts where the device link,
        not the fold, is the bottleneck (tunneled TPU; and on local hardware it
        turns replay into one streaming upload)."""
        import jax

        b = colev.num_aggregates
        lengths = np.bincount(colev.agg_idx, minlength=b).astype(np.int64)
        if self.sort_by_length and b > 1:
            perm = np.argsort(lengths, kind="stable").astype(np.int32)
            if np.array_equal(perm, np.arange(b, dtype=np.int32)):
                perm = None
            else:
                inv = np.empty_like(perm)
                inv[perm] = np.arange(b, dtype=np.int32)
                colev = ColumnarEvents(
                    num_aggregates=b, agg_idx=inv[colev.agg_idx],
                    type_ids=colev.type_ids, cols=colev.cols,
                    derived_cols=dict(colev.derived_cols))
                lengths = lengths[perm]
        else:
            perm = None
        sorted_ev = colev.sorted_by_aggregate()
        key, wire, _ = self._wire_fold(sorted_ev.derived_cols)
        t0 = time.perf_counter()
        packed, side_flat = wire.pack_flat(sorted_ev.type_ids, sorted_ev.cols)
        # tail padding so every [start + t_base, width) slab slice stays in
        # bounds without clamping (clamped slices would shift lane data);
        # content is irrelevant — slots past lens decode to the pad sentinel
        guard = self.resident_cap_width()
        packed = np.pad(packed, ((0, guard), (0, 0)))
        side_flat = {k: np.pad(v, (0, guard)) for k, v in side_flat.items()}
        self.stats["pack_s"] += time.perf_counter() - t0
        t0 = time.perf_counter()
        flat_word = jax.jit(wire.expand_flat)(jax.device_put(packed))
        flat_side = {k: jax.device_put(v) for k, v in side_flat.items()}
        jax.block_until_ready(flat_word)
        upload_s = time.perf_counter() - t0
        self.stats["h2d_s"] += upload_s
        starts = np.zeros(b + 1, dtype=np.int64)
        np.cumsum(lengths, out=starts[1:])
        return ResidentCorpus(
            derived_key=dict(sorted_ev.derived_cols), flat_word=flat_word,
            flat_side=flat_side, starts=starts[:-1].astype(np.int32),
            lengths=lengths.astype(np.int32), perm=perm,
            num_events=sorted_ev.num_events,
            wire_bytes=packed.nbytes + sum(v.nbytes for v in side_flat.values()),
            upload_s=upload_s)

    def replay_resident(self, resident: "ResidentCorpus",
                        init_carry: Mapping[str, Any] | None = None,
                        ordinal_base: np.ndarray | None = None) -> ReplayResult:
        """Fold a prepared resident corpus. Results are in the ORIGINAL
        aggregate order of the ColumnarEvents given to :meth:`prepare_resident`."""
        if self.mesh is not None:
            raise NotImplementedError(
                "resident-corpus replay is single-device; use replay_columnar "
                "for mesh-sharded folds")
        b = resident.lengths.shape[0]
        bs = min(self.batch_size, _round_up(max(b, 1), self._lane_multiple()))
        key = frozenset(resident.derived_key.items())
        fold = self._gather_fold(key)
        state_fields = self.spec.registry.state.fields
        out = {f.name: np.zeros((b,), dtype=f.dtype) for f in state_fields}
        padded = 0
        for start in range(0, max(b, 1), bs):
            stop = min(start + bs, b)
            if stop <= start:
                break
            idxs = None if resident.perm is None else resident.perm[start:stop]
            starts_c = np.zeros((bs,), dtype=np.int32)
            lens_c = np.zeros((bs,), dtype=np.int32)
            starts_c[: stop - start] = resident.starts[start:stop]
            lens_c[: stop - start] = resident.lengths[start:stop]
            carry = self._carry_slice(init_carry, start, stop, bs, idxs=idxs)
            ob = np.zeros((bs,), dtype=np.int32)
            if ordinal_base is not None:
                src = (np.asarray(ordinal_base)[idxs] if idxs is not None
                       else np.asarray(ordinal_base)[start:stop])
                ob[: stop - start] = src.astype(np.int32)
            # ONE dispatch per B-chunk (padding the scan costs compute only —
            # nothing crosses the link): width is the next power of two ≥ the
            # chunk's longest log, split into slab-cap-sized dispatches only
            # when the HBM budget demands it. Programs stay bounded by the
            # pow2 ladder.
            t_local = int(lens_c.max(initial=0))
            cap_w = self.resident_cap_width()
            t_base = 0
            while t_base < t_local:
                rem = t_local - t_base
                width = max(self.min_time_window, 1)
                while width < rem and width < cap_w:
                    width *= 2
                self.stats["windows"] += 1
                self._signatures.add(("resident", key, width, bs))
                carry = fold(carry, resident.flat_word, resident.flat_side,
                             starts_c, lens_c, ob, np.int32(t_base), width)
                padded += bs * width
                t_base += width
            chunk_states = {name: np.asarray(carry[name])[: stop - start]
                            for name in out}
            for name in out:
                if idxs is None:
                    out[name][start:stop] = chunk_states[name]
                else:
                    out[name][idxs] = chunk_states[name]
        return ReplayResult(states=out, num_aggregates=b,
                            num_events=resident.num_events,
                            padded_events=padded)

    def resident_cap_width(self) -> int:
        """Largest slab scan width the HBM budget allows (pow2 multiple of the
        min window): one dispatch materializes a [batch, width] u32 slab and
        its transpose, so width is capped by resident-slab-cap-mb."""
        budget = self.config.get_int("surge.replay.resident-slab-cap-mb", 512)
        w = max(self.min_time_window, 1)
        while w * 2 * self.batch_size * 8 <= budget * 1_000_000:
            w *= 2
        return w

    def resident_widths(self, max_len: int) -> list[int]:
        """Every scan width :meth:`replay_resident` can dispatch for logs up to
        ``max_len`` (min-time-window × powers of two, capped by the slab
        budget) — the warm-up set."""
        cap = self.resident_cap_width()
        w = max(self.min_time_window, 1)
        out = [w]
        while out[-1] < max_len and out[-1] < cap:
            out.append(out[-1] * 2)
        return out

    def _gather_fold(self, key: frozenset):
        """The jitted resident fold for one derived-column declaration:
        ``(carry, flat_word [N], side_flat, starts [B], lens [B], ord_base [B],
        t_base, width·static) -> carry``.

        Extraction strategy (measured on the tunneled v5e): per-element gathers
        run ~70M elem/s but per-lane CONTIGUOUS ``dynamic_slice`` slabs run
        4-5× faster and the dense fold runs at GB/s — so each dispatch slices
        one ``[B, width]`` slab per lane (events of one aggregate are adjacent
        in the flat corpus), transposes once to time-major, and scans dense
        rows. ``width`` is static, so programs stay bounded by the pow2
        ladder."""
        hit = self._resident_folds.get(key)
        if hit is not None:
            return hit
        import jax

        wire = WireFormat(self.spec.registry, dict(key))
        batch_step = jax.vmap(make_step_fn(self.spec), in_axes=(0, 0))

        def fold(carry, flat_word, side_flat, starts, lens, ord_base, t_base,
                 width):
            import jax.numpy as jnp

            def slab(arr):
                cut = jax.vmap(
                    lambda s0: jax.lax.dynamic_slice(arr, (s0,), (width,)))
                return cut(starts + t_base).T  # [width, B], rows contiguous

            words = slab(flat_word)
            sides = {name: slab(arr) for name, arr in side_flat.items()}
            ts = jnp.arange(width, dtype=jnp.int32) + t_base

            def body(c, xs):
                word, side_row, t = xs
                events = wire.decode_words(word, side_row, t < lens, ord_base, t)
                return batch_step(c, events), None

            out, _ = jax.lax.scan(body, carry, (words, sides, ts),
                                  unroll=self._unroll)
            return out

        donate = (0,) if self.donate_carry else ()
        jitted = jax.jit(fold, donate_argnums=donate, static_argnums=(7,))
        self._resident_folds[key] = jitted
        return jitted

    def replay_ragged(self, logs: Sequence[Sequence[Any]],
                      encode: Callable[[Any], Any] | None = None) -> ReplayResult:
        """Length-bucketed replay of ragged logs (SURVEY.md §5.7).

        Groups aggregates by log length into padded buckets, folds each bucket, and
        scatters results back into original order. ``encode`` (if given) maps each raw
        event to its tensor-schema form first — e.g. bank_account's host-side Vocab
        dictionary encoding.
        """
        from surge_tpu.codec.tensor import encode_events

        if encode is not None:
            logs = [[encode(e) for e in log] for log in logs]
        lengths = [len(l) for l in logs]
        groups = bucket_lengths(lengths, self.buckets)
        state_fields = self.spec.registry.state.fields
        out = {f.name: np.zeros((len(logs),), dtype=f.dtype) for f in state_fields}
        total_events = 0
        padded = 0
        for cap in sorted(groups):
            idxs = groups[cap]
            sub = [logs[i] for i in idxs]
            enc = encode_events(self.spec.registry, sub, pad_to=cap)
            res = self.replay_encoded(enc)
            for name in out:
                out[name][idxs] = res.states[name]
            total_events += res.num_events
            padded += res.padded_events
        return ReplayResult(states=out, num_aggregates=len(logs),
                            num_events=total_events, padded_events=padded)

    def replay_columnar_chunks(self, chunks: Iterable[ColumnarEvents]) -> ReplayResult:
        """Fold a stream of aggregate-range chunks (each covering a DISJOINT set of
        aggregates — the columnar segment layout, surge_tpu.log.columnar): chunks
        replay independently and their state columns concatenate in order. The
        whole-log array never materializes in host memory at once."""
        state_fields = self.spec.registry.state.fields
        parts: dict[str, list[np.ndarray]] = {f.name: [] for f in state_fields}
        total_aggregates = total_events = padded = 0
        ids: list = []
        saw_ids = True
        for colev in chunks:
            res = self.replay_columnar(colev)
            for name in parts:
                parts[name].append(res.states[name])
            total_aggregates += res.num_aggregates
            total_events += res.num_events
            padded += res.padded_events
            if colev.aggregate_ids is None:
                saw_ids = False
            elif saw_ids:
                ids.extend(colev.aggregate_ids)
        if total_aggregates == 0:
            return ReplayResult(states={f.name: np.zeros((0,), dtype=f.dtype)
                                        for f in state_fields},
                                num_aggregates=0, num_events=0, padded_events=0,
                                aggregate_ids=[] if saw_ids else None)
        return ReplayResult(
            states={name: np.concatenate(arrs) for name, arrs in parts.items()},
            num_aggregates=total_aggregates, num_events=total_events,
            padded_events=padded, aggregate_ids=ids if saw_ids else None)

    def replay_stream(self, chunks: Iterable[EncodedEvents], batch: int,
                      init_carry: Mapping[str, Any] | None = None,
                      ordinal_base: np.ndarray | None = None) -> ReplayResult:
        """Fold a stream of EncodedEvents chunks (same B, consecutive time windows),
        carrying state across chunks — the 100M-event-log path where the whole encoded
        log never exists in HBM at once. Every window is padded to ``time-chunk`` width
        so one compiled program serves the entire stream."""
        bs = min(self.batch_size, _round_up(max(batch, 1), self._lane_multiple()))
        n_bchunks = max((batch + bs - 1) // bs, 1)
        carries: list[StateTree | None] = [None] * n_bchunks
        total_events = 0
        padded = 0
        t_cursor = 0  # global time offset of the current chunk (ordinal base)
        for enc in chunks:
            if enc.batch_size != batch:
                raise ValueError(f"stream chunk batch {enc.batch_size} != {batch}")
            t = enc.max_len
            for ci in range(n_bchunks):
                start, stop = ci * bs, min((ci + 1) * bs, batch)
                if carries[ci] is None:
                    carries[ci] = self._carry_slice(init_carry, start, stop, bs)
                carries[ci], scanned = self._fold_window(
                    carries[ci], enc.type_ids[start:stop],
                    {k: v[start:stop] for k, v in enc.cols.items()}, bs,
                    derived_cols=enc.derived_cols, t_base=t_cursor,
                    ordinal_base=None if ordinal_base is None
                    else ordinal_base[start:stop])
                padded += bs * scanned
            total_events += int(enc.lengths.sum())
            t_cursor += t
        if carries[0] is None:
            raise ValueError("empty chunk stream")
        state_fields = self.spec.registry.state.fields
        out = {f.name: np.zeros((batch,), dtype=f.dtype) for f in state_fields}
        for ci in range(n_bchunks):
            start, stop = ci * bs, min((ci + 1) * bs, batch)
            for name in out:
                out[name][start:stop] = np.asarray(carries[ci][name])[: stop - start]
        return ReplayResult(states=out, num_aggregates=batch,
                            num_events=total_events, padded_events=padded)


def _round_up(n: int, m: int) -> int:
    return ((n + m - 1) // m) * m if m > 0 else n
