"""Core batched fold: vmap(switch-step) scanned over time-major event columns."""

from __future__ import annotations

import math
from dataclasses import dataclass
from functools import partial
from typing import Any, Callable, Mapping, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from surge_tpu.codec.tensor import PAD_TYPE_ID, EncodedEvents, bucket_lengths, encode_states
from surge_tpu.config import Config, default_config
from surge_tpu.engine.model import ReplaySpec, StateTree


def make_step_fn(spec: ReplaySpec) -> Callable[[StateTree, Mapping[str, Any]], StateTree]:
    """One-event step for a single aggregate: dispatch on type_id, mask padding.

    The returned function is scalar over the batch dim (engine vmaps it). Padding
    (``type_id == PAD_TYPE_ID``) must leave state untouched — scans run to the padded
    length for every lane.
    """
    num_types = spec.registry.num_event_types
    handlers = spec.handlers.ordered(num_types)
    state_fields = spec.registry.state.field_names

    def normalize(new: StateTree, old: StateTree) -> StateTree:
        # handlers may return partial dicts; missing columns carry through, and dtypes
        # are pinned to the schema so the scan carry shape is stable
        out = {}
        for name in state_fields:
            v = new.get(name, old[name])
            out[name] = jnp.asarray(v, dtype=old[name].dtype)
        return out

    def step(state: StateTree, event: Mapping[str, Any]) -> StateTree:
        tid = event["type_id"]
        branch = jnp.clip(tid, 0, num_types - 1)
        fields = {k: v for k, v in event.items() if k != "type_id"}
        wrapped = [
            (lambda h: lambda s: normalize(h(s, fields), s))(h) for h in handlers
        ]
        new_state = jax.lax.switch(branch, wrapped, state)
        is_real = tid != PAD_TYPE_ID
        return {k: jnp.where(is_real, new_state[k], state[k]) for k in state}

    return step


def make_batch_fold(spec: ReplaySpec, *, unroll: int = 1):
    """Batched fold: ``(carry {name:[B]}, events {col:[T,B]}) -> carry``.

    The per-aggregate fold of CommandModels.scala:20-21 / PersistentActor's applyEvents,
    vectorized: ``lax.scan`` over T of ``vmap``-over-B of the switch step. jit-compiled by
    the caller (ReplayEngine) with carry donation.
    """
    step = make_step_fn(spec)
    vstep = jax.vmap(step, in_axes=(0, 0))

    def fold(carry: StateTree, events: Mapping[str, jnp.ndarray]) -> StateTree:
        def scan_body(c, ev_t):
            return vstep(c, ev_t), None

        out, _ = jax.lax.scan(scan_body, carry, events, unroll=unroll)
        return out

    return fold


@dataclass
class ReplayResult:
    """Folded states + accounting for throughput metrics."""

    states: dict[str, np.ndarray]  # {col: [B]} in the original aggregate order
    num_aggregates: int
    num_events: int
    padded_events: int  # B*T actually scanned (padding overhead indicator)


class ReplayEngine:
    """Drives batched replay for one model family.

    Equivalent role: the bulk-restore path of AggregateStateStoreKafkaStreams
    (common/.../kafka/streams/AggregateStateStoreKafkaStreams.scala:53-178) with
    ``replayBackend = tpu`` (BASELINE.json). Consumes ``EncodedEvents`` batches (from
    surge_tpu.codec) and produces state columns; the KTable-equivalent store ingests the
    writeback.

    Parameters
    ----------
    spec: the model's ReplaySpec.
    config: batch size / time chunk / bucket knobs (``surge.replay.*``).
    mesh: optional ``jax.sharding.Mesh``; batch dim B is sharded over ``mesh_axis``.
    """

    def __init__(self, spec: ReplaySpec, config: Config | None = None,
                 mesh: Optional[jax.sharding.Mesh] = None, mesh_axis: str = "data",
                 unroll: int = 1) -> None:
        self.spec = spec
        self.config = config or default_config()
        self.mesh = mesh
        self.mesh_axis = mesh_axis
        self.time_chunk = self.config.get_int("surge.replay.time-chunk")
        self.batch_size = self.config.get_int("surge.replay.batch-size")
        self.buckets = self.config.get_int_list("surge.replay.length-buckets", "64,256,1024,4096")

        fold = make_batch_fold(spec, unroll=unroll)
        if mesh is not None:
            pspec = jax.sharding.PartitionSpec(mesh_axis)
            sharding = jax.sharding.NamedSharding(mesh, pspec)
            carry_sh = jax.tree_util.tree_map(lambda _: sharding, self._carry_struct())
            self._fold = jax.jit(fold, donate_argnums=(0,),
                                 in_shardings=(carry_sh, None), out_shardings=carry_sh)
            self._sharding = sharding
        else:
            self._fold = jax.jit(fold, donate_argnums=(0,))
            self._sharding = None

    # -- helpers ------------------------------------------------------------------------

    def _carry_struct(self) -> StateTree:
        return {f.name: None for f in self.spec.registry.state.fields}

    def _lane_multiple(self) -> int:
        """Pad B to a multiple of device count (for even mesh sharding) × 8."""
        n = 1 if self.mesh is None else int(np.prod(self.mesh.devices.shape))
        return max(8 * n, n)

    def init_carry(self, batch: int) -> StateTree:
        init = self.spec.init_state_tree()
        carry = {k: jnp.broadcast_to(jnp.asarray(v), (batch,)) for k, v in init.items()}
        if self._sharding is not None:
            carry = jax.device_put(carry, self._sharding)
        return {k: jnp.asarray(v) for k, v in carry.items()}

    def carry_from_states(self, states: Sequence[Any]) -> StateTree:
        """Resume from snapshots (checkpointed carry, SURVEY.md §5.4 TPU mapping)."""
        tree = encode_states(self.spec.registry.state, states)
        return {k: jnp.asarray(v) for k, v in tree.items()}

    # -- core entry points --------------------------------------------------------------

    def replay_encoded(self, enc: EncodedEvents,
                       init_carry: StateTree | None = None) -> ReplayResult:
        """Fold one encoded batch. Time axis is chunked to ``time_chunk`` so arbitrarily
        long (padded) logs stream through a fixed-size compiled program."""
        b, t = enc.batch_size, enc.max_len
        pad_b = -b % self._lane_multiple()
        bp = b + pad_b

        type_ids = np.full((bp, t), PAD_TYPE_ID, dtype=np.int32)
        type_ids[:b] = enc.type_ids
        cols = {}
        for name, col in enc.cols.items():
            buf = np.zeros((bp, t), dtype=col.dtype)
            buf[:b] = col
            cols[name] = buf

        carry = init_carry if init_carry is not None else self.init_carry(bp)
        if init_carry is not None and next(iter(carry.values())).shape[0] != bp:
            carry = {k: jnp.concatenate(
                [jnp.asarray(v), jnp.zeros((bp - v.shape[0],), dtype=v.dtype)])
                for k, v in carry.items()}
        if self._sharding is not None:
            carry = jax.device_put(carry, self._sharding)

        chunk = self.time_chunk if self.time_chunk > 0 else t
        for start in range(0, t, max(chunk, 1)):
            stop = min(start + chunk, t)
            width = stop - start
            # keep the compiled program count low: pad the tail chunk to full width
            ev = {"type_id": _time_major(type_ids, start, stop, chunk, PAD_TYPE_ID)}
            for name, col in cols.items():
                ev[name] = _time_major(col, start, stop, chunk, 0)
            if self._sharding is not None:
                col_sh = jax.sharding.NamedSharding(
                    self.mesh, jax.sharding.PartitionSpec(None, self.mesh_axis))
                ev = {k: jax.device_put(v, col_sh) for k, v in ev.items()}
            carry = self._fold(carry, ev)
            del width

        states = {k: np.asarray(v)[:b] for k, v in carry.items()}
        return ReplayResult(states=states, num_aggregates=b,
                            num_events=int(enc.lengths.sum()), padded_events=bp * t)

    def replay_ragged(self, registry_enc_logs: Sequence[Sequence[Any]],
                      encode=None) -> ReplayResult:
        """Length-bucketed replay of ragged logs (SURVEY.md §5.7).

        Groups aggregates by log length into padded buckets, folds each bucket, and
        scatters results back into original order.
        """
        from surge_tpu.codec.tensor import encode_events

        logs = registry_enc_logs
        lengths = [len(l) for l in logs]
        groups = bucket_lengths(lengths, self.buckets)
        state_fields = self.spec.registry.state.fields
        out = {f.name: np.zeros((len(logs),), dtype=f.dtype) for f in state_fields}
        total_events = 0
        padded = 0
        for cap in sorted(groups):
            idxs = groups[cap]
            sub = [logs[i] for i in idxs]
            enc = encode_events(self.spec.registry, sub, pad_to=cap)
            res = self.replay_encoded(enc)
            for name in out:
                out[name][idxs] = res.states[name]
            total_events += res.num_events
            padded += res.padded_events
        return ReplayResult(states=out, num_aggregates=len(logs),
                            num_events=total_events, padded_events=padded)

    def replay_stream(self, chunks, batch: int) -> ReplayResult:
        """Fold a stream of EncodedEvents chunks (same B, consecutive time windows),
        carrying state across chunks — the 100M-event-log path where the whole encoded
        log never exists in HBM at once."""
        carry = None
        total_events = 0
        padded = 0
        bp = None
        for enc in chunks:
            if carry is None:
                b = enc.batch_size
                pad_b = -b % self._lane_multiple()
                bp = b + pad_b
                carry = self.init_carry(bp)
            res_carry = self._fold_chunk(carry, enc, bp)
            carry = res_carry
            total_events += int(enc.lengths.sum())
            padded += bp * enc.max_len
        if carry is None:
            raise ValueError("empty chunk stream")
        states = {k: np.asarray(v)[:batch] for k, v in carry.items()}
        return ReplayResult(states=states, num_aggregates=batch,
                            num_events=total_events, padded_events=padded)

    def _fold_chunk(self, carry: StateTree, enc: EncodedEvents, bp: int) -> StateTree:
        b, t = enc.batch_size, enc.max_len
        type_ids = np.full((bp, t), PAD_TYPE_ID, dtype=np.int32)
        type_ids[:b] = enc.type_ids
        ev = {"type_id": np.ascontiguousarray(type_ids.T)}
        for name, col in enc.cols.items():
            buf = np.zeros((bp, t), dtype=col.dtype)
            buf[:b] = col
            ev[name] = np.ascontiguousarray(buf.T)
        if self._sharding is not None:
            col_sh = jax.sharding.NamedSharding(
                self.mesh, jax.sharding.PartitionSpec(None, self.mesh_axis))
            ev = {k: jax.device_put(v, col_sh) for k, v in ev.items()}
        return self._fold(carry, ev)


def _time_major(col: np.ndarray, start: int, stop: int, chunk: int, pad_value) -> np.ndarray:
    """Slice [B, start:stop], pad to ``chunk`` wide, return time-major [chunk, B]."""
    piece = col[:, start:stop]
    width = stop - start
    if chunk and width < chunk:
        pad = np.full((col.shape[0], chunk - width), pad_value, dtype=col.dtype)
        piece = np.concatenate([piece, pad], axis=1)
    return np.ascontiguousarray(piece.T)
