"""Version-bridging shims for the narrow slice of jax API the replay engine
uses where the surface moved between releases.

``shard_map`` graduated from ``jax.experimental.shard_map`` (where the
skip-the-replication-check kwarg is ``check_rep``) to ``jax.shard_map`` (where
it was renamed ``check_vma``); images pinned to 0.4.x only ship the
experimental spelling, and 0.7+ hard-removes it. One call site, one shim.
"""

from __future__ import annotations

import jax

__all__ = ["shard_map"]


def shard_map(f, *, mesh, in_specs, out_specs, check_vma: bool = True):
    """``jax.shard_map`` wherever it lives, with the vma/rep kwarg bridged."""
    sm = getattr(jax, "shard_map", None)
    if sm is not None:
        return sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                  check_vma=check_vma)
    from jax.experimental.shard_map import shard_map as exp_sm

    return exp_sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                  check_rep=check_vma)
