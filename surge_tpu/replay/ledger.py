"""Refresh-round ledger: the device observatory's bounded flight ring.

The resident plane's fold wall is invisible in coarse instruments: the
round timer says a fold took 4 ms, but not that 9 of every 10 dispatched
event slots were padding (BENCH_NOTES round 9 — the ~8 µs/event-slot,
~9× over-dispatch wall ROADMAP item 2 attacks). This module records every
refresh round's anatomy into a bounded ring in the flight-recorder shape:

- ``round`` — lanes dealt, events folded, dispatched vs occupied event
  slots (the padding-waste ratio), per-stage wall µs (feed/decode → encode
  → dispatch; the h2d rides the dispatch on the refresh path), window/batch
  bucketing, per-shard lane-deal sizes on the mesh path, and the round's
  fallback-cause deltas;
- ``gather`` — one batched-read drain: reads coalesced, rows gathered,
  coalesce wait and dispatch→fetch-barrier→decode µs;
- ``query`` — one scan/state query: rows, scanned/matched events
  (pushdown selectivity), elapsed µs.

Recording is allocation-cheap (one tuple into a ``deque`` under a short
lock — the :class:`~surge_tpu.observability.flight.FlightRecorder`
discipline) so the sites stay armed in production, NOT debug-gated: you
cannot attack over-dispatch you cannot continuously measure. ``dump()``
emits the exact flight envelope (``events`` + the mono↔wall header pair),
so a ledger dump interleaves with engine/broker flight dumps through
:func:`~surge_tpu.observability.flight.merge_dumps` and a device stall
lands on incident timelines next to the breach that paged. The
``DumpReplayLedger`` admin RPC pulls it; ``tools/roofline_record.py``
snapshots :meth:`ReplayLedger.summary` into append-only JSONL rows
comparable against docs/roofline.md.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

from surge_tpu.observability.flight import FlightRecorder

__all__ = ["ReplayLedger", "shard_skew", "waste_ratio"]


def waste_ratio(dispatched: float, occupied: float) -> float:
    """Dispatched/occupied event slots of a round (1.0 = zero padding).
    A round that folded nothing reports 0.0 — "no work" must be tellable
    apart from "perfectly packed work"."""
    if occupied <= 0:
        return 0.0
    return dispatched / occupied


def shard_skew(deal_sizes: Optional[Sequence[int]]) -> float:
    """Max/mean lane-deal imbalance across mesh shards (1.0 = balanced;
    single-device rounds and empty deals read 1.0)."""
    if not deal_sizes:
        return 1.0
    total = sum(deal_sizes)
    if total <= 0:
        return 1.0
    mean = total / len(deal_sizes)
    return max(deal_sizes) / mean


class ReplayLedger(FlightRecorder):
    """Bounded ring of refresh-round / gather / query anatomy events.

    A :class:`FlightRecorder` subclass: same thread-safe ring, same
    merge-ready dump envelope (``role="ledger"`` puts the rounds on their
    own lane of a merged timeline). On top of the ring it keeps cheap
    cumulative totals (under the same lock discipline — single bumps of
    plain ints/floats), so :meth:`summary` can answer the roofline
    questions (measured ev/s, µs/slot, waste ratio) without walking the
    ring.
    """

    def __init__(self, capacity: int = 512, name: str = "",
                 role: str = "ledger") -> None:
        super().__init__(capacity=capacity, name=name, role=role)
        self.totals: Dict[str, float] = {
            "rounds": 0, "events": 0, "lanes": 0, "windows": 0,
            "dispatched_slots": 0, "occupied_slots": 0,
            "dispatch_us": 0.0, "encode_us": 0.0, "feed_us": 0.0,
            "bucket_programs": 0, "bucket_lane_slots": 0,
            "gathers": 0, "gathered_rows": 0, "gather_wait_us": 0.0,
            "queries": 0, "query_rows": 0,
            "view_rounds": 0, "view_delta_rows": 0, "view_fold_us": 0.0,
        }

    # -- recording sites ----------------------------------------------------------------

    def record_round(self, *, events: int, lanes: int, windows: int,
                     dispatched: int, occupied: int, batch: int, width: int,
                     feed_us: float, encode_us: float, dispatch_us: float,
                     deal_sizes: Optional[Sequence[int]] = None,
                     causes: Optional[Dict[str, int]] = None,
                     evictions: int = 0,
                     buckets: Optional[Sequence[Dict]] = None,
                     bucket_table: Optional[int] = None) -> None:
        """One refresh round's anatomy. ``dispatched``/``occupied`` are
        event SLOTS (lane bucket × window width summed over the round's
        window dispatches vs events actually folded); ``causes`` carries
        the round's fallback-cause deltas; ``deal_sizes`` the per-shard
        lane-deal lengths on the mesh path (None single-device).

        ``buckets`` (bucketed refresh dispatch, ISSUE 18) carries one dict
        per fused bucket program the round issued — ``{width, lanes_b,
        lanes, windows, dispatched, occupied, ragged}`` — and
        ``bucket_table`` the size of the layout's bounded compile-signature
        table; both optional so pre-bucketing callers stay source-compatible."""
        t = self.totals
        t["rounds"] += 1
        t["events"] += events
        t["lanes"] += lanes
        t["windows"] += windows
        t["dispatched_slots"] += dispatched
        t["occupied_slots"] += occupied
        t["dispatch_us"] += dispatch_us
        t["encode_us"] += encode_us
        t["feed_us"] += feed_us
        if buckets:
            t["bucket_programs"] += len(buckets)
            t["bucket_lane_slots"] += sum(
                int(bk.get("lanes_b", 0)) for bk in buckets)
        self.record(
            "round", events=events, lanes=lanes, windows=windows,
            dispatched=dispatched, occupied=occupied,
            waste=round(waste_ratio(dispatched, occupied), 3),
            batch=batch, width=width,
            feed_us=round(feed_us, 1), encode_us=round(encode_us, 1),
            dispatch_us=round(dispatch_us, 1),
            deal_sizes=list(deal_sizes) if deal_sizes else None,
            skew=round(shard_skew(deal_sizes), 3),
            causes=dict(causes) if causes else None,
            evictions=evictions or None,
            buckets=[dict(bk) for bk in buckets] if buckets else None,
            bucket_table=bucket_table)

    def record_gather(self, *, reads: int, rows: int, wait_us: float,
                      dispatch_us: float, fetch_us: float,
                      decode_us: float) -> None:
        """One gather-lane drain: ``reads`` coalesced into one device
        gather of ``rows`` rows; ``wait_us`` is the coalesce wait (first
        enqueue → drain start), the rest the device legs."""
        t = self.totals
        t["gathers"] += 1
        t["gathered_rows"] += rows
        t["gather_wait_us"] += wait_us
        self.record("gather", reads=reads, rows=rows,
                    wait_us=round(wait_us, 1),
                    dispatch_us=round(dispatch_us, 1),
                    fetch_us=round(fetch_us, 1),
                    decode_us=round(decode_us, 1))

    def record_query(self, *, rows: int, scanned: int, matched: int,
                     elapsed_us: float, kind: str = "scan") -> None:
        """One query-engine scan: result rows + pushdown selectivity."""
        t = self.totals
        t["queries"] += 1
        t["query_rows"] += rows
        self.record("query", kind=kind, rows=rows, scanned=scanned,
                    matched=matched,
                    selectivity=round(matched / scanned, 4) if scanned else 0.0,
                    elapsed_us=round(elapsed_us, 1))

    def record_evict(self, count: int, *, resident: int, cause: str) -> None:
        self.record("evict", count=count, resident=resident, cause=cause)

    def record_view_round(self, *, views: int, rows: int, events: int,
                          fold_us: float) -> None:
        """One materialized-view fold round: ``views`` folded the round's
        ``events`` committed events, emitting ``rows`` changed view rows to
        the changefeeds (surge_tpu.replay.views)."""
        t = self.totals
        t["view_rounds"] += 1
        t["view_delta_rows"] += rows
        t["view_fold_us"] += fold_us
        self.record("view-round", views=views, rows=rows, events=events,
                    fold_us=round(fold_us, 1))

    # -- rollups ------------------------------------------------------------------------

    def summary(self) -> dict:
        """The roofline rollup: cumulative totals + the derived ratios the
        recorder snapshots (waste ratio, µs/slot, ev/s of device dispatch).
        Plain data — safe in a bench payload, an RPC reply or a JSONL row."""
        t = dict(self.totals)
        disp_us = t["dispatch_us"]
        events = t["events"]
        return {
            **{k: (round(v, 1) if isinstance(v, float) else v)
               for k, v in t.items()},
            "waste_ratio": round(
                waste_ratio(t["dispatched_slots"], t["occupied_slots"]), 3),
            "us_per_slot": round(disp_us / t["dispatched_slots"], 4)
            if t["dispatched_slots"] else 0.0,
            "us_per_event": round(disp_us / events, 3) if events else 0.0,
            "fold_events_per_sec": round(events / (disp_us / 1e6), 1)
            if disp_us > 0 else 0.0,
        }

    def round_stages_us(self, last: Optional[int] = None
                        ) -> Dict[str, List[float]]:
        """Per-round stage series off the ring (``{stage: [us, ...]}``) —
        what the bench ladders take medians over."""
        out: Dict[str, List[float]] = {"feed_us": [], "encode_us": [],
                                       "dispatch_us": [], "waste": []}
        for ev in self.events(last):
            if ev.get("type") != "round":
                continue
            for k in out:
                v = ev.get(k)
                if v is not None:
                    out[k].append(float(v))
        return out

    def dump(self, last: Optional[int] = None) -> dict:
        """The flight-shape envelope plus the roofline rollup (``summary``)
        riding alongside ``stats`` — merge consumers ignore it, the
        roofline recorder and surgetop read it without replaying the ring."""
        payload = super().dump(last)
        payload["summary"] = self.summary()
        return payload
