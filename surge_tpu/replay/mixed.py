"""Mixed aggregate-type replay: heterogeneous models folded in ONE batch.

The reference runs one engine per aggregate type; each type's KTable restores
independently (SURVEY.md §2.6). On TPU that leaves the chip idle while small
families restore serially — so this module combines several models'
:class:`~surge_tpu.engine.model.ReplaySpec`\\ s into one: event type_ids get
disjoint ranges, event/state columns merge into one union layout (tagged-union
columns — each lane only ever reads its own model's fields, SURVEY.md §5.7
"masked vmap for heterogeneous aggregate types"), and the per-type
``lax.switch`` dispatch already built into the fold does the rest. One
``ReplayEngine`` over the combined spec then folds counters, carts and bank
accounts side by side in the same ``[B]`` batch.

Scalar-world bridges (`encode_logs`, `init_carry`, `decode_states`) keep each
lane's model identity so states decode back to their own dataclasses.
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass
from typing import Any, Mapping, Sequence

import numpy as np

from surge_tpu.codec.schema import FieldSpec, SchemaRegistry
from surge_tpu.codec.tensor import ColumnarEvents
from surge_tpu.engine.model import ReplayHandlers, ReplaySpec


@dataclass
class MixedReplay:
    """A combined spec plus the per-model bookkeeping to use it."""

    spec: ReplaySpec
    #: model name -> type_id offset of its events in the combined registry
    bases: dict[str, int]
    #: model name -> its original ReplaySpec
    parts: dict[str, ReplaySpec]

    def type_id(self, model: str, local_type_id: int) -> int:
        return self.bases[model] + local_type_id

    def encode_logs(self, tagged_logs: Sequence[tuple[str, Sequence[Any]]]
                    ) -> ColumnarEvents:
        """Columnar-encode per-aggregate logs tagged with their model name.

        Events must already be in their tensor form (e.g. bank_account's
        vocab-encoded ``EncodedCreated``). The merged registry maps each event
        class to its offset type_id, so this delegates to the codec's grouped
        ``encode_events_columnar`` (one comprehension per (type, field), not a
        per-event Python loop); the model tags are only needed later, by
        :meth:`init_carry` and :meth:`decode_states`."""
        from surge_tpu.codec.tensor import encode_events_columnar

        return encode_events_columnar(self.spec.registry,
                                      [log for _, log in tagged_logs])

    def init_carry(self, models: Sequence[str]) -> dict[str, np.ndarray]:
        """Per-lane initial carry: each lane starts at ITS model's init record
        (models may disagree about a shared column's default)."""
        fields = self.spec.registry.state.fields
        b = len(models)
        out = {f.name: np.zeros((b,), dtype=f.dtype) for f in fields}
        for i, m in enumerate(models):
            init = self.parts[m].init_state_tree()
            for name, v in init.items():
                out[name][i] = v
        return out

    def decode_states(self, models: Sequence[str],
                      states: Mapping[str, np.ndarray]) -> list[Any]:
        """Decode the folded union columns lane by lane through each lane's own
        model state schema."""
        out = []
        for i, m in enumerate(models):
            schema = self.parts[m].registry.state
            rec = {f.name: states[f.name][i] for f in schema.fields}
            out.append(schema.from_record(rec))
        return out


def combine_replay_specs(specs: Mapping[str, ReplaySpec]) -> MixedReplay:
    """Merge model families into one replayable spec (sorted by model name so
    type-id assignment is deterministic).

    Shared column names are legal — the union layout promotes dtypes and each
    lane's handlers only touch their own model's fields — but one event CLASS
    may not belong to two models.

    The combined spec's own ``init_record`` is empty (all-zero lanes): a
    per-model initial state cannot be expressed globally because lanes of
    different models share columns. Models that declare a nonzero
    ``init_record`` are therefore REFUSED here — use
    :func:`combine_replay_specs_with_init` to acknowledge that, and always
    supply ``init_carry=mixed.init_carry(models)`` to the fold."""
    return _combine(specs, allow_nonzero_init=False)


def combine_replay_specs_with_init(specs: Mapping[str, ReplaySpec]) -> MixedReplay:
    """:func:`combine_replay_specs` for model sets with nonzero init records —
    the caller promises to pass ``init_carry=mixed.init_carry(models)``."""
    return _combine(specs, allow_nonzero_init=True)


def _combine(specs: Mapping[str, ReplaySpec], *,
             allow_nonzero_init: bool) -> MixedReplay:
    merged = SchemaRegistry()
    bases: dict[str, int] = {}
    handlers: dict[int, Any] = {}
    state_fields: dict[str, np.dtype] = {}
    offset = 0
    for name in sorted(specs):
        spec = specs[name]
        if not allow_nonzero_init and any(
                np.any(np.asarray(v) != 0) for v in spec.init_record.values()):
            raise ValueError(
                f"model {name!r} declares a nonzero init_record, which a "
                "combined spec cannot honor per-lane; use "
                "combine_replay_specs_with_init and pass "
                "init_carry=mixed.init_carry(models) to the fold")
        bases[name] = offset
        for schema in spec.registry.event_schemas:
            merged.register_event(schema.cls,
                                  type_id=offset + schema.type_id,
                                  fields=schema.fields)
        for tid, h in spec.handlers.by_type_id.items():
            handlers[offset + tid] = h
        for f in spec.registry.state.fields:
            if f.name in state_fields:
                state_fields[f.name] = np.promote_types(state_fields[f.name],
                                                        f.dtype)
            else:
                state_fields[f.name] = f.dtype
        offset += spec.registry.num_event_types

    fields = tuple(FieldSpec(n, state_fields[n]) for n in sorted(state_fields))
    cls = dataclasses.make_dataclass(
        "MixedState", [(f.name, object) for f in fields])
    merged.register_state(cls, fields=fields)
    combined = ReplaySpec(registry=merged,
                          handlers=ReplayHandlers(by_type_id=handlers),
                          init_record={})
    return MixedReplay(spec=combined, bases=bases, parts=dict(specs))
