"""Pallas TPU kernel for the resident tile scan (the hot op).

The XLA lowering of the tile fold — ``lax.scan`` of a vmapped per-event step —
spends most of its time in per-step loop machinery, not arithmetic: the
measured fold rate sits far below the VPU's throughput for the few scalar ops
each event handler performs. This kernel runs the WHOLE tile scan inside one
``pallas_call``: the `[width, lanes]` word slab streams HBM→VMEM once per lane
block, the carry lives in registers/VMEM across all ``width`` steps, and the
per-event dispatch is the branchless select form (compute every handler,
mask-combine — pure VPU data flow).

Gated by ``surge.replay.tile-backend = pallas`` (default ``xla``); on CPU the
kernel runs in interpreter mode so tests exercise the exact same program.
Gather/expand and the tile work-list loop stay in XLA — only the dense scan
moves into the kernel.
"""

from __future__ import annotations

from typing import Any, Mapping

import numpy as np

#: lanes per kernel grid cell (8 sublanes × 128 lanes when viewed 2-D)
_LANE_BLOCK = 1024


def make_tile_scan(spec, wire, width: int, bs: int, unroll: int):
    """Build ``(carry {f: [bs]}, words u32 [width, bs], sides {name: [width, bs]},
    lens_rel i32 [bs], ord_rel i32 [bs]) -> carry`` as a pallas_call.

    ``lens_rel`` is each lane's remaining length within this tile
    (``lens - t_base``); ``ord_rel`` is the lane's ordinal base shifted by the
    tile offset, so the derived ordinal of local step t is ``ord_rel + t + 1``
    — identical to the XLA tile's global-t decode."""
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    from surge_tpu.replay.engine import make_step_fn

    # the select (branchless) step, applied to [LB] vectors directly — no vmap:
    # handlers are scalar jnp expressions that broadcast over the lane vector
    step = make_step_fn(spec, "select")
    state_fields = [f.name for f in spec.registry.state.fields]
    side_names = sorted(f.name for f in wire.side_fields)
    lb = min(_LANE_BLOCK, bs)
    while bs % lb != 0:  # largest power-of-two-ish divisor ≤ the lane block
        lb //= 2
    assert lb >= 1, bs
    # the kernel is written for the Mosaic/TPU lowering; every other backend
    # (cpu tests, gpu hosts) runs it through the interpreter unchanged
    # ("axon" is the tunneled TPU plugin's platform name)
    interpret = jax.default_backend() not in ("tpu", "axon")

    def kernel(*refs):
        words_ref = refs[0]
        side_refs = dict(zip(side_names, refs[1: 1 + len(side_names)]))
        k = 1 + len(side_names)
        lens_ref, ord_ref = refs[k], refs[k + 1]
        in_refs = dict(zip(state_fields, refs[k + 2: k + 2 + len(state_fields)]))
        out_refs = dict(zip(state_fields, refs[k + 2 + len(state_fields):]))

        lens = lens_ref[:]
        ordr = ord_ref[:]
        state0 = {name: in_refs[name][:] for name in state_fields}

        def body(t, state):
            word = words_ref[t, :]
            side_row = {name: r[t, :] for name, r in side_refs.items()}
            events = wire.decode_words(word, side_row, t < lens, ordr, t)
            return step(state, events)

        state = jax.lax.fori_loop(0, width, body, state0, unroll=unroll)
        for name in state_fields:
            out_refs[name][:] = state[name]

    grid = (bs // lb,)
    slab_spec = pl.BlockSpec((width, lb), lambda i: (0, i))
    vec_spec = pl.BlockSpec((lb,), lambda i: (i,))

    def tile_scan(carry: Mapping[str, Any], words, sides: Mapping[str, Any],
                  lens_rel, ord_rel):
        state_dtypes = {f.name: np.dtype(f.dtype)
                        for f in spec.registry.state.fields}
        out = pl.pallas_call(
            kernel,
            grid=grid,
            in_specs=[slab_spec] + [slab_spec] * len(side_names)
                     + [vec_spec, vec_spec] + [vec_spec] * len(state_fields),
            out_specs=[vec_spec] * len(state_fields),
            out_shape=[jax.ShapeDtypeStruct((bs,), state_dtypes[n])
                       for n in state_fields],
            interpret=interpret,
        )(words, *(sides[n] for n in side_names), lens_rel, ord_rel,
          *(carry[n] for n in state_fields))
        return dict(zip(state_fields, out))

    return tile_scan


def make_ragged_fold(spec, wire, width: int, bs: int, rows: int, unroll: int):
    """The RAGGED refresh tile (ISSUE 18 leg b): ``(carry {f: [bs]},
    words u32 [rows], sides {name: [rows]}, starts i32 [bs], lens i32 [bs],
    ord i32 [bs]) -> carry`` as a pallas_call.

    Instead of streaming a dense ``[width, lanes]`` rectangle (whose padding
    the steady ragged round pays ~9× over), the kernel walks a per-lane
    offset index over ONE flat packed event buffer: step ``t`` of lane ``b``
    reads ``words[starts[b] + t]``, valid while ``t < lens[b]``. Out-of-range
    steps clip-gather into OTHER lanes' regions — safe because ``valid``
    masks the decoded type to the pad sentinel (−1) and the step fn carries
    state through pad events (the same contract as the engine's flat-corpus
    worklists). ``starts`` arrive pre-shifted for chained windows; ``lens``
    is the lane's remaining length within this window, and the derived
    ordinal of local step ``t`` is ``ord[b] + t + 1``."""
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    from surge_tpu.replay.engine import make_step_fn

    step = make_step_fn(spec, "select")
    state_fields = [f.name for f in spec.registry.state.fields]
    side_names = sorted(f.name for f in wire.side_fields)
    lb = min(_LANE_BLOCK, bs)
    while bs % lb != 0:
        lb //= 2
    assert lb >= 1, bs
    interpret = jax.default_backend() not in ("tpu", "axon")

    def kernel(*refs):
        words_ref = refs[0]
        side_refs = dict(zip(side_names, refs[1: 1 + len(side_names)]))
        k = 1 + len(side_names)
        starts_ref, lens_ref, ord_ref = refs[k], refs[k + 1], refs[k + 2]
        in_refs = dict(zip(state_fields,
                           refs[k + 3: k + 3 + len(state_fields)]))
        out_refs = dict(zip(state_fields, refs[k + 3 + len(state_fields):]))

        # the flat buffer rides whole into each grid cell (every lane block
        # gathers arbitrary offsets of it); it is sized to the bucket's
        # OCCUPIED events, not the padded rectangle, so "whole" is the point
        words = words_ref[:]
        sides_all = {name: r[:] for name, r in side_refs.items()}
        starts = starts_ref[:]
        lens = lens_ref[:]
        ordr = ord_ref[:]
        state0 = {name: in_refs[name][:] for name in state_fields}

        def body(t, state):
            idx = jnp.minimum(starts + t, np.int32(rows - 1))
            word = words[idx]
            side_row = {name: v[idx] for name, v in sides_all.items()}
            events = wire.decode_words(word, side_row, t < lens, ordr, t)
            return step(state, events)

        state = jax.lax.fori_loop(0, width, body, state0, unroll=unroll)
        for name in state_fields:
            out_refs[name][:] = state[name]

    grid = (bs // lb,)
    flat_spec = pl.BlockSpec((rows,), lambda i: (0,))
    vec_spec = pl.BlockSpec((lb,), lambda i: (i,))

    def ragged_fold(carry: Mapping[str, Any], words, sides: Mapping[str, Any],
                    starts, lens, ordinals):
        state_dtypes = {f.name: np.dtype(f.dtype)
                        for f in spec.registry.state.fields}
        out = pl.pallas_call(
            kernel,
            grid=grid,
            in_specs=[flat_spec] + [flat_spec] * len(side_names)
                     + [vec_spec] * 3 + [vec_spec] * len(state_fields),
            out_specs=[vec_spec] * len(state_fields),
            out_shape=[jax.ShapeDtypeStruct((bs,), state_dtypes[n])
                       for n in state_fields],
            interpret=interpret,
        )(words, *(sides[n] for n in side_names), starts, lens, ordinals,
          *(carry[n] for n in state_fields))
        return dict(zip(state_fields, out))

    return ragged_fold
