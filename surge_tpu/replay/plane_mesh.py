"""Mesh-native resident plane: the sharded slab with device-local gather lanes.

The single-device :class:`~surge_tpu.replay.resident_state.ResidentStatePlane`
holds its KTable slab as ``{field: [capacity+1]}`` on one device. Its original
mesh wiring just ``device_put`` the same 1-D columns with a sharded layout and
kept the plain-``jit`` programs — so every batched read's arbitrary-index
gather made XLA REPLICATE the slab across the mesh, and every refresh scatter
ran as full-slab SPMD work on all devices (``n_dev×`` the single-device cost).
That legacy layout survives as the ``surge.replay.mesh.gather = replicated``
arm (the paired-bench baseline and the rollback switch).

This module is the first-class path (``= local``, the default): slot
ownership is explicit and every program runs under ``shard_map``.

- **Layout.** Capacity rounds up to a device multiple; the slab is
  ``{field: [n_dev, per_dev+1]}`` sharded ``P(axis, None)``. Global slot
  ``s`` lives on device ``s // per_dev`` at local row ``s % per_dev``; each
  shard's last row is its own scratch (absorbing every padding / non-owned
  write, exactly like the single-device scratch row).
- **Refresh (one sharded h2d, zero d2h, 1/n_dev work per device).** The host
  deals a fold group's lanes to their owning shards and packs PER-DEVICE
  window tensors ``[n_dev, width, lanes_local, nbytes]``; ``device_put`` with
  a ``P(axis, …)`` sharding ships each device only its shard's bytes. Inside
  ``shard_map`` each device admits, gathers carries, decodes and folds ONLY
  its own lanes and scatters back locally — no collectives, no cross-device
  traffic, total fold work equal to the single-device plane's.
- **Reads (one cross-device collective per batched-read round).** A gather of
  ``k`` slots runs device-local: each device gathers the rows it owns (masked
  zeros elsewhere) and ONE ``psum`` combines the partials into the replicated
  ``[words, k]`` result every reader decodes — the slab itself never moves.
  The u16 narrow wire and its fit-flag contract are preserved bit for bit
  (the sum happens on exact u32/i32 partials; the narrow pack runs after the
  collective).

Byte-identity against the single-device golden replay — across evict /
re-admit cycles and a partition rebalance — is held by
tests/test_resident_mesh_plane.py on the forced-8-device CPU mesh.
"""

from __future__ import annotations

from typing import Dict, List, Mapping, Tuple

import numpy as np

__all__ = ["MeshPlane"]


def _pow2(n: int, lo: int = 8) -> int:
    cap = lo
    while cap < n:
        cap *= 2
    return cap


class MeshPlane:
    """Device programs + host lane-dealing for one plane's sharded slab."""

    def __init__(self, plane) -> None:
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        self.plane = plane
        self.mesh = plane.mesh
        self.axis = plane.engine.mesh_axis
        self.n_dev = int(np.prod(self.mesh.devices.shape))
        # plane.capacity is already rounded to a device multiple (plane init)
        assert plane.capacity % self.n_dev == 0, (plane.capacity, self.n_dev)
        self.per_dev = plane.capacity // self.n_dev
        self.rows = self.per_dev + 1  # +1: each shard's own scratch row
        self._fields = plane._fields
        self._sh2 = NamedSharding(self.mesh, P(self.axis, None))
        self._sh3 = NamedSharding(self.mesh, P(self.axis, None, None))
        self._sh4 = NamedSharding(self.mesh, P(self.axis, None, None, None))
        self._rep = NamedSharding(self.mesh, P())
        self._programs: dict = {}
        #: lane-deal sizes of the last refresh round — the device
        #: observatory's shard-skew source (max/mean over these)
        self.last_deal: List[int] = []

    # -- layout helpers -------------------------------------------------------------

    def owners(self, slots: np.ndarray) -> np.ndarray:
        """Owning device of each global slot (scratch → last device, whose
        local index then lands past per_dev and resolves to local scratch)."""
        return np.minimum(slots // self.per_dev, self.n_dev - 1)

    def init_slab(self):
        """Fresh sharded slab + ordinal columns ({field: [n_dev, rows]})."""
        import jax

        init = self.plane.spec.init_state_tree()
        slab = {f.name: jax.device_put(
            np.full((self.n_dev, self.rows), init[f.name], dtype=f.dtype),
            self._sh2) for f in self._fields}
        ords = jax.device_put(
            np.zeros((self.n_dev, self.rows), dtype=np.int32), self._sh2)
        return slab, ords

    # -- refresh: host lane deal + sharded fold -------------------------------------

    def _deal(self, slots: np.ndarray, bucket_lo: int = 8
              ) -> Tuple[List[np.ndarray], int]:
        """Deal global-slot positions to their owners: per-device index lists
        (positions into the input arrays) + the shared local lane bucket.
        Scratch-sentinel entries (pure padding) are dropped — they fold
        nothing and own no shard."""
        cap = self.plane.capacity
        live = slots < cap
        owner = self.owners(slots)
        deals = [np.nonzero(live & (owner == d))[0] for d in range(self.n_dev)]
        # pow2 local lane bucket: the global arrays already arrive at pow2
        # (bucketed) or pow8 (dense) lane buckets, so the per-shard ladder
        # stays bounded without re-coarsening a small bucket's deal to 8×
        width = _pow2(max((len(d) for d in deals), default=1), bucket_lo)
        return deals, width

    def refresh(self, slab, ords, admit_idx: np.ndarray,
                admit_vals: Mapping[str, np.ndarray], admit_ord: np.ndarray,
                lane_slots: np.ndarray, counts: np.ndarray,
                packed: np.ndarray, side: Mapping[str, np.ndarray]):
        """One refresh window against the sharded slab. Host inputs are the
        single-device plane's global arrays (slots in [0, capacity] with the
        scratch sentinel); the deal + per-device re-pack happens here, then
        ONE sharded ``device_put`` per tensor ships each device its shard's
        lanes and the shard_map program folds them locally."""
        import jax

        a_deals, a_b = self._deal(admit_idx)
        l_deals, l_b = self._deal(lane_slots)
        self.last_deal = [len(d) for d in l_deals]
        per_dev, n_dev = self.per_dev, self.n_dev
        width = packed.shape[0]
        nbytes = packed.shape[2]

        adm_loc = np.full((n_dev, a_b), per_dev, dtype=np.int32)
        adm_ord = np.zeros((n_dev, a_b), dtype=np.int32)
        adm_vals = {f.name: np.zeros((n_dev, a_b), dtype=f.dtype)
                    for f in self._fields}
        for d, sel in enumerate(a_deals):
            adm_loc[d, : len(sel)] = admit_idx[sel] - d * per_dev
            adm_ord[d, : len(sel)] = admit_ord[sel]
            for k, col in adm_vals.items():
                col[d, : len(sel)] = admit_vals[k][sel]

        lane_loc = np.full((n_dev, l_b), per_dev, dtype=np.int32)
        cnt_l = np.zeros((n_dev, l_b), dtype=np.int32)
        packed_l = np.zeros((n_dev, width, l_b, nbytes), dtype=packed.dtype)
        side_l = {k: np.zeros((n_dev, width, l_b), dtype=v.dtype)
                  for k, v in side.items()}
        for d, sel in enumerate(l_deals):
            lane_loc[d, : len(sel)] = lane_slots[sel] - d * per_dev
            cnt_l[d, : len(sel)] = counts[sel]
            packed_l[d, :, : len(sel)] = packed[:, sel]
            for k, col in side_l.items():
                col[d, :, : len(sel)] = side[k][:, sel]

        prog = self._refresh_program(a_b, l_b, width, nbytes,
                                     tuple(sorted(side_l)))
        return prog(
            slab, ords,
            jax.device_put(adm_loc, self._sh2),
            {k: jax.device_put(v, self._sh2) for k, v in adm_vals.items()},
            jax.device_put(adm_ord, self._sh2),
            jax.device_put(lane_loc, self._sh2),
            jax.device_put(cnt_l, self._sh2),
            jax.device_put(packed_l, self._sh4),
            {k: jax.device_put(v, self._sh3) for k, v in side_l.items()})

    def _refresh_program(self, a_b: int, l_b: int, width: int, nbytes: int,
                         side_names: tuple):
        key = ("refresh", a_b, l_b, width, nbytes, side_names)
        hit = self._programs.get(key)
        if hit is not None:
            return hit
        import jax
        from jax.sharding import PartitionSpec as P

        from surge_tpu.replay.engine import make_batch_fold
        from surge_tpu.replay.jax_compat import shard_map as _shard_map

        plane = self.plane
        wire = plane._wire
        fold = make_batch_fold(plane.spec, dispatch=plane._dispatch)
        fnames = [f.name for f in self._fields]

        def local(slab_d, ords_d, adm_loc, adm_vals, adm_ord, lane_loc,
                  cnt, packed, side):
            # local blocks keep the (size-1) device axis; drop it
            slab0 = {k: v[0] for k, v in slab_d.items()}
            ords0 = ords_d[0]
            al, ao = adm_loc[0], adm_ord[0]
            ll, cn = lane_loc[0], cnt[0]
            pk = packed[0]
            sd = {k: v[0] for k, v in side.items()}
            # 1. admission scatter (spilled carries / init rows re-enter);
            # non-owned and padding entries all land on the local scratch row
            slab0 = {k: v.at[al].set(adm_vals[k][0]) for k, v in slab0.items()}
            ords0 = ords0.at[al].set(ao)
            # 2. gather this shard's lane carries, decode+fold its window
            carry = {k: v[ll] for k, v in slab0.items()}
            events = wire.decode(pk, sd, ords0[ll])
            out = fold(carry, events)
            # 3. scatter back + advance ordinals, all shard-local
            slab0 = {k: v.at[ll].set(out[k]) for k, v in slab0.items()}
            ords0 = ords0.at[ll].add(cn)
            return ({k: v[None] for k, v in slab0.items()}, ords0[None])

        axis = self.axis
        p2 = P(axis, None)
        mapped = _shard_map(
            local, mesh=self.mesh,
            in_specs=({k: p2 for k in fnames}, p2, p2,
                      {k: p2 for k in fnames}, p2, p2, p2,
                      P(axis, None, None, None),
                      {k: P(axis, None, None) for k in side_names}),
            out_specs=({k: p2 for k in fnames}, p2),
            # handlers may return literal columns whose varying-manual-axes
            # type differs per switch branch; everything here is
            # per-device-local (no collectives), so skip the VMA check
            check_vma=False)
        # sharded slab+ordinal donation (surge.replay.donate-refresh): each
        # shard's refresh scatter consumes the columns it rewrites instead of
        # copying them every window — the plane republishes its handle after
        # every donated dispatch (resident_state._dispatch_plan)
        prog = jax.jit(mapped, donate_argnums=(
            (0, 1) if plane._donate_refresh else ()))
        self._programs[key] = prog
        return prog

    # -- seeding --------------------------------------------------------------------

    def seed_rows(self, slab, ords, vals: Mapping[str, np.ndarray],
                  dst_slots: np.ndarray, lens: np.ndarray):
        """Scatter host state rows into the sharded slab (the mesh cold-start
        admission): values ride replicated, each device keeps its own."""
        import jax

        k_b = len(dst_slots)
        prog = self._seed_program(k_b)
        return prog(slab, ords,
                    {k: jax.device_put(np.asarray(v), self._rep)
                     for k, v in vals.items()},
                    jax.device_put(np.asarray(dst_slots, np.int32),
                                   self._rep),
                    jax.device_put(np.asarray(lens, np.int32), self._rep))

    def _seed_program(self, k_b: int):
        key = ("seed", k_b)
        hit = self._programs.get(key)
        if hit is not None:
            return hit
        import jax
        import jax.numpy as jnp
        from jax.sharding import PartitionSpec as P

        from surge_tpu.replay.jax_compat import shard_map as _shard_map

        fnames = [f.name for f in self._fields]
        per_dev = self.per_dev
        axis = self.axis

        def local(slab_d, ords_d, vals, dst, lens):
            d = jax.lax.axis_index(axis)
            loc = dst - d * per_dev
            own = (loc >= 0) & (loc < per_dev)
            pos = jnp.where(own, jnp.clip(loc, 0, per_dev - 1), per_dev)
            slab0 = {k: v[0].at[pos].set(vals[k]) for k, v in slab_d.items()}
            ords0 = ords_d[0].at[pos].set(lens)
            return ({k: v[None] for k, v in slab0.items()}, ords0[None])

        p2 = P(axis, None)
        mapped = _shard_map(
            local, mesh=self.mesh,
            in_specs=({k: p2 for k in fnames}, p2, {k: P() for k in fnames},
                      P(), P()),
            out_specs=({k: p2 for k in fnames}, p2), check_vma=False)
        prog = jax.jit(mapped)
        self._programs[key] = prog
        return prog

    # -- reads: device-local gather + ONE collective ---------------------------------

    def gather_wide(self, slab, ords, idx: np.ndarray):
        """The wide (u32-matrix) gather: each device contributes the rows it
        owns, one psum replicates the result. Signature-compatible with the
        single-device ``_gather_wide`` jit."""
        import jax

        prog = self._gather_program(len(np.asarray(idx)), narrow=False)
        return prog(slab, ords, jax.device_put(
            np.asarray(idx, np.int32), self._rep))

    def gather_narrow(self, slab, idx: np.ndarray):
        """The u16 narrow read wire: exact partials psum first, the narrow
        pack + fit flags run post-collective — identical buffer layout and
        overflow contract to the single-device program."""
        import jax

        prog = self._gather_program(len(np.asarray(idx)), narrow=True)
        return prog(slab, jax.device_put(np.asarray(idx, np.int32),
                                         self._rep))

    def _gather_program(self, k_b: int, narrow: bool):
        key = ("gather-narrow" if narrow else "gather-wide", k_b)
        hit = self._programs.get(key)
        if hit is not None:
            return hit
        import jax
        import jax.numpy as jnp
        from jax.sharding import PartitionSpec as P

        from surge_tpu.replay.jax_compat import shard_map as _shard_map

        plane = self.plane
        names = [f.name for f in self._fields]
        dts = [plane._dev_dts[n] for n in names]
        per_dev = self.per_dev
        axis = self.axis
        p2 = P(axis, None)

        def local_wide(slab_d, ords_d, idx):
            d = jax.lax.axis_index(axis)
            loc = idx - d * per_dev
            own = (loc >= 0) & (loc < per_dev)
            locc = jnp.clip(loc, 0, per_dev - 1)
            cols = []
            for name, dt in zip(names, dts):
                v = slab_d[name][0][locc]
                if np.issubdtype(dt, np.floating) and dt.itemsize < 4:
                    v = jax.lax.bitcast_convert_type(
                        v.astype(jnp.float32), jnp.uint32)
                elif dt == np.bool_ or dt.itemsize < 4:
                    v = v.astype(jnp.uint32)
                elif dt != np.dtype(np.uint32):
                    v = jax.lax.bitcast_convert_type(v, jnp.uint32)
                if v.ndim == 2:  # 64-bit column: one row per u32 word
                    for j in range(v.shape[1]):
                        cols.append(jnp.where(own, v[:, j], 0))
                else:
                    cols.append(jnp.where(own, v, 0))
            # the ordinal row rides the same matrix: exactly ONE collective
            # per batched-read round
            cols.append(jnp.where(own, ords_d[0][locc].astype(jnp.uint32), 0))
            both = jax.lax.psum(jnp.stack(cols), axis)
            return both[:-1], both[-1].astype(jnp.int32)

        if not narrow:
            mapped = _shard_map(
                local_wide, mesh=self.mesh,
                in_specs=({k: p2 for k in names}, p2, P()),
                out_specs=(P(), P()), check_vma=False)
            prog = jax.jit(mapped)
            self._programs[key] = prog
            return prog

        def local_narrow(slab_d, idx):
            d = jax.lax.axis_index(axis)
            loc = idx - d * per_dev
            own = (loc >= 0) & (loc < per_dev)
            locc = jnp.clip(loc, 0, per_dev - 1)
            # exact i32 partials cross ONE collective; the u16 pack and its
            # fit flags run on the REPLICATED true values after the psum, so
            # the overflow contract matches the single-device wire exactly
            # (narrow_ok already excludes floats and >4-byte columns)
            part = jnp.stack([
                jnp.where(own, slab_d[name][0][locc].astype(jnp.int32), 0)
                for name in names])
            mat = jax.lax.psum(part, axis)
            cols16, flags = [], []
            for i, dt in enumerate(dts):
                v = mat[i]
                if dt == np.bool_:
                    fits = jnp.bool_(True)
                elif np.issubdtype(dt, np.signedinteger):
                    fits = jnp.all((v >= -32768) & (v <= 32767))
                else:  # unsigned: a >2^31 source wrapped negative — refetch
                    fits = jnp.all((v >= 0) & (v <= 65535))
                cols16.append(v.astype(jnp.uint16).ravel())
                flags.append(fits.astype(jnp.uint16))
            return jnp.concatenate(cols16 + [jnp.stack(flags)])

        mapped = _shard_map(
            local_narrow, mesh=self.mesh,
            in_specs=({k: p2 for k in names}, P()),
            out_specs=P(), check_vma=False)
        prog = jax.jit(mapped)
        self._programs[key] = prog
        return prog
