"""Per-stage profiler for the chunked TPU replay fold.

The replay is the headline workload (~400M events/s, BENCH_r0*.json) yet the
bench trajectory only carried one end-to-end timer: a regression in encode,
H2D transfer, compile behavior, device fold, or the state fetch was
indistinguishable. This profiler splits a replay pass into the five stages the
roofline analysis reasons about (docs/roofline.md):

- ``encode``  — host-side wire packing / bucketing (CPU-bound);
- ``h2d``     — host→device transfer of windows / the resident corpus;
- ``compile`` — fold dispatches that triggered a fresh XLA compilation
  (detected from the engine's static-shape signature set, never a private
  JAX API);
- ``dispatch``— steady fold dispatches (host-side async cost only — the
  device keeps executing after dispatch returns);
- ``fetch``   — dispatch → results on host. The stage is closed by the repo's
  **fetch-barrier discipline**: a real device→host fetch whose data dependency
  forces the chained programs to finish (bench.py). ``block_until_ready`` can
  return before execution completes on the tunneled relay, so it is never used
  to close device time.
- ``refresh`` — one incremental fold round of the resident state plane
  (surge_tpu.replay.resident_state): encode + h2d + dispatch of a committed
  batch into the on-device slab. The plane also reports its pack time under
  ``encode`` and its window dispatches under ``compile``/``dispatch``, so
  incremental folds break down in the per-stage profile exactly like
  cold-start passes; ``refresh`` is the per-round umbrella.

Each stage occurrence feeds the DEBUG-level ``surge.replay.profile.*`` timers
in :class:`~surge_tpu.metrics.EngineMetrics`, emits a span when a tracer is
attached, and — when ``jax.profiler`` is importable — wraps
device-dispatching stages in ``jax.profiler.TraceAnnotation`` so the stages
line up with XLA ops in a captured device profile.

Two modes, same names (docs/observability.md):

- **counter-only** (:meth:`ReplayProfiler.counters`) — always on; the
  resident plane's per-round "refresh" umbrella runs through it. Stage
  seconds/counts accumulate as plain float/int bumps and the histogram
  ``record_ms`` calls no-op because the timers' sensors are disabled below
  DEBUG — the device observatory's per-stage accounting without histogram
  cost.
- **full histograms** (:meth:`ReplayProfiler.if_enabled`, or the same
  counters profiler under a DEBUG registry) — the cold-start replay path's
  opt-in: every stage occurrence also lands in the
  ``surge.replay.profile.*`` timer distributions.

Usage::

    registry = Metrics(recording_level=RecordingLevel.DEBUG)
    metrics = engine_metrics(registry)
    prof = ReplayProfiler.if_enabled(registry, metrics, tracer=tracer)
    engine = ReplayEngine(spec, config=cfg, profiler=prof)
    engine.replay_columnar(events)
    print(prof.summary())
"""

from __future__ import annotations

import time
from contextlib import contextmanager
from typing import Dict, Optional

from surge_tpu.metrics import EngineMetrics, Metrics, RecordingLevel, Timer

__all__ = ["ReplayProfiler"]

#: stage name -> EngineMetrics timer attribute
_STAGE_TIMERS = {
    "encode": "replay_encode_timer",
    "h2d": "replay_h2d_timer",
    "compile": "replay_compile_timer",
    "dispatch": "replay_dispatch_timer",
    "fetch": "replay_fetch_timer",
    "refresh": "replay_refresh_timer",
}

#: stages that dispatch device work — annotated into XLA profiles
_DEVICE_STAGES = frozenset({"compile", "dispatch", "fetch"})


def _trace_annotation(name: str):
    """A ``jax.profiler.TraceAnnotation`` for device-visible stages, or None
    when jax (or its profiler) is unavailable — profiling must never create a
    jax dependency for host-only callers."""
    try:
        import jax.profiler as jp

        return jp.TraceAnnotation(name)
    except Exception:  # noqa: BLE001 — optional integration only
        return None


class ReplayProfiler:
    """Accumulates per-stage wall time and occurrence counts for replay passes.

    Thread-compatible with the engine's single-dispatcher model (replay runs
    on one thread); the summary dict is plain data, safe to ship in a bench
    payload or log line.
    """

    def __init__(self, metrics: Optional[EngineMetrics] = None,
                 tracer=None, annotate: bool = True) -> None:
        self.metrics = metrics
        self.tracer = tracer
        self.annotate = annotate
        self.stage_s: Dict[str, float] = {s: 0.0 for s in _STAGE_TIMERS}
        self.stage_n: Dict[str, int] = {s: 0 for s in _STAGE_TIMERS}
        self.windows = 0  # windows/tiles dispatched (engine-reported)
        self._pass_span = None  # current pass-level span (parent of stages)

    @classmethod
    def if_enabled(cls, registry: Metrics,
                   metrics: Optional[EngineMetrics] = None,
                   tracer=None, annotate: bool = True
                   ) -> Optional["ReplayProfiler"]:
        """A profiler iff the registry records at DEBUG or finer — the gate
        that keeps the INFO hot path paying nothing (the engine then holds
        ``profiler=None`` and every hook short-circuits on one ``is None``)."""
        if registry.recording_level < RecordingLevel.DEBUG:
            return None
        return cls(metrics=metrics, tracer=tracer, annotate=annotate)

    @classmethod
    def counters(cls, metrics: Optional[EngineMetrics] = None,
                 tracer=None, annotate: bool = True) -> "ReplayProfiler":
        """Counter-only mode: ALWAYS returns a profiler (no recording-level
        gate). The resident plane's per-round "refresh" umbrella runs through
        this — cheap always-on accounting (``stage_s``/``stage_n`` float/int
        bumps, the device observatory's per-stage wall µs) with the histogram
        cost still opt-in: the ``surge.replay.profile.*`` timers are
        registered at DEBUG, so at the default INFO recording level their
        sensors are disabled and ``record_ms`` is a no-op. Raising the
        registry to DEBUG upgrades the SAME profiler to full-histogram mode
        with zero call-site changes — the names stay stable across both
        modes (docs/observability.md, "Two profiler modes")."""
        return cls(metrics=metrics, tracer=tracer, annotate=annotate)

    # -- recording ----------------------------------------------------------------------

    def record(self, stage: str, seconds: float, **attrs) -> None:
        """Attribute ``seconds`` of wall time to ``stage`` (already measured by
        the caller — the engine's hot loops keep their own perf_counter reads)."""
        self.stage_s[stage] = self.stage_s.get(stage, 0.0) + seconds
        self.stage_n[stage] = self.stage_n.get(stage, 0) + 1
        if self.metrics is not None:
            timer: Timer = getattr(self.metrics, _STAGE_TIMERS[stage])
            timer.record_ms(seconds * 1000.0)
        if self.tracer is not None:
            span = self.tracer.start_span(f"replay.{stage}",
                                          parent=self._pass_span)
            # retro-dated to the measured interval so the trace timeline
            # matches the perf_counter numbers the engine recorded — BOTH
            # clocks: the tail sampler's keep decision and the anatomy
            # placement read the mono pair first, so a wall-only retro-date
            # would make a 2s stage look like a 0ms span
            span.start_time = time.time() - seconds
            span.start_mono = time.monotonic() - seconds
            try:
                for k, v in attrs.items():
                    span.set_attribute(k, v)
            finally:
                # finish unconditionally (span-leak rule): a raising
                # attribute value must not leak the span — under tail
                # sampling a leaked span pins its whole trace in the buffer
                span.finish()

    def count_windows(self, n: int = 1) -> None:
        """Engine-reported window/tile dispatch count (one bump per window the
        fold actually dispatched — record() calls must not inflate it)."""
        self.windows += n
        if self.metrics is not None:
            self.metrics.replay_profile_windows.record(n)

    @contextmanager
    def stage(self, name: str, **attrs):
        """Time a stage inline (used where the engine has no existing timer),
        wrapping device stages in a TraceAnnotation for XLA profiles. The
        record lands even when the block raises — a failing compile/fetch is
        exactly the pass an operator profiles."""
        ann = (_trace_annotation(f"surge.replay.{name}")
               if self.annotate and name in _DEVICE_STAGES else None)
        t0 = time.perf_counter()
        try:
            if ann is not None:
                with ann:
                    yield
            else:
                yield
        finally:
            self.record(name, time.perf_counter() - t0, **attrs)

    @contextmanager
    def replay_pass(self, name: str = "replay.pass", **attrs):
        """Span + timing for one whole replay pass; stage spans emitted inside
        become its children so a trace shows the breakdown under one parent."""
        span = None
        if self.tracer is not None:
            span = self.tracer.start_span(name)
            for k, v in attrs.items():
                span.set_attribute(k, v)
            self._pass_span = span
        try:
            yield span
        finally:
            self._pass_span = None
            if span is not None:
                span.finish()

    # -- reporting ----------------------------------------------------------------------

    def summary(self) -> dict:
        """``{stage: {"seconds": s, "count": n}}`` plus the covered total."""
        out = {s: {"seconds": round(self.stage_s[s], 4),
                   "count": self.stage_n[s]}
               for s in _STAGE_TIMERS}
        out["windows"] = self.windows
        out["total_accounted_s"] = round(sum(self.stage_s.values()), 4)
        return out

    def reset(self) -> None:
        for s in self.stage_s:
            self.stage_s[s] = 0.0
            self.stage_n[s] = 0
        self.windows = 0
