"""TPU scan engine over committed columnar segments — the analytics plane.

PAPER.md's KTable analogy has two halves: Surge materializes per-aggregate
STATE (the resident plane serves that), but the reference never had the other
half — analytical reads over the event log itself. ``log/columnar.py`` already
stores committed events as struct-of-arrays chunks, which is exactly the
layout a vectorized scan wants: this module runs projection / filter /
grouped-aggregation queries over those chunks as batched device programs,
turning the event store into a real-time analytics plane no JVM Surge
deployment could offer (ROADMAP item 4).

Design:

- **Predicate pushdown on typed columns.** A :class:`ScanQuery` carries
  conjunctive predicates over the union event columns (plus ``type_id`` and an
  event-type name filter); the segment reader is told exactly which columns
  the query touches, so untouched column payloads are *seeked past, never
  decompressed* (``read_segment(columns=...)``) — and inside the device
  program the predicate mask is fused into the segment reduce, so filtered
  events cost a compare, not a branch.
- **Grouped aggregates keyed by aggregate id.** ``count | sum | min | max``
  per aggregate via one segment-reduce (``.at[agg_idx].add/min/max``) over the
  flat event axis — no per-aggregate padding, no [B, T] batch materialization.
  Chunks cover disjoint aggregate ranges (the columnar-segment contract), so
  chunk results concatenate.
- **Mesh-sharded scans.** With a mesh, the EVENT axis shards across devices
  (``shard_map``): each device reduces its slice into full per-aggregate
  partials, then ONE collective per output (psum / pmin / pmax) replicates the
  result — the scan scales with devices and only ``[B]``-sized partials cross
  the interconnect.
- **Bucketed shapes.** Event and aggregate axes pad to power-of-two buckets
  (events at least ``surge.query.chunk-events``), so a steady stream of
  different-sized chunks reuses a handful of compiled programs.
- **Exactness contract.** Arithmetic happens in the DEVICE dtype of each
  column (with x64 off an int64 column reduces in int32); the numpy host
  reference (:func:`scan_reference`) mirrors that bit for bit, and the
  query-engine tests hold device == reference on every op. Aggregates with
  zero matched events report 0 for every output (the ``count`` column, always
  present, is the tell).

Served through ``SurgeEngine.query()`` / ``query_states()`` and the admin
``ScanSegments`` / ``QueryStates`` RPCs (docs/replay.md "Query engine").
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Any, Dict, Iterable, List, Mapping, Optional, Sequence, Tuple

import numpy as np

from surge_tpu.codec.tensor import ColumnarEvents
from surge_tpu.config import Config, default_config

__all__ = ["Predicate", "Aggregate", "ScanQuery", "StateQuery", "QueryResult",
           "QueryEngine", "scan_reference", "state_query_reference",
           "predicate_mask_np"]

#: comparison ops a predicate may use (conjunctive; applied on device)
_OPS = ("==", "!=", "<", "<=", ">", ">=")


@dataclass(frozen=True)
class Predicate:
    """One comparison over a typed event column (or ``type_id``)."""

    column: str
    op: str
    value: float

    def __post_init__(self) -> None:
        if self.op not in _OPS:
            raise ValueError(f"unknown predicate op {self.op!r} (one of {_OPS})")

    def as_json(self) -> dict:
        return {"column": self.column, "op": self.op, "value": self.value}


@dataclass(frozen=True)
class Aggregate:
    """One grouped aggregate: ``count`` (no column) or ``sum|min|max`` over a
    column. Output column name: ``count`` / ``<op>_<column>``."""

    op: str
    column: Optional[str] = None

    def __post_init__(self) -> None:
        if self.op not in ("count", "sum", "min", "max"):
            raise ValueError(f"unknown aggregate op {self.op!r}")
        if self.op != "count" and not self.column:
            raise ValueError(f"aggregate {self.op!r} needs a column")

    @property
    def name(self) -> str:
        return "count" if self.op == "count" else f"{self.op}_{self.column}"

    def as_json(self) -> dict:
        out: dict = {"op": self.op}
        if self.column:
            out["column"] = self.column
        return out


@dataclass(frozen=True)
class ScanQuery:
    """Filter + grouped-aggregate scan over event columns.

    Rows group by aggregate id, or — with ``group_by`` — by the distinct
    values of one event column (``type_id`` allowed), the classic
    group-by-dimension rollup. ``event_types`` filters by event CLASS name
    (resolved to type ids against the registry — the typed pushdown the wire
    format makes free); ``predicates`` are conjunctive, and each entry of
    ``or_groups`` is a disjunction (OR) of predicates whose groups AND with
    each other and with ``predicates`` — CNF, enough for the dashboard-filter
    shapes the reference's KTable reads cover. A ``count`` output is always
    computed even when not requested, so zero-match groups are
    distinguishable."""

    aggregates: Tuple[Aggregate, ...]
    predicates: Tuple[Predicate, ...] = ()
    event_types: Optional[Tuple[str, ...]] = None
    or_groups: Tuple[Tuple[Predicate, ...], ...] = ()
    group_by: Optional[str] = None

    def __post_init__(self) -> None:
        # normalize nested sequences so signature()/program keys hash
        object.__setattr__(self, "or_groups",
                           tuple(tuple(g) for g in self.or_groups))
        for g in self.or_groups:
            if not g:
                raise ValueError("empty OR-group (would match nothing)")

    def as_json(self) -> dict:
        out: dict = {"aggregates": [a.as_json() for a in self.aggregates],
                     "predicates": [p.as_json() for p in self.predicates]}
        if self.event_types is not None:
            out["event_types"] = list(self.event_types)
        if self.or_groups:
            out["or_groups"] = [[p.as_json() for p in g]
                                for g in self.or_groups]
        if self.group_by is not None:
            out["group_by"] = self.group_by
        return out

    @classmethod
    def from_json(cls, d: Mapping[str, Any]) -> "ScanQuery":
        return cls(
            aggregates=tuple(Aggregate(a["op"], a.get("column"))
                             for a in d.get("aggregates", ())),
            predicates=tuple(Predicate(p["column"], p["op"], p["value"])
                             for p in d.get("predicates", ())),
            event_types=(tuple(d["event_types"])
                         if d.get("event_types") is not None else None),
            or_groups=tuple(
                tuple(Predicate(p["column"], p["op"], p["value"]) for p in g)
                for g in d.get("or_groups", ())),
            group_by=d.get("group_by"))

    def all_predicates(self) -> Tuple[Predicate, ...]:
        """Flat predicate order the device program indexes by: conjunctive
        predicates first, then each OR-group's members in declaration
        order."""
        return self.predicates + tuple(p for g in self.or_groups for p in g)

    def columns_needed(self) -> List[str]:
        """Every stored union column this query touches — the projection the
        segment reader pushes down (``type_id`` / ``type_ids`` ride the chunk
        header columns and cost nothing extra, for predicates AND
        aggregates)."""
        cols: List[str] = []
        for p in self.all_predicates():
            if p.column not in cols and p.column != "type_id":
                cols.append(p.column)
        for a in self.aggregates:
            if a.column and a.column not in cols and a.column != "type_id":
                cols.append(a.column)
        if self.group_by and self.group_by != "type_id" \
                and self.group_by not in cols:
            cols.append(self.group_by)
        return cols

    def signature(self) -> tuple:
        """Hashable program-cache key: everything that changes the compiled
        scan (values are traced, so they are NOT part of the key — except
        each value's integrality, which picks the comparison dtype)."""
        return (tuple((p.column, p.op, _is_integral(p.value))
                      for p in self.predicates),
                tuple(tuple((p.column, p.op, _is_integral(p.value))
                            for p in g) for g in self.or_groups),
                tuple((a.op, a.column) for a in self.aggregates),
                self.event_types is not None)


@dataclass(frozen=True)
class StateQuery:
    """Projection + filter over FOLDED aggregate state columns: the segment's
    chunks fold through the (mesh-aware) replay engine, then predicates run
    over the resulting state columns and ``select`` projects the output."""

    select: Optional[Tuple[str, ...]] = None
    predicates: Tuple[Predicate, ...] = ()
    limit: Optional[int] = None

    def as_json(self) -> dict:
        out: dict = {"predicates": [p.as_json() for p in self.predicates]}
        if self.select is not None:
            out["select"] = list(self.select)
        if self.limit is not None:
            out["limit"] = self.limit
        return out

    @classmethod
    def from_json(cls, d: Mapping[str, Any]) -> "StateQuery":
        return cls(
            select=(tuple(d["select"]) if d.get("select") is not None
                    else None),
            predicates=tuple(Predicate(p["column"], p["op"], p["value"])
                             for p in d.get("predicates", ())),
            limit=d.get("limit"))


@dataclass
class QueryResult:
    """Grouped scan output: per-aggregate columns in chunk order."""

    aggregate_ids: Optional[List[str]]
    columns: Dict[str, np.ndarray]
    num_aggregates: int
    scanned_events: int
    matched_events: int
    chunks: int
    elapsed_s: float = 0.0

    def rows(self, limit: Optional[int] = None) -> List[dict]:
        """Row-oriented view (the RPC payload shape): one dict per aggregate."""
        names = list(self.columns)
        ids = (self.aggregate_ids if self.aggregate_ids is not None
               else [str(i) for i in range(self.num_aggregates)])
        n = self.num_aggregates if limit is None else min(limit,
                                                          self.num_aggregates)
        cols = [self.columns[k][:n].tolist() for k in names]
        return [{"aggregate_id": ids[j],
                 **{k: cols[i][j] for i, k in enumerate(names)}}
                for j in range(n)]


def _pow2(n: int, lo: int) -> int:
    cap = lo
    while cap < n:
        cap *= 2
    return cap


def _is_integral(v) -> bool:
    """Whether a predicate value is exactly an integer (picks the compare
    dtype: fractional values against integer columns compare in f32 —
    truncating them to the column dtype would corrupt <=/>=/==/!=)."""
    try:
        return float(v).is_integer()
    except (TypeError, ValueError):
        return True


def _apply_op_np(col, op: str, value):
    if op == "==":
        return col == value
    if op == "!=":
        return col != value
    if op == "<":
        return col < value
    if op == "<=":
        return col <= value
    if op == ">":
        return col > value
    return col >= value


def _pred_mask_one_np(col: np.ndarray, p: Predicate) -> np.ndarray:
    if not _is_integral(p.value) and col.dtype.kind != "f":
        # mirror the device program: fractional vs integer compares in f32,
        # not by truncating the value to the column dtype
        return _apply_op_np(col.astype(np.float32), p.op, np.float32(p.value))
    return _apply_op_np(col, p.op, np.asarray(p.value, dtype=col.dtype))


def predicate_mask_np(cols: Mapping[str, np.ndarray], type_ids: np.ndarray,
                      predicates: Sequence[Predicate],
                      or_groups: Sequence[Sequence[Predicate]] = ()
                      ) -> np.ndarray:
    """Host mirror of the device predicate mask, over DEVICE-dtype columns
    (cast them first — ``QueryEngine._device_dtype``). Conjunctive
    ``predicates`` AND together; each ``or_groups`` entry ORs internally then
    ANDs with the rest. Shared by :func:`scan_reference` and the
    materialized-view oracle so every predicate consumer filters
    identically."""
    n = len(type_ids)
    mask = np.ones((n,), dtype=bool)
    for p in predicates:
        col = type_ids if p.column == "type_id" else cols[p.column]
        mask &= _pred_mask_one_np(col, p)
    for g in or_groups:
        hit = np.zeros((n,), dtype=bool)
        for p in g:
            col = type_ids if p.column == "type_id" else cols[p.column]
            hit |= _pred_mask_one_np(col, p)
        mask &= hit
    return mask


def _group_key_str(v, dt: np.dtype) -> str:
    """Stable string key for one group-by column value (views and changefeeds
    key rows by these across processes, so the format is part of the wire
    contract)."""
    if dt.kind in "iub":
        return str(int(v))
    return repr(float(v))


def _factorize_group(col: np.ndarray) -> Tuple[List[str], np.ndarray]:
    """Distinct values of a DEVICE-dtype group column → (string keys in
    ascending value order, int32 group index per event)."""
    vals, inv = np.unique(col, return_inverse=True)
    dt = np.dtype(col.dtype)
    return ([_group_key_str(v, dt) for v in vals],
            inv.astype(np.int32).reshape(-1))


def _sentinel(op: str, dt: np.dtype):
    """The identity element min/max partials carry until normalization."""
    if op == "min":
        return np.finfo(dt).max if dt.kind == "f" else np.iinfo(dt).max
    return np.finfo(dt).min if dt.kind == "f" else np.iinfo(dt).min


def _normalize_zero_match(out: Dict[str, np.ndarray], query: ScanQuery
                          ) -> Dict[str, np.ndarray]:
    """Zero-match aggregates report 0 everywhere: min/max sentinels flip to 0
    (the always-present ``count`` column is the tell; sum/count are already
    0). Runs ONCE, after any cross-chunk merge."""
    count = out["count"]
    for a in query.aggregates:
        if a.op in ("min", "max"):
            col = out[a.name]
            out[a.name] = np.where(count > 0, col, np.zeros((), col.dtype))
    return out


def _merge_scan_outputs(collected, query: ScanQuery, saw_ids: bool,
                        has_dup: bool, seen: Dict[str, int]):
    """Combine per-chunk RAW scan outputs into the final grouped columns.

    Disjoint chunks (the common case, detected while streaming) concatenate;
    chunks repeating an aggregate id — auto-extended segments append delta
    chunks continuing base-chunk aggregates — MERGE into one row per id
    (count/sum add, min/max combine over the sentinel-carrying partials).
    Returns ``(aggregate_ids | None, columns)`` post-normalization."""
    agg_specs = [(a.op, a.name) for a in query.aggregates if a.op != "count"]
    if not (saw_ids and has_dup):
        parts: Dict[str, List[np.ndarray]] = {}
        ids: List[str] = []
        for ids_c, out in collected:
            for name, col in out.items():
                parts.setdefault(name, []).append(col)
            if saw_ids:
                ids.extend(ids_c)
        columns = {name: (np.concatenate(arrs) if arrs
                          else np.zeros((0,), np.int32))
                   for name, arrs in parts.items()}
        if not columns:
            columns = {"count": np.zeros((0,), np.int32)}
        return (ids if saw_ids else None,
                _normalize_zero_match(columns, query))
    b = len(seen)
    columns = {"count": np.zeros((b,), np.int32)}
    for ids_c, out in collected:
        if not ids_c:
            continue
        idxs = np.fromiter((seen[a] for a in ids_c), dtype=np.int64,
                           count=len(ids_c))
        np.add.at(columns["count"], idxs, out["count"])
        for op, name in agg_specs:
            col = out[name]
            if name not in columns:
                init = (0 if op == "sum"
                        else _sentinel(op, np.dtype(col.dtype)))
                columns[name] = np.full((b,), init, dtype=col.dtype)
            if op == "sum":
                np.add.at(columns[name], idxs, col)
            elif op == "min":
                np.minimum.at(columns[name], idxs, col)
            else:
                np.maximum.at(columns[name], idxs, col)
    order = [None] * b
    for a, i in seen.items():
        order[i] = a
    return order, _normalize_zero_match(columns, query)


class QueryEngine:
    """Batched (optionally mesh-sharded) scan executor for one model family.

    One engine caches compiled scan programs per (query signature, shape
    bucket); chunks stream through :meth:`scan_chunks` /
    :meth:`scan_segment`. ``mesh`` shards the event axis; without one the
    same program runs single-device."""

    def __init__(self, spec, config: Config | None = None, mesh=None,
                 mesh_axis: Optional[str] = None) -> None:
        self.spec = spec
        self.registry = spec.registry
        self.config = config or default_config()
        self.mesh = mesh if self.config.get_bool("surge.query.mesh", True) \
            else None
        if mesh_axis is None:
            mesh_axis = (self.config.get_str("surge.replay.mesh-axes", "data")
                         .split(",")[0].strip() or "data")
        self.mesh_axis = mesh_axis
        # normalized to a power of two: the raw knob value seeds the bucket
        # ladder, and a non-pow2 floor would produce buckets no device count
        # divides (shard_map rejects the event-axis sharding outright)
        self._event_bucket = _pow2(max(
            self.config.get_int("surge.query.chunk-events", 65536), 1), 1024)
        self._programs: dict = {}
        self._col_dtypes = {f.name: np.dtype(f.dtype)
                            for f in self.registry.union_columns()}
        self._type_ids = {s.cls.__name__: s.type_id
                          for s in self.registry.event_schemas}
        self.stats = {"scans": 0, "chunks": 0, "scanned_events": 0,
                      "matched_events": 0}

    # -- helpers ------------------------------------------------------------------------

    def _n_dev(self) -> int:
        return 1 if self.mesh is None else int(np.prod(self.mesh.devices.shape))

    def resolve_type_ids(self, names: Sequence[str]) -> np.ndarray:
        try:
            return np.asarray(sorted(self._type_ids[n] for n in names),
                              dtype=np.int32)
        except KeyError as exc:
            raise ValueError(
                f"unknown event type {exc.args[0]!r} (registry has "
                f"{sorted(self._type_ids)})") from None

    def _device_dtype(self, dt: np.dtype):
        """The dtype a column actually reduces in on device: with
        jax_enable_x64 off (the default) 64-bit columns canonicalize to their
        32-bit kin — the host reference mirrors this exactly."""
        import jax

        if not jax.config.read("jax_enable_x64") and dt.itemsize == 8:
            return np.dtype(np.int32 if dt.kind in "iu" else np.float32)
        return dt

    def _materialize_columns(self, colev: ColumnarEvents,
                             needed: Sequence[str]) -> Dict[str, np.ndarray]:
        """The query's columns from a chunk, deriving declared-derived ones
        (an ``ordinal`` column is positional — synthesized from agg_idx, the
        exact inverse of ``columnar._drop_derived``'s verification)."""
        out: Dict[str, np.ndarray] = {}
        for name in needed:
            col = colev.cols.get(name)
            if col is not None:
                out[name] = col
                continue
            kind = colev.derived_cols.get(name)
            if kind != "ordinal":
                raise ValueError(
                    f"query references column {name!r} which the chunk "
                    f"neither stores nor derives (has "
                    f"{sorted(colev.cols) + sorted(colev.derived_cols)})")
            n = colev.num_events
            starts = np.zeros(colev.num_aggregates + 1, dtype=np.int64)
            np.cumsum(np.bincount(colev.agg_idx,
                                  minlength=colev.num_aggregates),
                      out=starts[1:])
            dt = self._col_dtypes.get(name, np.dtype(np.int32))
            out[name] = (np.arange(n, dtype=np.int64)
                         - starts[colev.agg_idx] + 1).astype(dt)
        return out

    # -- the device program -------------------------------------------------------------

    def _program(self, query: ScanQuery, n_bucket: int, b_bucket: int,
                 col_names: Tuple[str, ...]):
        key = (query.signature(), n_bucket, b_bucket, col_names)
        hit = self._programs.get(key)
        if hit is not None:
            return hit
        import jax
        import jax.numpy as jnp

        dev_dts = {n: self._device_dtype(self._col_dtypes.get(
            n, np.dtype(np.int32))) for n in col_names}
        preds = tuple((p.column, p.op, _is_integral(p.value))
                      for p in query.predicates)
        groups = tuple(tuple((p.column, p.op, _is_integral(p.value))
                             for p in g) for g in query.or_groups)
        aggs = tuple((a.op, a.column, a.name) for a in query.aggregates)
        has_types = query.event_types is not None

        def local_scan(agg_idx, type_ids, valid, pred_vals, type_allow, cols):
            def compare(cname, op, integral, j):
                # one predicate leg, indexed into the FLAT pred_vals vector
                # (conjunctive predicates first, then OR-group members)
                col = type_ids if cname == "type_id" else cols[cname]
                if not integral and not jnp.issubdtype(col.dtype,
                                                       jnp.floating):
                    # fractional value vs integer column: compare in f32
                    # (exact for |values| < 2^24) — truncating the value to
                    # the column dtype would corrupt <=/>=/==/!=
                    col = col.astype(jnp.float32)
                    v = pred_vals[j].astype(jnp.float32)
                else:
                    v = pred_vals[j].astype(col.dtype)
                if op == "==":
                    return col == v
                if op == "!=":
                    return col != v
                if op == "<":
                    return col < v
                if op == "<=":
                    return col <= v
                if op == ">":
                    return col > v
                return col >= v

            mask = valid
            if has_types:
                # few allowed ids: an OR of compares beats a gather-based
                # isin and fuses into the same elementwise pass
                hit_t = jnp.zeros_like(mask)
                for j in range(type_allow.shape[0]):
                    hit_t = hit_t | (type_ids == type_allow[j])
                mask = mask & hit_t
            j = 0
            for cname, op, integral in preds:
                mask = mask & compare(cname, op, integral, j)
                j += 1
            for g in groups:
                hit = None
                for cname, op, integral in g:
                    leg = compare(cname, op, integral, j)
                    hit = leg if hit is None else hit | leg
                    j += 1
                mask = mask & hit
            out: dict = {}
            out["count"] = jnp.zeros((b_bucket,), jnp.int32).at[agg_idx].add(
                mask.astype(jnp.int32))
            for op, cname, oname in aggs:
                if op == "count":
                    continue
                col = (type_ids if cname == "type_id" else cols[cname])
                dt = col.dtype
                if op == "sum":
                    out[oname] = jnp.zeros((b_bucket,), dt).at[agg_idx].add(
                        jnp.where(mask, col, jnp.zeros((), dt)))
                elif op == "min":
                    big = (jnp.array(jnp.finfo(dt).max, dt)
                           if jnp.issubdtype(dt, jnp.floating)
                           else jnp.array(jnp.iinfo(dt).max, dt))
                    out[oname] = jnp.full((b_bucket,), big, dt).at[
                        agg_idx].min(jnp.where(mask, col, big))
                else:
                    small = (jnp.array(jnp.finfo(dt).min, dt)
                             if jnp.issubdtype(dt, jnp.floating)
                             else jnp.array(jnp.iinfo(dt).min, dt))
                    out[oname] = jnp.full((b_bucket,), small, dt).at[
                        agg_idx].max(jnp.where(mask, col, small))
            return out

        if self.mesh is None or self._n_dev() <= 1:
            prog = jax.jit(lambda ai, ti, va, pv, ta, cs:
                           local_scan(ai, ti, va, pv, ta, cs))
        else:
            from jax.sharding import PartitionSpec as P

            from surge_tpu.replay.jax_compat import shard_map as _shard_map

            axis = self.mesh_axis
            pe = P(axis)  # event axis, sharded
            pr = P()      # replicated (predicate values, type filter, output)

            def sharded(agg_idx, type_ids, valid, pred_vals, type_allow, cols):
                part = local_scan(agg_idx, type_ids, valid, pred_vals,
                                  type_allow, cols)
                # ONE collective per output column: partial per-aggregate
                # reduces combine across the event shards
                out: dict = {}
                for name, v in part.items():
                    op = next((a[0] for a in aggs if a[2] == name), "count")
                    if op == "min":
                        out[name] = jax.lax.pmin(v, axis)
                    elif op == "max":
                        out[name] = jax.lax.pmax(v, axis)
                    else:  # count / sum
                        out[name] = jax.lax.psum(v, axis)
                return out

            mapped = _shard_map(
                sharded, mesh=self.mesh,
                in_specs=(pe, pe, pe, pr, pr, {n: pe for n in col_names}),
                out_specs={name: pr for name in
                           ["count"] + [a[2] for a in aggs
                                        if a[0] != "count"]},
                check_vma=False)
            prog = jax.jit(mapped)
        self._programs[key] = prog
        return prog

    # -- chunk / segment scans ----------------------------------------------------------

    def scan_chunk(self, colev: ColumnarEvents, query: ScanQuery
                   ) -> Dict[str, np.ndarray]:
        """Scan one chunk; returns ``{output: np[num_groups]}`` (always
        including ``count``). Zero-match groups report 0 everywhere."""
        return _normalize_zero_match(self._raw_scan(colev, query)[1], query)

    def _raw_scan(self, colev: ColumnarEvents, query: ScanQuery
                  ) -> Tuple[Optional[List[str]], Dict[str, np.ndarray]]:
        """The device scan of one chunk WITHOUT zero-match normalization:
        min/max keep their dtype sentinels, so per-chunk partials of a
        repeated group (delta chunks, per-refresh-round view folds) stay
        combinable. Returns ``(group keys, raw outputs)`` — keys are the
        chunk's aggregate ids, or under ``group_by`` the distinct group-column
        values of THIS chunk as stable strings."""
        import jax

        n = colev.num_events
        needed = tuple(query.columns_needed())
        cols_np = self._materialize_columns(colev, needed)
        if query.group_by is not None:
            gcol = (colev.type_ids if query.group_by == "type_id"
                    else cols_np[query.group_by])
            gcol = gcol.astype(self._device_dtype(np.dtype(gcol.dtype)))
            ids, grp_idx = _factorize_group(gcol)
            b = len(ids)
        else:
            ids, grp_idx = colev.aggregate_ids, colev.agg_idx
            b = colev.num_aggregates
        n_dev = self._n_dev()
        n_bucket = _pow2(max(n, 1), max(self._event_bucket, n_dev))
        b_bucket = _pow2(max(b, 1), 8)

        agg_p = np.zeros((n_bucket,), dtype=np.int32)
        agg_p[:n] = grp_idx
        type_p = np.full((n_bucket,), -1, dtype=np.int32)
        type_p[:n] = colev.type_ids
        valid = np.zeros((n_bucket,), dtype=bool)
        valid[:n] = True
        cols_p: Dict[str, np.ndarray] = {}
        for name in needed:
            dt = self._device_dtype(cols_np[name].dtype)
            cp = np.zeros((n_bucket,), dtype=dt)
            cp[:n] = cols_np[name].astype(dt)
            cols_p[name] = cp
        pred_vals = np.asarray([p.value for p in query.all_predicates()],
                               dtype=np.float64)
        type_allow = (self.resolve_type_ids(query.event_types)
                      if query.event_types is not None
                      else np.zeros((0,), dtype=np.int32))

        if self.mesh is not None and n_dev > 1:
            from jax.sharding import NamedSharding, PartitionSpec as P

            sh = NamedSharding(self.mesh, P(self.mesh_axis))
            rep = NamedSharding(self.mesh, P())
            put_e = lambda a: jax.device_put(a, sh)  # noqa: E731
            put_r = lambda a: jax.device_put(a, rep)  # noqa: E731
        else:
            put_e = put_r = lambda a: a  # noqa: E731
        prog = self._program(query, n_bucket, b_bucket, needed)
        out_dev = prog(put_e(agg_p), put_e(type_p), put_e(valid),
                       put_r(pred_vals), put_r(type_allow),
                       {k: put_e(v) for k, v in cols_p.items()})
        return ids, {k: np.asarray(v)[:b] for k, v in out_dev.items()}

    def scan_chunks(self, chunks: Iterable[ColumnarEvents], query: ScanQuery
                    ) -> QueryResult:
        """Scan a stream of chunks. Disjoint-aggregate chunks (the base
        columnar-segment layout) concatenate in chunk order; chunks REPEATING
        an aggregate id (auto-extended segments append delta chunks whose
        aggregates continue base chunks) MERGE into one row per id —
        count/sum add, min/max combine, zero-match normalization runs after
        the merge. Chunks without aggregate ids cannot be matched across
        chunks and keep the disjointness contract. Under ``group_by`` rows
        key by group value (the same value recurring across chunks merges
        exactly like a repeated aggregate id)."""
        t0 = time.perf_counter()
        collected: List[Tuple[Optional[List[str]], Dict[str, np.ndarray]]] = []
        saw_ids = True
        has_dup = False
        seen: Dict[str, int] = {}
        scanned = matched = n_chunks = 0
        for colev in chunks:
            ids_c, out = self._raw_scan(colev, query)
            collected.append((ids_c, out))
            scanned += colev.num_events
            matched += int(out["count"].sum())
            n_chunks += 1
            if ids_c is None:
                saw_ids = False
            elif saw_ids:
                for a in ids_c:
                    if a in seen:
                        has_dup = True
                    else:
                        seen[a] = len(seen)
        ids, columns = _merge_scan_outputs(collected, query, saw_ids,
                                           has_dup, seen)
        self.stats["scans"] += 1
        self.stats["chunks"] += n_chunks
        self.stats["scanned_events"] += scanned
        self.stats["matched_events"] += matched
        return QueryResult(
            aggregate_ids=ids, columns=columns,
            num_aggregates=len(next(iter(columns.values()))),
            scanned_events=scanned, matched_events=matched, chunks=n_chunks,
            elapsed_s=time.perf_counter() - t0)

    def scan_segment(self, path: str, query: ScanQuery,
                     partitions: Optional[set] = None) -> QueryResult:
        """Scan a committed columnar segment file. Only the columns the query
        touches are decompressed (projection pushdown into the reader)."""
        from surge_tpu.log.columnar import read_segment

        return self.scan_chunks(
            read_segment(path, partitions=partitions,
                         columns=query.columns_needed()),
            query)

    # -- state queries (fold + filter + project) ----------------------------------------

    def query_states(self, chunks: Iterable[ColumnarEvents],
                     query: StateQuery, replay_engine) -> QueryResult:
        """Fold the chunks' events to per-aggregate STATE through the
        (mesh-aware) replay engine, then filter on state columns and project
        ``select`` — the "current state of every matching aggregate" read.

        Chunks REPEATING an aggregate id (auto-extended segments append delta
        chunks continuing base chunks) fold as CONTINUATIONS: the repeated
        rows' carries and already-folded event counts seed the delta fold,
        and the final row is the complete state — one row per id, same as
        the segment restore. (Snapshot-only aggregates — state publishes with
        no events at all — live in snapshot sections the tensor fold cannot
        see; they are a restore concern, not a state-query one.)"""
        t0 = time.perf_counter()
        chunk_list = list(chunks)
        state_names = [f.name for f in self.spec.registry.state.fields]
        dtypes = {f.name: np.dtype(f.dtype)
                  for f in self.spec.registry.state.fields}
        if any(c.aggregate_ids is None for c in chunk_list):
            # id-less chunks cannot be matched across chunks: keep the
            # disjoint-aggregate contract verbatim
            res = replay_engine.replay_columnar_chunks(chunk_list)
            states, ids_order = res.states, res.aggregate_ids
            num_events = res.num_events
        else:
            init_tree = self.spec.init_state_tree()
            index: Dict[str, int] = {}
            ids_order = []
            states = {n: np.zeros((0,), dtype=dtypes[n])
                      for n in state_names}
            folded = np.zeros((0,), dtype=np.int32)  # events per id so far
            num_events = 0
            for colev in chunk_list:
                b_c = colev.num_aggregates
                ids_c = colev.aggregate_ids
                rep = [(j, index[a]) for j, a in enumerate(ids_c)
                       if a in index]
                init_carry = None
                ord_base = None
                if rep:
                    # continuation: repeated rows resume from their folded
                    # carry + event count (delta chunks store positional
                    # columns explicitly, but a derived declaration still
                    # continues correctly through ordinal_base)
                    init_carry = {n: np.full((b_c,), init_tree[n],
                                             dtype=dtypes[n])
                                  for n in state_names}
                    ord_base = np.zeros((b_c,), dtype=np.int32)
                    js = np.asarray([j for j, _ in rep], dtype=np.int64)
                    ks = np.asarray([k for _, k in rep], dtype=np.int64)
                    for n in state_names:
                        init_carry[n][js] = states[n][ks]
                    ord_base[js] = folded[ks]
                res = replay_engine.replay_columnar(
                    colev, init_carry=init_carry, ordinal_base=ord_base)
                counts_c = np.bincount(colev.agg_idx,
                                       minlength=b_c).astype(np.int32)
                num_events += res.num_events
                new = [j for j, a in enumerate(ids_c) if a not in index]
                if rep:
                    for n in state_names:
                        states[n][ks] = res.states[n][js]
                    folded[ks] += counts_c[js]
                if new:
                    nj = np.asarray(new, dtype=np.int64)
                    for n in state_names:
                        states[n] = np.concatenate(
                            [states[n], res.states[n][nj]])
                    folded = np.concatenate([folded, counts_c[nj]])
                    for j in new:
                        index[ids_c[j]] = len(ids_order)
                        ids_order.append(ids_c[j])
        n_rows = len(next(iter(states.values()))) if states else 0
        mask = np.ones((n_rows,), dtype=bool)
        for p in query.predicates:
            if p.column not in states:
                raise ValueError(
                    f"state query references unknown state column "
                    f"{p.column!r} (has {state_names})")
            mask &= _apply_op_np(states[p.column], p.op, p.value)
        select = list(query.select) if query.select is not None else state_names
        for name in select:
            if name not in states:
                raise ValueError(f"unknown state column {name!r} in select "
                                 f"(has {state_names})")
        idx = np.nonzero(mask)[0]
        if query.limit is not None:
            idx = idx[: query.limit]
        columns = {name: states[name][idx] for name in select}
        ids = ([ids_order[i] for i in idx]
               if ids_order is not None else None)
        self.stats["scans"] += 1
        self.stats["scanned_events"] += num_events
        return QueryResult(
            aggregate_ids=ids, columns=columns, num_aggregates=len(idx),
            scanned_events=num_events, matched_events=len(idx),
            chunks=len(chunk_list), elapsed_s=time.perf_counter() - t0)

    def query_states_segment(self, path: str, query: StateQuery,
                             replay_engine,
                             partitions: Optional[set] = None) -> QueryResult:
        from surge_tpu.log.columnar import read_segment

        return self.query_states(read_segment(path, partitions=partitions),
                                 query, replay_engine)


# -- numpy host references (the golden the device scans must equal) ------------------


def scan_reference(chunks: Iterable[ColumnarEvents], query: ScanQuery,
                   registry) -> QueryResult:
    """Pure-numpy oracle for :meth:`QueryEngine.scan_chunks` — identical
    dtype discipline (device-canonicalized reduce dtypes), identical
    zero-match normalization. The query-engine tests hold device == this."""
    import jax

    def dev_dt(dt: np.dtype) -> np.dtype:
        if not jax.config.read("jax_enable_x64") and dt.itemsize == 8:
            return np.dtype(np.int32 if dt.kind in "iu" else np.float32)
        return dt

    type_ids_of = {s.cls.__name__: s.type_id for s in registry.event_schemas}
    union_dts = {f.name: np.dtype(f.dtype) for f in registry.union_columns()}
    collected: List[Tuple[Optional[List[str]], Dict[str, np.ndarray]]] = []
    saw_ids = True
    has_dup = False
    seen: Dict[str, int] = {}
    total_b = scanned = matched = n_chunks = 0
    for colev in chunks:
        n = colev.num_events
        cols: Dict[str, np.ndarray] = {}
        for name in query.columns_needed():
            col = colev.cols.get(name)
            if col is None and colev.derived_cols.get(name) == "ordinal":
                starts = np.zeros(colev.num_aggregates + 1, dtype=np.int64)
                np.cumsum(np.bincount(colev.agg_idx,
                                      minlength=colev.num_aggregates),
                          out=starts[1:])
                col = (np.arange(n, dtype=np.int64)
                       - starts[colev.agg_idx] + 1).astype(
                    union_dts.get(name, np.dtype(np.int32)))
            cols[name] = col.astype(dev_dt(col.dtype))
        if query.group_by is not None:
            gcol = (colev.type_ids if query.group_by == "type_id"
                    else cols[query.group_by])
            ids_c, grp_idx = _factorize_group(gcol)
            b = len(ids_c)
        else:
            ids_c, grp_idx = colev.aggregate_ids, colev.agg_idx
            b = colev.num_aggregates
        mask = np.ones((n,), dtype=bool)
        if query.event_types is not None:
            allow = {type_ids_of[t] for t in query.event_types}
            mask &= np.isin(colev.type_ids, sorted(allow))
        mask &= predicate_mask_np(cols, colev.type_ids, query.predicates,
                                  query.or_groups)
        count = np.zeros((b,), dtype=np.int32)
        np.add.at(count, grp_idx, mask.astype(np.int32))
        out: Dict[str, np.ndarray] = {"count": count}
        for a in query.aggregates:
            if a.op == "count":
                continue
            col = (colev.type_ids.astype(np.int32) if a.column == "type_id"
                   else cols[a.column])
            dt = col.dtype
            if a.op == "sum":
                acc = np.zeros((b,), dtype=dt)
                np.add.at(acc, grp_idx, np.where(mask, col,
                                                 np.zeros((), dt)))
            elif a.op == "min":
                big = _sentinel("min", dt)
                acc = np.full((b,), big, dtype=dt)
                np.minimum.at(acc, grp_idx,
                              np.where(mask, col, np.asarray(big, dt)))
            else:
                small = _sentinel("max", dt)
                acc = np.full((b,), small, dtype=dt)
                np.maximum.at(acc, grp_idx,
                              np.where(mask, col, np.asarray(small, dt)))
            out[a.name] = acc  # raw: sentinels normalize after the merge
        collected.append((ids_c, out))
        total_b += b
        scanned += n
        matched += int(count.sum())
        n_chunks += 1
        if ids_c is None:
            saw_ids = False
        elif saw_ids:
            for a_id in ids_c:
                if a_id in seen:
                    has_dup = True
                else:
                    seen[a_id] = len(seen)
    ids, columns = _merge_scan_outputs(collected, query, saw_ids, has_dup,
                                       seen)
    return QueryResult(aggregate_ids=ids, columns=columns,
                       num_aggregates=len(next(iter(columns.values()))),
                       scanned_events=scanned, matched_events=matched,
                       chunks=n_chunks)


def state_query_reference(states: Mapping[str, np.ndarray],
                          aggregate_ids: Optional[Sequence[str]],
                          query: StateQuery) -> QueryResult:
    """Numpy oracle for :meth:`QueryEngine.query_states`, given already-folded
    state columns (fold them with the scalar model in tests)."""
    n = len(next(iter(states.values()))) if states else 0
    mask = np.ones((n,), dtype=bool)
    for p in query.predicates:
        mask &= _apply_op_np(states[p.column], p.op, p.value)
    idx = np.nonzero(mask)[0]
    if query.limit is not None:
        idx = idx[: query.limit]
    select = list(query.select) if query.select is not None else list(states)
    return QueryResult(
        aggregate_ids=([aggregate_ids[i] for i in idx]
                       if aggregate_ids is not None else None),
        columns={name: np.asarray(states[name])[idx] for name in select},
        num_aggregates=len(idx), scanned_events=0, matched_events=len(idx),
        chunks=1)
