"""Mesh-sharded resident replay: the single-sync tile design across devices.

Entity parallelism (SURVEY.md §2.10 row 1) for the resident path: lanes are
dealt round-robin across the mesh axis (descending length order, so every
device draws the same length distribution and finishes together), each device
holds its shard of the flat wire corpus, and one ``shard_map``-wrapped
dispatch runs the per-device tile loop — no collectives anywhere, because
aggregate folds are independent. Per-device tile counts ride in as data, so
devices with slightly different work loop independently inside the same SPMD
program. The whole replay still crosses the host⇄device boundary exactly
twice per granularity (dispatch in, states out).
"""

from __future__ import annotations

from typing import Any, Mapping, Optional

import numpy as np

from surge_tpu.codec.wire import WireFormat
from surge_tpu.replay.engine import (
    ReplayResult,
    ResidentWire,
    _apply_perm,
    _bucket_len,
    _make_tile,
    _round_up,
    _unapply_perm,
)


def _deal(b: int, n_dev: int) -> list[np.ndarray]:
    """Round-robin lane deal: device d gets sorted-rank lanes d, d+D, d+2D…"""
    return [np.arange(d, b, n_dev, dtype=np.int64) for d in range(n_dev)]


class ShardedResident:
    """Device-resident sharded corpus + plan, ready for :func:`replay`."""

    def __init__(self, engine, wire: ResidentWire) -> None:
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        if engine.mesh is None:
            raise ValueError("ShardedResident requires a mesh-backed engine")
        engine.check_wire(wire)  # layout/guard safety, same as upload_resident
        self.engine = engine
        self.wire_host = wire
        mesh = engine.mesh
        axis = engine.mesh_axis
        n_dev = int(np.prod(mesh.devices.shape))
        self.n_dev = n_dev
        b = wire.lengths.shape[0]
        self.b = b
        self.num_events = wire.num_events

        # --- partition lanes (sorted desc) round-robin across devices -------
        deals = _deal(max(b, 1), n_dev) if b else [np.zeros(0, np.int64)
                                                  for _ in range(n_dev)]
        self.deals = deals
        b_local_max = max((len(d) for d in deals), default=0)
        bs = min(engine.batch_size, _round_up(max(b_local_max, 1),
                                              engine._lane_multiple()))
        self.bs = bs
        b_pad = _round_up(max(b_local_max, 1), bs)
        self.b_pad = b_pad
        width = engine.resident_tile_width()
        self.width = width

        # --- per-device flat corpora (contiguous lane spans, re-packed) -----
        guard = wire.guard
        n_locals = [int(wire.lengths[d].sum()) for d in deals]
        n_rows = _bucket_len(max(n_locals, default=0) + guard)
        nbytes = wire.packed.shape[1]
        flat = np.zeros((n_dev, n_rows, nbytes), dtype=np.uint8)
        side = {k: np.zeros((n_dev, n_rows), dtype=v.dtype)
                for k, v in wire.side.items()}
        starts_l = np.zeros((n_dev, b_pad), dtype=np.int32)
        lens_l = np.zeros((n_dev, b_pad), dtype=np.int32)
        for d, lanes in enumerate(deals):
            pos = 0
            for j, lane in enumerate(lanes):
                ln = int(wire.lengths[lane])
                s0 = int(wire.starts[lane])
                flat[d, pos: pos + ln] = wire.packed[s0: s0 + ln]
                for k, col in side.items():
                    col[d, pos: pos + ln] = wire.side[k][s0: s0 + ln]
                starts_l[d, j] = pos
                lens_l[d, j] = ln
                pos += ln

        # --- per-device tile plans (shared shapes, data-driven trip count) --
        # Plans see the FULL padded [b_pad] length row (zero tails are still
        # descending and schedule no rounds), so every device derives the same
        # bs and the shared compiled program's static shapes hold everywhere.
        from surge_tpu.replay.engine import ResidentPlan

        plan_fn = type(engine)._resident_plan  # unbound: sees the view's bs
        plans: list[ResidentPlan] = []
        for d in range(n_dev):
            fake = _FakeResident(lens_l[d])
            plans.append(plan_fn(_PlanView(engine, bs), fake))
        self.plans = plans
        assert all(p.bs_big == bs for p in plans)
        self.bs_small = plans[0].bs_small if plans else bs
        assert all(p.bs_small == self.bs_small for p in plans)
        self.k_caps = {}
        for kind in ("big", "small"):
            k_max = max((len(getattr(p, f"{kind}_i0")) for p in plans),
                        default=0)
            self.k_caps[kind] = engine._plan_cap(k_max) if k_max else 0
        self.padded_slots = sum(p.padded_slots for p in plans)

        # --- upload, sharded ------------------------------------------------
        shard = NamedSharding(mesh, P(axis, *([None] * 2)))
        shard2 = NamedSharding(mesh, P(axis, None))
        self.flat_dev = jax.device_put(flat, shard)
        self.side_dev = {k: jax.device_put(v, shard2) for k, v in side.items()}
        self.starts_dev = jax.device_put(starts_l, shard2)
        self.lens_dev = jax.device_put(lens_l, shard2)
        self.wire_bytes = flat.nbytes + sum(v.nbytes for v in side.values())

    def worklists(self, kind: str) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
        """Stacked per-device (i0s [D,k_cap], t_bases [D,k_cap], k_n [D])."""
        k_cap = self.k_caps[kind]
        i0s = np.zeros((self.n_dev, k_cap), dtype=np.int32)
        tbs = np.zeros((self.n_dev, k_cap), dtype=np.int32)
        kn = np.zeros((self.n_dev,), dtype=np.int32)
        for d, p in enumerate(self.plans):
            a = getattr(p, f"{kind}_i0")
            t = getattr(p, f"{kind}_tb")
            i0s[d, : len(a)] = a
            tbs[d, : len(t)] = t
            kn[d] = len(a)
        return i0s, tbs, kn


class _FakeResident:
    """Minimal duck-type for engine._resident_plan (lengths only)."""

    def __init__(self, lengths: np.ndarray) -> None:
        self.lengths = np.asarray(lengths, dtype=np.int32)


class _PlanView:
    """Engine facade pinning the plan's batch size to the sharded local bs."""

    def __init__(self, engine, bs: int) -> None:
        self._engine = engine
        self.batch_size = bs

    def __getattr__(self, name):
        return getattr(self._engine, name)


def _sharded_program(engine, key: frozenset, width: int, bs: int, k_cap: int):
    """jit(shard_map(tile loop)) over the device axis; cached on the engine."""
    cache_key = ("sharded", key, width, bs, k_cap)
    hit = engine._resident_folds.get(cache_key)
    if hit is not None:
        return hit
    import jax
    from jax.sharding import PartitionSpec as P

    wire = WireFormat(engine.spec.registry, dict(key))
    tile = _make_tile(engine.spec, wire, width, bs, engine._unroll,
                      engine._dispatch, engine.tile_backend)

    def local_fold(slab_state, flat_wire, side_flat, starts_all, lens_all,
                   ord_all, i0s, t_bases, k_n):
        # local blocks arrive with the device axis (size 1) still on; drop it
        slab0 = {k: v[0] for k, v in slab_state.items()}
        fw0 = flat_wire[0]
        sf0 = {k: v[0] for k, v in side_flat.items()}

        def body(k, st):
            return tile(st, fw0, sf0, starts_all[0], lens_all[0], ord_all[0],
                        i0s[0, k], t_bases[0, k])

        out = jax.lax.fori_loop(0, k_n[0], body, slab0)
        return {k: v[None] for k, v in out.items()}

    axis = engine.mesh_axis
    p2 = P(axis, None)
    p3 = P(axis, None, None)
    from surge_tpu.replay.jax_compat import shard_map as _shard_map

    mapped = _shard_map(
        local_fold, mesh=engine.mesh,
        in_specs=({k: p2 for k in
                   (f.name for f in engine.spec.registry.state.fields)},
                  p3, {k: p2 for k in sorted(
                      f.name for f in wire.side_fields)}, p2, p2, p2, p2, p2,
                  P(axis)),
        out_specs={k: p2 for k in
                   (f.name for f in engine.spec.registry.state.fields)},
        # handlers may return literal columns (e.g. created=True) whose
        # varying-manual-axes type differs per switch branch; everything here
        # is per-device-local anyway (no collectives), so skip the VMA check
        check_vma=False)
    donate = (0,) if engine.donate_carry else ()
    jitted = jax.jit(mapped, donate_argnums=donate)
    engine._resident_folds[cache_key] = jitted
    return jitted


def fold_resident_sharded(engine, sharded: ShardedResident,
                          init_carry: Mapping[str, Any] | None = None,
                          ordinal_base: Optional[np.ndarray] = None):
    """Fold a :class:`ShardedResident` and return the DEVICE slab —
    ``{field: [n_dev, b_pad] sharded array}`` — without the host pull.

    Row ``[d, j]`` holds sorted-rank lane ``sharded.deals[d][j]`` (rows past
    each deal's length are padding). The mesh half of
    :meth:`ReplayEngine.fold_resident_slab`, used by the resident state plane
    to keep a cold-start replay's states on device; ``replay_resident_sharded``
    is this plus one pull + reassembly."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    w = sharded.wire_host
    b = sharded.b
    state_fields = engine.spec.registry.state.fields
    perm = w.perm
    n_dev, b_pad = sharded.n_dev, sharded.b_pad
    key = frozenset(w.derived_key.items())

    ord_l = np.zeros((n_dev, b_pad), dtype=np.int32)
    slab = {f.name: np.zeros((n_dev, b_pad), dtype=f.dtype)
            for f in state_fields}
    init_tree = engine.spec.init_state_tree()
    for name, col in slab.items():
        col[:] = init_tree[name]
    init_sorted, src_ord = _apply_perm(perm, init_carry, ordinal_base)
    for d, lanes in enumerate(sharded.deals):
        if src_ord is not None:
            ord_l[d, : len(lanes)] = src_ord[lanes].astype(np.int32)
        if init_sorted is not None:
            for k, full in init_sorted.items():
                slab[k][d, : len(lanes)] = full[lanes]

    shard2 = NamedSharding(engine.mesh, P(engine.mesh_axis, None))
    shard1 = NamedSharding(engine.mesh, P(engine.mesh_axis))
    slab_dev = {k: jax.device_put(v, shard2) for k, v in slab.items()}
    ord_dev = jax.device_put(ord_l, shard2)

    for kind in ("big", "small"):
        k_cap = sharded.k_caps[kind]
        if k_cap == 0:
            continue
        # each granularity runs its OWN program: small tiles sliced bs-wide
        # would overlap/clamp and re-fold the same lanes' windows
        bs_kind = sharded.bs if kind == "big" else sharded.bs_small
        i0s, tbs, kn = sharded.worklists(kind)
        fold = _sharded_program(engine, key, sharded.width, bs_kind, k_cap)
        engine._signatures.add(("resident-sharded", key, sharded.width,
                               bs_kind, k_cap, b_pad,
                               int(sharded.flat_dev.shape[1])))
        engine.stats["windows"] += int(kn.sum())
        slab_dev = fold(slab_dev, sharded.flat_dev, sharded.side_dev,
                        sharded.starts_dev, sharded.lens_dev, ord_dev,
                        jax.device_put(i0s, shard2),
                        jax.device_put(tbs, shard2),
                        jax.device_put(kn, shard1))
    return slab_dev


def replay_resident_sharded(engine, sharded: ShardedResident,
                            init_carry: Mapping[str, Any] | None = None,
                            ordinal_base: Optional[np.ndarray] = None
                            ) -> ReplayResult:
    """Fold a :class:`ShardedResident` across the engine's mesh. Results come
    back in the ORIGINAL aggregate order of the packed corpus."""
    b = sharded.b
    state_fields = engine.spec.registry.state.fields
    if b == 0:
        return ReplayResult(states={f.name: np.zeros((0,), dtype=f.dtype)
                                    for f in state_fields},
                            num_aggregates=0, num_events=0, padded_events=0)
    perm = sharded.wire_host.perm
    slab_dev = fold_resident_sharded(engine, sharded, init_carry=init_carry,
                                     ordinal_base=ordinal_base)
    # single pull; reassemble original order through deal + perm
    out_sorted = {name: np.empty((b,), dtype=f.dtype)
                  for name, f in ((f.name, f) for f in state_fields)}
    host = {name: np.asarray(v) for name, v in slab_dev.items()}
    for d, lanes in enumerate(sharded.deals):
        for name in out_sorted:
            out_sorted[name][lanes] = host[name][d, : len(lanes)]
    return ReplayResult(states=_unapply_perm(perm, out_sorted),
                        num_aggregates=b,
                        num_events=sharded.num_events,
                        padded_events=sharded.padded_slots)
